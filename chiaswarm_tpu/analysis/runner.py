"""Shared driver behind the CLI and the tier-1 ``tests/test_lint.py`` gate.

Runs the per-file rules (R1-R8) over every linted file, then builds the
swarmflow :class:`~.project.ProjectIndex` over the same file set (warm
runs reuse the content-hash cache) and runs the interprocedural rules
(R9/R10) once against it. ``--changed-only`` narrows the per-file pass to
files changed vs the merge base plus their reverse-dependency closure
from the import graph — the pre-commit fast path.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
from typing import Callable

from chiaswarm_tpu.analysis import baseline as baseline_mod
from chiaswarm_tpu.analysis.core import (
    Finding, ProjectRule, all_rules, analyze_paths, get_rule,
    iter_python_files,
)
from chiaswarm_tpu.analysis.project import DEFAULT_CACHE_NAME, ProjectIndex


#: the repo surfaces the lint gate covers — single source of truth for
#: the CLI default paths, tests/test_lint.py, and the CI job
DEFAULT_LINT_PATHS = ("chiaswarm_tpu", "tests", "tools",
                      "bench.py", "__graft_entry__.py")


@dataclasses.dataclass
class RunResult:
    exit_code: int
    new: list[Finding]
    suppressed: list[Finding]
    stale: list[str]
    errors: list[str]
    report: str
    checked_files: int = 0
    total_files: int = 0


def repo_root() -> str:
    """The directory findings are reported relative to (and where the
    default baseline lives): the repo checkout containing this package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _scope_checker(paths: list[str], root: str,
                   rules) -> Callable[[str], bool]:
    """Predicate: did THIS run (its paths + selected rules) re-check the
    file/rule a baseline key refers to? Out-of-scope entries are neither
    stale nor erasable."""
    rule_names = {r.name for r in rules}
    prefixes: list[str] = []
    exact: set[str] = set()
    for p in paths:
        rel = os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")
        if rel == ".":
            prefixes.append("")  # whole repo
        elif os.path.isdir(p):
            prefixes.append(rel.rstrip("/") + "/")
        else:
            exact.add(rel)

    def in_scope(key: str) -> bool:
        rule, path, _, _ = key.split("::", 3)
        return rule in rule_names and (
            path in exact or any(path.startswith(px) for px in prefixes))

    return in_scope


def _git_changed_files(root: str) -> set[str] | None:
    """Root-relative posix paths of .py files changed vs the merge base
    with origin/main (falling back to origin/master, then local main,
    then plain HEAD = uncommitted work only), plus untracked files.
    None when git itself is unusable here."""
    def git(*args: str):
        try:
            return subprocess.run(["git", "-C", root, *args],
                                  capture_output=True, text=True,
                                  timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
    base = None
    for ref in ("origin/main", "origin/master", "main"):
        p = git("merge-base", "HEAD", ref)
        if p is not None and p.returncode == 0:
            base = p.stdout.strip()
            break
    # --relative: paths come back relative to ``root`` (the -C dir), not
    # the git toplevel — they must intersect the lint surface even when
    # this package sits below the top of a larger checkout
    p = git("diff", "--name-only", "--relative", base or "HEAD")
    if p is None or p.returncode != 0:
        return None
    changed = {ln.strip() for ln in p.stdout.splitlines() if ln.strip()}
    p = git("ls-files", "--others", "--exclude-standard")
    if p is not None and p.returncode == 0:
        changed |= {ln.strip() for ln in p.stdout.splitlines()
                    if ln.strip()}
    return {c.replace(os.sep, "/") for c in changed if c.endswith(".py")}


def run(paths: list[str],
        *,
        baseline_path: str | None = None,
        strict: bool = False,
        select: list[str] | None = None,
        write_baseline: bool = False,
        root: str | None = None,
        changed_only: bool = False,
        cache: bool = True) -> RunResult:
    """Lint ``paths``; returns exit code 0 when clean.

    - new (non-baselined) findings -> exit 1
    - stale baseline entries -> exit 1 under ``strict``, warning otherwise
    - unparseable files / bad input -> exit 2
    """
    root = root or repo_root()
    if baseline_path is None:
        baseline_path = os.path.join(
            root, baseline_mod.DEFAULT_BASELINE_NAME)
    try:
        rules = [get_rule(s) for s in select] if select else all_rules()
    except KeyError as exc:
        # typo'd --select is bad input (exit 2), not lint findings
        return RunResult(2, [], [], [], [str(exc)],
                         f"swarmlint: {exc.args[0]}")
    file_rules = [r for r in rules if not isinstance(r, ProjectRule)]
    project_rules = [r for r in rules if isinstance(r, ProjectRule)]

    errors: list[str] = []
    error_paths: set[str] = set()

    def record_error(rel: str, exc: Exception) -> None:
        errors.append(f"{rel}: {exc}")
        error_paths.add(rel)

    # one enumeration of the lint surface; the project index and the
    # changed-only closure both work off it. The index is only built
    # when something consumes it — a --select R1 subset run must stay as
    # cheap as it was before the whole-program layer existed
    files = list(iter_python_files([os.path.abspath(p) for p in paths
                                    if os.path.exists(p)], root=root))
    index = None
    if project_rules or changed_only:
        index = ProjectIndex.build(
            files, cache_path=(os.path.join(root, DEFAULT_CACHE_NAME)
                               if cache else None))

    only_files: set[str] | None = None
    allowed_rel: set[str] | None = None
    note = ""
    if changed_only:
        changed = _git_changed_files(root)
        if changed is None:
            return RunResult(
                2, [], [], [], ["--changed-only requires a usable git "
                                "checkout"],
                "swarmlint: --changed-only requires a usable git checkout")
        in_surface = {rel for _, rel in files}
        # the closure walks the import graph (which only knows parseable
        # files) — union the raw changed set back in so a changed file
        # with a syntax error is still OPENED and fails the run loudly
        allowed_rel = (index.reverse_closure(changed & in_surface)
                       | (changed & in_surface))
        only_files = {ap for ap, rel in files if rel in allowed_rel}
        note = (f"changed-only: linting {len(only_files)} of "
                f"{len(files)} files ({len(changed & in_surface)} changed "
                f"+ reverse-dependency closure)")

    findings = analyze_paths(paths, file_rules, root=root,
                             on_error=record_error, only_files=only_files)
    for rule in project_rules:
        for f in rule.check_project(index):
            if f.path in error_paths:
                continue
            if allowed_rel is not None and f.path not in allowed_rel \
                    and not any(hop[0] in allowed_rel for hop in f.chain):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    scope_paths = sorted(only_files) if only_files is not None else paths
    scope = _scope_checker(scope_paths, root, rules)

    def in_scope(key: str) -> bool:
        # a file that failed to parse was NOT re-checked: its baseline
        # entries are neither stale nor safe to drop on a rewrite
        return scope(key) and key.split("::", 3)[1] not in error_paths

    if write_baseline:
        if select:
            return RunResult(
                2, [], [], [], ["--write-baseline with --select would "
                                "erase other rules' entries"],
                "swarmlint: refusing --write-baseline with --select — a "
                "partial rule run cannot regenerate the full baseline")
        if changed_only:
            return RunResult(
                2, [], [], [], ["--write-baseline with --changed-only "
                                "would regenerate from a partial run"],
                "swarmlint: refusing --write-baseline with --changed-only "
                "— a partial file run cannot regenerate the full baseline")
        if errors:
            # refuse to write a silently incomplete baseline
            report = "\n".join(
                [f"error: {e}" for e in errors]
                + ["swarmlint: baseline NOT written — fix unparseable "
                   "files first"])
            return RunResult(2, [], [], [], errors, report)
        # preserve entries this run never re-checked (out-of-scope paths)
        try:
            existing = baseline_mod.load_baseline(baseline_path).entries
        except Exception as exc:
            return RunResult(
                2, [], [], [], [f"{baseline_path}: {exc}"],
                f"swarmlint: cannot read existing baseline "
                f"{baseline_path}: {exc}")
        keep = {k: n for k, n in existing.items() if not in_scope(k)}
        n = baseline_mod.write_baseline(baseline_path, findings, keep)
        report = (f"wrote {n} baseline entr{'y' if n == 1 else 'ies'} "
                  f"({len(findings)} findings, {len(keep)} out-of-scope "
                  f"kept) to {baseline_path}")
        return RunResult(0, [], findings, [], errors, report)

    try:
        bl = baseline_mod.load_baseline(baseline_path)
    except Exception as exc:
        # truncated / merge-conflicted / wrong-schema baseline: bad
        # input (exit 2), not a lint failure
        return RunResult(
            2, [], [], [], [f"{baseline_path}: {exc}"],
            f"swarmlint: unreadable baseline {baseline_path}: {exc}")
    new, suppressed, stale = bl.split(findings, in_scope=in_scope)

    lines: list[str] = ([note] if note else []) + [f.render() for f in new]
    for key in stale:
        lines.append(
            f"stale baseline entry (finding no longer present — delete it "
            f"from {os.path.basename(baseline_path)}): {key}")
    for e in errors:
        lines.append(f"error: {e}")
    lines.append(
        f"swarmlint: {len(new)} finding{'s' if len(new) != 1 else ''}, "
        f"{len(suppressed)} baselined, {len(stale)} stale baseline "
        f"entr{'y' if len(stale) == 1 else 'ies'}")

    exit_code = 0
    if errors:
        exit_code = 2
    elif new or (strict and stale):
        exit_code = 1
    return RunResult(exit_code, new, suppressed, stale, errors,
                     "\n".join(lines),
                     checked_files=(len(only_files)
                                    if only_files is not None
                                    else len(files)),
                     total_files=len(files))
