"""R18–R21 — the keyflow executable-identity rules (swarmkey).

R1–R13 prove what the *values* do, R14–R17 what the *threads* do; these
four prove what the *cache key* knows, via the trace-input provenance
interpreter in ``analysis/keyflow.py`` (see its module docstring for the
domain):

- **R18 unkeyed-trace-input** — a trace-affecting env knob (read at
  trace time, or frozen into a module constant that a traced function
  loads) that is never folded into the executable-cache key: a knob flip
  silently serves a stale executable from a warm slot.
- **R19 frozen-env-reread** — an env read lexically inside a build/
  traced scope, written as if live-per-call but executed at most once
  per cache slot.
- **R20 unstable-key-component** — ``id()``/``hash()``/``repr()`` in
  the PERSISTENT key surface (``cache_fingerprint``/
  ``artifact_cache_key``); in-process ``static_cache_key`` owners may
  keep ``id(self.c)``.
- **R21 cache-tag-collision** — two distinct build callables sharing an
  (owner, tag, statics-vocabulary) triple: one slot, two programs.

All four are conservative: dynamic env names, unresolvable references
and non-canonical owners are silent — a lint must not invent a cache-key
bug it cannot defend with a chain.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator

from chiaswarm_tpu.analysis.core import Finding, ProjectRule, register

if TYPE_CHECKING:  # the index arrives at check time; no runtime dep
    from chiaswarm_tpu.analysis.project import ProjectIndex


class _KeyflowRule(ProjectRule):
    def check_project(self, index: "ProjectIndex") -> Iterator[Finding]:
        from chiaswarm_tpu.analysis import keyflow

        for f in keyflow.results(index).findings:
            if f.rule == self.name:
                yield f


@register
class UnkeyedTraceInput(_KeyflowRule):
    code = "R18"
    name = "unkeyed-trace-input"
    description = ("a trace-affecting env knob never reaches the "
                   "executable-cache key — a warm slot serves the stale "
                   "program after a knob flip; fold it into "
                   "static_cache_key only-when-set")


@register
class FrozenEnvReread(_KeyflowRule):
    code = "R19"
    name = "frozen-env-reread"
    description = ("an env read inside a build/traced scope executes "
                   "once per cache slot, not per call — hoist to "
                   "dispatch or fold into the key")


@register
class UnstableKeyComponent(_KeyflowRule):
    code = "R20"
    name = "unstable-key-component"
    description = ("id()/hash()/repr() flow into the persistent key "
                   "surface — unstable across processes, so a shipped "
                   "artifact keyed by them can never hit")


@register
class CacheTagCollision(_KeyflowRule):
    code = "R21"
    name = "cache-tag-collision"
    description = ("two distinct build callables share the cache "
                   "owner/tag/statics vocabulary — their programs "
                   "collide in one executable slot")
