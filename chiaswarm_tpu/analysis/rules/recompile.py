"""R6 recompile-hazard: raw request shapes must be bucketed before they
reach compiled code.

Every distinct (height, width, batch) that flows into a jitted program is
a fresh XLA compilation — minutes on TPU for an SDXL-class UNet. The
whole point of ``compile_cache.bucket_image_size``/``bucket_batch`` is
that arbitrary requested sizes snap onto a small compiled lattice; a
pipeline that feeds ``req.height`` straight into its executable reopens
the compile-per-job failure mode the cache exists to close.

Heuristic (program layer only — ``pipelines/``, ``workloads/``): a
function is flagged when it

1. executes compiled code — it calls ``<jit wrapper>(fn)(...)``
   immediately, calls a local name previously bound from a jit wrapper,
   or goes through ``cached_executable``/``get_or_create``; and
2. reads a raw shape attribute (``.height``/``.width``/``.batch``/
   ``.num_frames``) from a request-like object; and
3. never calls a bucketing helper (``bucket_image_size``,
   ``bucket_batch``, or a local ``_bucket*``/``snap*`` helper).

The finding sits on the first raw shape read. Intra-function only: a
function that merely forwards the request object is fine — the function
that unpacks shapes next to the executable is the one that must bucket.
"""

from __future__ import annotations

import ast
from typing import Iterator

from chiaswarm_tpu.analysis.core import (
    Finding, FunctionInfo, ModuleContext, Rule, register,
)
from chiaswarm_tpu.analysis.rules import JIT_WRAPPERS, own_nodes, resolves_to

_TOPLEVEL_PACKAGES = ("chiaswarm_tpu/pipelines/", "chiaswarm_tpu/workloads/")
_SHAPE_ATTRS = frozenset({"height", "width", "batch", "num_frames"})
_BUCKET_HELPERS = ("bucket_image_size", "bucket_batch",
                   "compile_cache.bucket_image_size",
                   "compile_cache.bucket_batch")
_EXEC_ATTRS = frozenset({"cached_executable", "get_or_create"})


@register
class RecompileHazard(Rule):
    code = "R6"
    name = "recompile-hazard"
    description = ("raw request shapes (.height/.width/.batch) must pass "
                   "through the shape-bucketing helpers before reaching "
                   "compiled code")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not any(p in ctx.relpath for p in _TOPLEVEL_PACKAGES):
            return
        # the repo's dominant pattern binds executables to SELF in
        # __init__ (self._fwd = toplevel_jit(...)) and calls them from
        # other methods — collect those attr names module-wide
        self_jit_attrs: set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) and resolves_to(
                    ctx.callable_target(node.value), *JIT_WRAPPERS):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and isinstance(
                            t.value, ast.Name) and t.value.id == "self":
                        self_jit_attrs.add(t.attr)
        for info in ctx.functions:
            if not isinstance(info.node,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_function(ctx, info, self_jit_attrs)

    def _check_function(self, ctx: ModuleContext, info: FunctionInfo,
                        self_jit_attrs: set[str]) -> Iterator[Finding]:
        executes = False
        buckets = False
        jit_bound: set[str] = set()
        shape_reads: list[ast.Attribute] = []
        nodes = list(own_nodes(info.node))

        # pass 1: names bound from jit wrappers (AST walk order is not
        # source order, so bindings must be known before the use pass)
        for node in nodes:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) and resolves_to(
                    ctx.callable_target(node.value), *JIT_WRAPPERS):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jit_bound.add(t.id)

        for node in nodes:
            if isinstance(node, ast.Call):
                resolved = ctx.resolve_call(node)
                if resolves_to(resolved, *_BUCKET_HELPERS) or (
                        resolved and _is_bucket_name(
                            resolved.rsplit(".", 1)[-1])):
                    buckets = True
                if isinstance(node.func, ast.Call) and resolves_to(
                        ctx.resolve_call(node.func), *JIT_WRAPPERS):
                    executes = True  # jax.jit(fn)(args)
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in jit_bound:
                    executes = True  # fn = toplevel_jit(...); fn(args)
                elif isinstance(node.func, ast.Attribute) and (
                        node.func.attr in _EXEC_ATTRS
                        or (node.func.attr in self_jit_attrs
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id == "self")):
                    executes = True  # self._fwd(...) bound in __init__
            if isinstance(node, ast.Attribute) \
                    and node.attr in _SHAPE_ATTRS \
                    and isinstance(node.ctx, ast.Load) \
                    and isinstance(node.value, ast.Name):
                shape_reads.append(node)

        if executes and shape_reads and not buckets:
            first = min(shape_reads, key=lambda n: (n.lineno, n.col_offset))
            attrs = sorted({n.attr for n in shape_reads})
            yield self.finding(
                ctx, first,
                f"raw request shape attribute(s) {', '.join(attrs)} reach "
                f"compiled code without shape bucketing — every distinct "
                f"value is a fresh XLA compile; snap through "
                f"compile_cache.bucket_image_size/bucket_batch first")


def _is_bucket_name(name: str) -> bool:
    """Local bucketing helpers by naming convention. Deliberately
    narrow: a word-boundary is required so e.g. ``store.snapshot()``
    does not silence the rule for the whole function."""
    return (name in ("snap", "bucket")
            or name.startswith(("bucket_", "_bucket", "snap_")))
