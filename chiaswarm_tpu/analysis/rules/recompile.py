"""R6 recompile-hazard: raw request shapes must be bucketed before they
reach compiled code.

Every distinct (height, width, batch) that flows into a jitted program is
a fresh XLA compilation — minutes on TPU for an SDXL-class UNet. The
whole point of ``compile_cache.bucket_image_size``/``bucket_batch`` is
that arbitrary requested sizes snap onto a small compiled lattice; a
pipeline that feeds ``req.height`` straight into its executable reopens
the compile-per-job failure mode the cache exists to close.

Two faces since ISSUE 20:

**Per-function heuristic** (the original AST rule, now replayed from
summarize-time facts so the rule sees the whole program): a function is
flagged when it

1. executes compiled code — it calls ``<jit wrapper>(fn)(...)``
   immediately, calls a local name previously bound from a jit wrapper,
   or goes through ``cached_executable``/``get_or_create``; and
2. reads a raw shape attribute (``.height``/``.width``/``.batch``/
   ``.num_frames``) from a request-like object; and
3. never calls a bucketing helper (``bucket_image_size``,
   ``bucket_batch``, or a local ``_bucket*``/``snap*`` helper).

The finding sits on the first raw shape read. Intra-function only: a
function that merely forwards the request object is fine — the function
that unpacks shapes next to the executable is the one that must bucket.

**Interprocedural face** (analysis/keyflow.py): the static vocabulary of
a ``static_cache_key`` call is an executable-cardinality contract, so a
non-hashable container display built from varying values inside the
static dict, or a bare key-site parameter that a CALLER feeds straight
from a raw request attribute without bucketing, is the same hazard one
call hop away — the per-function pass cannot see it because the read and
the key site live in different functions. Facts ride the swarmflow
index; the program layer gate (``pipelines/``/``workloads/``) applies to
the per-function face only, matching the original rule's jurisdiction.
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING, Iterator

from chiaswarm_tpu.analysis.core import (
    Finding, FunctionInfo, ModuleContext, ProjectRule, register,
)
from chiaswarm_tpu.analysis.rules import JIT_WRAPPERS, own_nodes, resolves_to

if TYPE_CHECKING:  # the index arrives at check time; no runtime dep
    from chiaswarm_tpu.analysis.project import ProjectIndex

_TOPLEVEL_PACKAGES = ("chiaswarm_tpu/pipelines/", "chiaswarm_tpu/workloads/")
_SHAPE_ATTRS = frozenset({"height", "width", "batch", "num_frames"})
_BUCKET_HELPERS = ("bucket_image_size", "bucket_batch",
                   "compile_cache.bucket_image_size",
                   "compile_cache.bucket_batch")
_EXEC_ATTRS = frozenset({"cached_executable", "get_or_create"})


# ---------------------------------------------------------------------------
# summarize-time fact extraction (called by project._Summarizer, the same
# hook shape as host_sync.sync_sites: the AST is only in hand while the
# summary is built, and the whole-program pass replays the compact facts)


def self_jit_attrs(ctx: ModuleContext) -> set[str]:
    """Module-wide ``self._fwd = toplevel_jit(...)`` attribute names: the
    repo's dominant pattern binds executables to SELF in __init__ and
    calls them from other methods."""
    attrs: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call) and resolves_to(
                ctx.callable_target(node.value), *JIT_WRAPPERS):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and isinstance(
                        t.value, ast.Name) and t.value.id == "self":
                    attrs.add(t.attr)
    return attrs


def recompile_facts(ctx: ModuleContext, info: FunctionInfo,
                    jattrs: set[str]) -> dict | None:
    """Compact per-function facts: ``x`` (executes compiled code), ``b``
    (calls a bucketing helper), ``reads`` ([line, col, attr] raw shape
    reads). None when the function touches none of the vocabulary."""
    if isinstance(info.node, ast.Lambda):
        return None
    executes = False
    buckets = False
    jit_bound: set[str] = set()
    reads: list[list] = []
    nodes = list(own_nodes(info.node))

    # pass 1: names bound from jit wrappers (AST walk order is not
    # source order, so bindings must be known before the use pass)
    for node in nodes:
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call) and resolves_to(
                ctx.callable_target(node.value), *JIT_WRAPPERS):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    jit_bound.add(t.id)

    for node in nodes:
        if isinstance(node, ast.Call):
            resolved = ctx.resolve_call(node)
            if resolves_to(resolved, *_BUCKET_HELPERS) or (
                    resolved and _is_bucket_name(
                        resolved.rsplit(".", 1)[-1])):
                buckets = True
            if isinstance(node.func, ast.Call) and resolves_to(
                    ctx.resolve_call(node.func), *JIT_WRAPPERS):
                executes = True  # jax.jit(fn)(args)
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in jit_bound:
                executes = True  # fn = toplevel_jit(...); fn(args)
            elif isinstance(node.func, ast.Attribute) and (
                    node.func.attr in _EXEC_ATTRS
                    or (node.func.attr in jattrs
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self")):
                executes = True  # self._fwd(...) bound in __init__
        if isinstance(node, ast.Attribute) \
                and node.attr in _SHAPE_ATTRS \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.value, ast.Name):
            reads.append([node.lineno, node.col_offset, node.attr])

    facts: dict = {}
    if executes:
        facts["x"] = 1
    if buckets:
        facts["b"] = 1
    if reads:
        facts["reads"] = reads
    return facts or None


@register
class RecompileHazard(ProjectRule):
    code = "R6"
    name = "recompile-hazard"
    description = ("raw request shapes (.height/.width/.batch) must pass "
                   "through the shape-bucketing helpers before reaching "
                   "compiled code")

    def check_project(self, index: "ProjectIndex") -> Iterator[Finding]:
        for rel in sorted(index.summaries):
            if not any(p in rel for p in _TOPLEVEL_PACKAGES):
                continue
            s = index.summaries[rel]
            for qual in sorted(s["functions"]):
                r6 = s["functions"][qual].get("r6")
                if not r6 or "x" not in r6 or "b" in r6 \
                        or not r6.get("reads"):
                    continue
                reads = sorted(tuple(r) for r in r6["reads"])
                line, col, _ = reads[0]
                attrs = sorted({r[2] for r in reads})
                yield Finding(
                    rule=self.name, path=rel, line=line, col=col,
                    message=(
                        f"raw request shape attribute(s) "
                        f"{', '.join(attrs)} reach compiled code without "
                        f"shape bucketing — every distinct value is a "
                        f"fresh XLA compile; snap through "
                        f"compile_cache.bucket_image_size/bucket_batch "
                        f"first"),
                    symbol=qual)
        # interprocedural face: unbounded/non-hashable values flowing
        # into a static key vocabulary across the call graph
        from chiaswarm_tpu.analysis import keyflow

        for f in keyflow.results(index).findings:
            if f.rule == self.name:
                yield f


def _is_bucket_name(name: str) -> bool:
    """Local bucketing helpers by naming convention. Deliberately
    narrow: a word-boundary is required so e.g. ``store.snapshot()``
    does not silence the rule for the whole function."""
    return (name in ("snap", "bucket")
            or name.startswith(("bucket_", "_bucket", "snap_")))
