"""R2 prng-key-reuse: a PRNG key feeds at most one jax.random consumer.

Stateless PRNG discipline (core/rng.py): every ``jax.random.*`` draw —
and ``split`` itself — consumes its key; reusing the same key variable
for a second draw yields CORRELATED samples silently (two "independent"
noise tensors that are bit-identical). The classic bug::

    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    noise = jax.random.normal(key, shape)   # <- key already spent by split

``fold_in(key, i)`` is the sanctioned non-consuming derivation (it maps
the parent key to a fresh stream without invalidating it for further
fold_ins — the per-row pattern in pipelines/cascade.py), so it neither
consumes nor trips the rule.

Analysis is per-function and flow-sensitive over straight-line code:
branches are analyzed independently then merged (consumed-anywhere wins);
loop bodies are analyzed twice so a draw from a loop-invariant key is
caught as cross-iteration reuse. Names are tracked when assigned from a
key-producing call or when a parameter looks like a key (``key``,
``rng``, ``*key``). Interprocedural flows are not tracked: passing a key
to a helper does not consume it here — the helper's own body is analyzed
on its own.
"""

from __future__ import annotations

import ast
from typing import Iterator

from chiaswarm_tpu.analysis.core import (
    Finding, ModuleContext, Rule, register,
)
from chiaswarm_tpu.analysis.rules import FUNC_NODES as _FUNC_NODES
from chiaswarm_tpu.analysis.rules import resolves_to

_FRESH = "fresh"
_CONSUMED = "consumed"

#: calls whose result is a key (or batch of keys)
_PRODUCERS = ("jax.random.PRNGKey", "jax.random.key", "jax.random.split",
              "jax.random.fold_in", "jax.random.wrap_key_data",
              "rng.key_for_seed", "key_for_seed", "rng.per_sample_keys",
              "per_sample_keys")
#: jax.random calls that do NOT consume their key argument
_NON_CONSUMING = ("jax.random.fold_in", "jax.random.key_data",
                  "jax.random.key_impl")


def _keyish_param(name: str) -> bool:
    return name in ("rng", "prng") or name.endswith("key")


@register
class PrngKeyReuse(Rule):
    code = "R2"
    name = "prng-key-reuse"
    description = ("the same PRNG key must not feed two jax.random calls "
                   "without an intervening split/fold_in")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        findings: dict[tuple[int, int, str], Finding] = {}

        def emit(node: ast.AST, name: str) -> None:
            loc = (node.lineno, node.col_offset, name)
            if loc not in findings:
                findings[loc] = self.finding(
                    ctx, node,
                    f"PRNG key '{name}' is consumed by a second "
                    f"jax.random call without an intervening "
                    f"split/fold_in rebind — draws will be correlated")

        for scope, body in _scopes(ctx):
            state: dict[str, str] = {}
            if isinstance(scope, _FUNC_NODES):
                args = scope.args
                for a in (args.posonlyargs + args.args + args.kwonlyargs):
                    if _keyish_param(a.arg):
                        state[a.arg] = _FRESH
            _scan_block(ctx, body, state, emit)
        yield from findings.values()


def _scopes(ctx: ModuleContext):
    """(scope_node, stmt_list) for the module and every function."""
    yield ctx.tree, ctx.tree.body
    for info in ctx.functions:
        node = info.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.body
        elif isinstance(node, ast.Lambda):
            yield node, [ast.Expr(value=node.body)]


def _scan_block(ctx, stmts, state, emit) -> None:
    for stmt in stmts:
        _scan_stmt(ctx, stmt, state, emit)


def _scan_stmt(ctx, stmt, state, emit) -> None:
    if isinstance(stmt, _FUNC_NODES + (ast.ClassDef,)):
        return  # nested scopes analyzed separately
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        value = stmt.value
        if value is not None:
            _scan_expr(ctx, value, state, emit)
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        produced = (isinstance(value, ast.Call)
                    and resolves_to(ctx.resolve_call(value), *_PRODUCERS))
        for t in targets:
            for name in _target_names(t):
                if produced:
                    state[name] = _FRESH
                else:
                    state.pop(name, None)  # rebound to something untracked
        return
    if isinstance(stmt, (ast.If,)):
        _scan_expr(ctx, stmt.test, state, emit)
        s1, s2 = dict(state), dict(state)
        _scan_block(ctx, stmt.body, s1, emit)
        _scan_block(ctx, stmt.orelse, s2, emit)
        _merge(state, s1, s2)
        return
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        _scan_expr(ctx, stmt.iter, state, emit)
        # iterating a key-producing call (`for k in split(key, n)`) binds
        # a FRESH per-iteration key each pass; anything else untracks
        produced = (isinstance(stmt.iter, ast.Call)
                    and resolves_to(ctx.resolve_call(stmt.iter),
                                    *_PRODUCERS))
        targets = _target_names(stmt.target)
        # two passes: the second models re-entering the loop, catching
        # draws from a key that is never rebound inside the body
        for _ in range(2):
            for name in targets:
                if produced:
                    state[name] = _FRESH
                else:
                    state.pop(name, None)
            _scan_block(ctx, stmt.body, state, emit)
        _scan_block(ctx, stmt.orelse, state, emit)
        return
    if isinstance(stmt, ast.While):
        _scan_expr(ctx, stmt.test, state, emit)
        _scan_block(ctx, stmt.body, state, emit)
        _scan_block(ctx, stmt.body, state, emit)
        _scan_block(ctx, stmt.orelse, state, emit)
        return
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            _scan_expr(ctx, item.context_expr, state, emit)
        _scan_block(ctx, stmt.body, state, emit)
        return
    if isinstance(stmt, ast.Try):
        _scan_block(ctx, stmt.body, state, emit)
        for handler in stmt.handlers:
            _scan_block(ctx, handler.body, dict(state), emit)
        _scan_block(ctx, stmt.orelse, state, emit)
        _scan_block(ctx, stmt.finalbody, state, emit)
        return
    if isinstance(stmt, ast.Match):
        _scan_expr(ctx, stmt.subject, state, emit)
        branches: list[dict] = []
        for case in stmt.cases:
            s = dict(state)
            if case.guard is not None:
                _scan_expr(ctx, case.guard, s, emit)
            _scan_block(ctx, case.body, s, emit)
            branches.append(s)
        # merge like If/else: consumed in any arm wins. The implicit
        # no-match path keeps the incoming state — unless a wildcard arm
        # (`case _:` / bare capture) makes no-match impossible
        exhaustive = any(
            isinstance(c.pattern, ast.MatchAs) and c.pattern.pattern is None
            for c in stmt.cases)
        incoming = [] if exhaustive else [dict(state)]
        _merge_many(state, branches + incoming)
        return
    # Return / Expr / Assert / Raise / ...
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, ast.expr):
            _scan_expr(ctx, child, state, emit)


_COMP_NODES = (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)


def _scan_expr(ctx, expr, state, emit,
               skip: frozenset[str] = frozenset()) -> None:
    """Find key-consuming draws in an expression.

    Comprehensions get special treatment: their ``for`` targets are
    per-iteration bindings (a target shadowing an outer key name must
    not consume it), and their bodies run repeatedly — modeled as two
    passes so a loop-invariant key drawn per element is caught as reuse.
    """
    todo = [expr]
    while todo:
        node = todo.pop()
        if isinstance(node, _COMP_NODES):
            bound = frozenset(
                name for gen in node.generators
                for name in _target_names(gen.target)) | skip
            for gen in node.generators:
                # iter evaluates in the enclosing scope (once)
                _scan_expr(ctx, gen.iter, state, emit, skip)
            parts = ([node.key, node.value]
                     if isinstance(node, ast.DictComp) else [node.elt])
            parts += [i for gen in node.generators for i in gen.ifs]
            for _ in range(2):  # model iteration
                for part in parts:
                    _scan_expr(ctx, part, state, emit, bound)
            continue
        if isinstance(node, ast.Call):
            _check_draw(ctx, node, state, emit, skip=skip)
        todo.extend(ast.iter_child_nodes(node))


def _check_draw(ctx, node: ast.Call, state, emit,
                skip: frozenset[str] = frozenset()) -> None:
    resolved = ctx.resolve_call(node)
    if not (resolved and (resolved.startswith("jax.random.")
                          or resolves_to(resolved, "random.split",
                                         "random.fold_in"))):
        return
    if resolves_to(resolved, *_NON_CONSUMING):
        return
    if resolves_to(resolved, "jax.random.PRNGKey", "jax.random.key",
                   "jax.random.wrap_key_data"):
        return  # constructors take ints, not keys
    key_arg = None
    if node.args:
        key_arg = node.args[0]
    else:
        for kw in node.keywords:
            if kw.arg == "key":
                key_arg = kw.value
    if isinstance(key_arg, ast.Name) and key_arg.id in state \
            and key_arg.id not in skip:
        if state[key_arg.id] == _CONSUMED:
            emit(node, key_arg.id)
        else:
            state[key_arg.id] = _CONSUMED


def _target_names(target) -> list[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: list[str] = []
        for elt in target.elts:
            out.extend(_target_names(
                elt.value if isinstance(elt, ast.Starred) else elt))
        return out
    return []


def _merge(state, s1, s2) -> None:
    _merge_many(state, [s1, s2])


def _merge_many(state, branches: list[dict]) -> None:
    """Join branch states: consumed anywhere wins, fresh anywhere next,
    and a name absent from EVERY branch (rebound to something untracked
    on all paths) is untracked — including names still in ``state``."""
    for name in set(state) | {n for s in branches for n in s}:
        vals = [s.get(name) for s in branches]
        if _CONSUMED in vals:
            state[name] = _CONSUMED
        elif _FRESH in vals:
            state[name] = _FRESH
        else:
            state.pop(name, None)
