"""R10 sharding-spec-drift: mesh axis names must agree across modules.

The mesh vocabulary is defined once (``core/mesh.py``: ``data`` /
``model`` / ``seq``) but consumed everywhere — ``PartitionSpec`` layouts
in ``parallel/sharding.py``, ``shard_map`` in/out specs in
``ops/attention.py``, collective ``axis_name=`` deep inside
``parallel/ring_attention.py``, lane row sharding in
``serving/stepper.py``. Nothing ties them together: a renamed axis, a
misspelled spec, or an in_specs tuple that no longer matches the callee's
signature compiles fine in whatever unit test never builds the real mesh,
then fails (or silently reshards) on the pod. The open seq-parallel
numerics divergence is exactly this class of bug.

Checks, over the swarmflow project index:

- **unknown axis**: an axis name in a ``PartitionSpec``, ``shard_map``
  spec or collective that no mesh construct anywhere binds. The universe
  is every ``*_AXIS``/``*AXES`` string constant, ``Mesh(..., axis_names)``
  literal and ``MeshSpec({...})`` key in the project, with constants
  resolved through imports. No meshes in the project -> the rule is
  silent (nothing to drift from).
- **in_specs arity**: ``shard_map(f, in_specs=(...))`` passes exactly
  ``len(in_specs)`` positional arguments to ``f`` — flagged when ``f``
  resolves to a project function (``functools.partial`` unwrapped, its
  positional bindings counted) whose signature cannot accept that many.
  The finding chains caller -> callee.
- **unbound collective axis**: a collective reading its axis name from a
  function parameter (including via closure, e.g. a scan body) where
  callers exist but none binds that parameter — a guaranteed TypeError
  once the code path runs — or where a caller binds it to an axis no
  mesh defines (finding at the caller, chained caller -> callee).

All value judgments are conservative: an axis expression that cannot be
resolved to a string constant is silent, a callee that does not resolve
to a project function is silent. This is a lint, not a prover.
"""

from __future__ import annotations

from typing import Iterator

from chiaswarm_tpu.analysis.core import Finding, ProjectRule, register
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # the index arrives at check time; no runtime dep
    from chiaswarm_tpu.analysis.project import ProjectIndex


@register
class ShardingSpecDrift(ProjectRule):
    code = "R10"
    name = "sharding-spec-drift"
    description = ("PartitionSpec/shard_map/collective axis names must be "
                   "bound by a mesh; in_specs arity must match the callee "
                   "(whole-program)")

    def check_project(self, index: "ProjectIndex") -> Iterator[Finding]:
        universe = index.axis_universe()
        if not universe:
            return
        known = ", ".join(sorted(universe))
        for rel in sorted(index.summaries):
            s = index.summaries[rel]
            module = s["module"]
            yield from self._unknown_axes(index, s, universe, known)
            for rec in s["shard_maps"]:
                yield from self._arity(index, module, rel, rec)
                yield from self._instance(index, module, rel, rec,
                                          universe)
        yield from self._collectives(index, universe, known)

    # ---- unknown axis names in specs -----------------------------------
    def _unknown_axes(self, index, s, universe, known) -> Iterator[Finding]:
        for spec in s["specs"]:
            for ref in spec["axes"]:
                axis = index.resolve_axis(ref, s["module"])
                if axis is not None and axis not in universe:
                    yield Finding(
                        rule=self.name, path=s["relpath"],
                        line=spec["line"], col=spec["col"],
                        message=(f"PartitionSpec axis {axis!r} is bound by "
                                 f"no mesh in the project (known axes: "
                                 f"{known})"),
                        symbol=spec["symbol"],
                    )

    # ---- shard_map in_specs arity vs callee signature ------------------
    def _arity(self, index, module, rel, rec) -> Iterator[Finding]:
        if rec["in_arity"] is None:
            return
        caller_hop = (rel, rec["line"], f"{module}.{rec['symbol']}")
        if rec.get("lam"):  # inline `shard_map(lambda q, k, v: ...)`
            lam = rec["lam"]
            if lam["vararg"]:
                return
            lo, hi = lam["npos"] - lam["ndef"], lam["npos"]
            if not lo <= rec["in_arity"] <= hi:
                want = str(hi) if lo == hi else f"{lo}..{hi}"
                yield Finding(
                    rule=self.name, path=rel,
                    line=rec["line"], col=rec["col"],
                    message=(f"shard_map supplies {rec['in_arity']} "
                             f"positional arg(s) (in_specs arity) but its "
                             f"lambda takes {want}"),
                    symbol=rec["symbol"],
                    chain=(caller_hop,),
                )
            return
        if not rec["callee"]:
            return
        targets = index.func_targets(module, rec["callee"])
        if len(targets) != 1:
            return  # unresolvable or ambiguous: stay silent
        callee = targets[0]
        f = index.funcs[callee]
        if f["vararg"] or f["meth"]:
            return
        supplied = rec["in_arity"] + rec["pconsumed"]
        lo, hi = f["npos"] - f["ndef"], f["npos"]
        if lo <= supplied <= hi:
            return
        callee_rel = index.modules[callee[0]]
        callee_hop = (callee_rel, f["line"], f"{callee[0]}.{callee[1]}")
        want = str(hi) if lo == hi else f"{lo}..{hi}"
        yield Finding(
            rule=self.name, path=rel, line=rec["line"], col=rec["col"],
            message=(f"shard_map supplies {supplied} positional arg(s) "
                     f"(in_specs arity {rec['in_arity']}"
                     + (f" + {rec['pconsumed']} partial-bound"
                        if rec["pconsumed"] else "")
                     + f") but '{callee[0]}.{callee[1]}' takes {want}"),
            symbol=rec["symbol"],
            chain=(caller_hop, callee_hop),
        )

    # ---- per-mesh-instance axis universes (swarmproof extension) -------
    def _instance(self, index, module, rel, rec,
                  universe) -> Iterator[Finding]:
        """Axis names in a shard_map's in/out specs must be bound by THE
        mesh instance the site runs on, not merely by *some* mesh in the
        project — a ``data``-only ``Mesh`` literal does not sanction
        ``seq`` specs just because an unrelated ``seq`` mesh exists.

        Only CLOSED instances (raw ``Mesh(devices, axis_names)``
        literals) constrain the check: ``MeshSpec``-built meshes carry
        every vocabulary axis at size >= 1 (core/mesh.py), so any
        project-known axis is legal on them. Axes unknown to the whole
        project are already reported by the global check — this one only
        fires on names the global universe KNOWS but this instance does
        not bind, which is exactly the R10 imprecision the per-instance
        extension retires."""
        inst = index.resolve_mesh(module, rec["symbol"], rec.get("mesh"))
        if inst is None or inst["open"]:
            return
        specs = list(rec.get("in_axes") or [])
        if rec.get("in_single") is not None:
            specs.append(rec["in_single"])
        if rec.get("out_axes") is not None:
            specs.append(rec["out_axes"])
        caller_hop = (rel, rec["line"], f"{module}.{rec['symbol']}")
        flagged: set[str] = set()
        for spec in specs:
            if spec is None:
                continue
            for ref in spec["may"]:
                axis = index.resolve_axis(ref, module)
                if axis is None or axis in flagged:
                    continue
                if axis in universe and axis not in inst["axes"]:
                    flagged.add(axis)
                    have = ", ".join(sorted(inst["axes"])) or "none"
                    yield Finding(
                        rule=self.name, path=rel,
                        line=rec["line"], col=rec["col"],
                        message=(f"shard_map spec uses axis {axis!r} "
                                 f"but its mesh instance "
                                 f"'{inst['hop'][2]}' binds only "
                                 f"[{have}] — another mesh defining "
                                 f"{axis!r} elsewhere does not apply "
                                 f"here"),
                        symbol=rec["symbol"],
                        chain=(caller_hop, inst["hop"]),
                    )

    # ---- collectives reading parameter-borne axis names ----------------
    def _collectives(self, index, universe, known) -> Iterator[Finding]:
        # caller records per callee, built once: (caller, call-record)
        calls_to: dict[tuple, list[tuple[tuple, dict]]] = {}
        for caller, f in index.funcs.items():
            for call in f["calls"]:
                if not call["t"]:
                    continue
                for target in index.func_targets(caller[0], call["t"]):
                    calls_to.setdefault(target, []).append((caller, call))

        # collective sites grouped by the (function, parameter) whose value
        # they read — ring_attention's ppermute/ppermute/axis_size all read
        # one axis_name, and a bad caller binding is ONE finding, not three
        by_param: dict[tuple[str, str, str], list[tuple[str, dict]]] = {}
        for rel in sorted(index.summaries):
            s = index.summaries[rel]
            module = s["module"]
            for col in s["collectives"]:
                axis = col["axis"]
                if axis is None:
                    continue
                if "param" in axis:
                    key = (module, axis["owner"], axis["param"])
                    by_param.setdefault(key, []).append((rel, col))
                    continue
                v = index.resolve_axis(axis, module)
                if v is not None and v not in universe:
                    yield Finding(
                        rule=self.name, path=rel,
                        line=col["line"], col=col["col"],
                        message=(f"collective {col['op']} uses axis "
                                 f"name {v!r} which no mesh binds "
                                 f"(known axes: {known})"),
                        symbol=col["symbol"],
                    )
        for (module, owner_qual, param), sites in sorted(by_param.items()):
            yield from self._param_axis(index, universe, known, module,
                                        owner_qual, param, sites, calls_to)

    def _param_axis(self, index, universe, known, module, owner_qual,
                    param, sites, calls_to) -> Iterator[Finding]:
        owner = (module, owner_qual)
        f = index.funcs.get(owner)
        if f is None:
            return
        callers = calls_to.get(owner, [])
        if not callers:
            return  # library entry point: nothing to check against
        ops = "/".join(sorted({col["op"] for _, col in sites}))
        owner_rel = index.modules[owner[0]]
        owner_hop = (owner_rel, f["line"], f"{owner[0]}.{owner[1]}")
        pidx = f["pargs"].index(param) if param in f["pargs"] else None
        bound = False
        for caller, call in callers:
            value = None
            if param in call["kw"]:
                bound = True
                value = index.resolve_axis(call["kw"][param], caller[0])
            elif pidx is not None and call["np"] > pidx:
                bound = True
                value = call["poslits"].get(str(pidx))
            if value is not None and value not in universe:
                caller_rel = index.modules[caller[0]]
                caller_f = index.funcs[caller]
                yield Finding(
                    rule=self.name, path=caller_rel,
                    line=call["line"], col=0,
                    message=(f"caller binds axis parameter {param!r} of "
                             f"'{owner[0]}.{owner[1]}' to {value!r} which "
                             f"no mesh binds (known axes: {known}); "
                             f"collective(s) {ops} read it"),
                    symbol=caller[1],
                    chain=((caller_rel, caller_f["line"],
                            f"{caller[0]}.{caller[1]}"), owner_hop),
                )
        has_default = not (
            param in f["kwreq"]
            or (pidx is not None and pidx < f["npos"] - f["ndef"]))
        if not bound and not has_default:
            for rel, col in sites:
                yield Finding(
                    rule=self.name, path=rel,
                    line=col["line"], col=col["col"],
                    message=(f"collective {col['op']} reads axis name "
                             f"from parameter {param!r} of "
                             f"'{owner[0]}.{owner[1]}' which no caller "
                             f"binds"),
                    symbol=col["symbol"],
                    chain=(owner_hop,),
                )
