"""R7 scan-carry-dtype: mixed-precision loop bodies must pin the carry
dtype before returning it.

``lax.scan``/``while_loop``/``fori_loop`` require the carry's dtype to be
invariant across iterations; a body that upcasts to a compute dtype
(``x.astype(jnp.float32)``) and returns the result un-pinned either fails
at trace time (scan) or — in a HOST-driven step loop like the continuous
batcher's per-row carry (serving/stepper.py) — silently recompiles every
iteration and corrupts multistep state that straddles the promotion. The
repo's sampler pins its carry explicitly
(``x_next.astype(sample.dtype)``, schedulers/sampling.py) — this rule
enforces that discipline.

Heuristic: for every function syntactically passed as the body of
``jax.lax.scan`` (arg 0), ``jax.lax.while_loop`` (arg 1) or
``jax.lax.fori_loop`` (arg 2) — or bound via ``f=``/``body_fun=`` — if
the body contains at least one explicit dtype cast (``.astype(...)`` or a
``jnp.float32/bfloat16/float16(...)`` constructor), then the returned
carry (the first element of a scan body's return tuple; the whole return
value otherwise) must be dtype-pinned: an ``.astype(...)`` call, a name
whose last assignment was one, or a parameter returned untouched. Bodies
without casts are single-precision and stay silent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from chiaswarm_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)
from chiaswarm_tpu.analysis.rules import FUNC_NODES, own_nodes, resolves_to

#: loop primitive -> positional index of the body callable
_LOOP_BODY_ARG = {
    "jax.lax.scan": 0,
    "lax.scan": 0,
    "jax.lax.while_loop": 1,
    "lax.while_loop": 1,
    "jax.lax.fori_loop": 2,
    "lax.fori_loop": 2,
}
_BODY_KEYWORDS = ("f", "body_fun", "body")

_CAST_CONSTRUCTORS = ("jax.numpy.float32", "jax.numpy.bfloat16",
                      "jax.numpy.float16", "jax.numpy.float64")


@register
class ScanCarryDtype(Rule):
    code = "R7"
    name = "scan-carry-dtype"
    description = ("mixed-precision scan/loop bodies must pin the carry "
                   "dtype (.astype) before returning it — a promoted "
                   "carry breaks lax.scan and silently recompiles "
                   "host-driven step loops")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        bodies: dict[ast.AST, str] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.resolve_call(node)
            idx = None
            for name, pos in _LOOP_BODY_ARG.items():
                if resolves_to(target, name):
                    idx = pos
                    kind = name.rsplit(".", 1)[-1]
                    break
            if idx is None:
                continue
            body_expr = None
            if len(node.args) > idx:
                body_expr = node.args[idx]
            else:
                for kw in node.keywords:
                    if kw.arg in _BODY_KEYWORDS:
                        body_expr = kw.value
                        break
            fn = self._resolve_body(ctx, body_expr)
            if fn is not None:
                bodies[fn] = kind
        for fn, kind in bodies.items():
            yield from self._check_body(ctx, fn, kind)

    @staticmethod
    def _resolve_body(ctx: ModuleContext, expr) -> ast.AST | None:
        if isinstance(expr, FUNC_NODES):
            return expr
        if isinstance(expr, ast.Name):
            for info in ctx.functions:
                node = info.node
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node.name == expr.id:
                    return node
        return None

    def _check_body(self, ctx: ModuleContext, fn: ast.AST,
                    kind: str) -> Iterator[Finding]:
        nodes = list(own_nodes(fn))
        has_cast = False
        pinned_names: set[str] = set()
        reassigned: set[str] = set()
        for node in nodes:
            if self._is_cast(ctx, node):
                has_cast = True
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        reassigned.add(t.id)
                        # an astype pins a name — unless it is itself a
                        # float promotion (x.astype(jnp.float32))
                        if self._is_astype(node.value) and \
                                not self._is_cast(ctx, node.value):
                            pinned_names.add(t.id)
                        else:
                            pinned_names.discard(t.id)
        if not has_cast:
            return
        params: set[str] = set()
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        elif isinstance(fn, ast.Lambda):
            params = {a.arg for a in fn.args.args}
        untouched = params - reassigned

        for node in nodes:
            carry = self._carry_expr(node, fn, kind)
            if carry is None:
                continue
            if not self._pinned(ctx, carry, pinned_names, untouched):
                yield self.finding(
                    ctx, carry,
                    f"{kind} body mixes dtypes (explicit cast present) "
                    f"but returns its carry un-pinned — a promoted carry "
                    f"dtype breaks the loop or recompiles per step; "
                    f"return carry.astype(<carry-in dtype>) instead")
                return  # one finding per body

    @staticmethod
    def _carry_expr(node, fn, kind):
        if isinstance(fn, ast.Lambda):
            value = fn.body if node is fn.body else None
        elif isinstance(node, ast.Return):
            value = node.value
        else:
            return None
        if value is None:
            return None
        if kind == "scan" and isinstance(value, ast.Tuple) and value.elts:
            return value.elts[0]  # scan returns (carry, per-step output)
        return value

    @classmethod
    def _pinned(cls, ctx: ModuleContext, expr, pinned_names: set[str],
                untouched_params: set[str]) -> bool:
        if isinstance(expr, ast.Tuple):
            return all(cls._pinned(ctx, e, pinned_names, untouched_params)
                       for e in expr.elts)
        if cls._is_cast(ctx, expr):
            # returning an explicit FLOAT promotion (``jnp.float32(y)``,
            # ``y.astype(jnp.bfloat16)``) IS the hazard, not a pin
            return False
        if cls._is_astype(expr):
            return True  # .astype(x.dtype)-style pin
        if isinstance(expr, ast.Name):
            return (expr.id in pinned_names
                    or expr.id in untouched_params)
        if isinstance(expr, ast.Call):
            # opaque helper calls (``sampler_step(...)``-shaped carries)
            # get the benefit of the doubt — pinning may happen inside
            return True
        return False

    @staticmethod
    def _is_astype(expr) -> bool:
        return (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr == "astype")

    @classmethod
    def _is_cast(cls, ctx: ModuleContext, node) -> bool:
        """Only FLOAT dtype casts count as mixed precision: integer/bool
        casts (token ids, loop counters) cannot silently promote a bf16
        carry, and ``.astype(x.dtype)`` is the PIN, not a hazard."""
        if not isinstance(node, ast.Call):
            return False
        if cls._is_astype(node):
            if not node.args:
                return False
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return "float" in arg.value
            target = ctx.resolve(arg)
            return bool(target) and any(
                resolves_to(target, c) for c in _CAST_CONSTRUCTORS)
        target = ctx.resolve_call(node)
        return resolves_to(target, *_CAST_CONSTRUCTORS)
