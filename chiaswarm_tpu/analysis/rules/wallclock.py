"""R8 wallclock-duration: durations come from perf_counter, not time.time.

``time.time()`` is wall clock: NTP slews and steps it, VM migrations
jump it, and a leap-smear can stretch it — a duration computed by
subtracting two wall-clock stamps can be negative, or off by whatever
the clock did in between. Everything in this repo that MEASURES
(deadlines, backoff, lane pacing, the swarmscope span tracer in
``chiaswarm_tpu/obs``) runs on ``time.perf_counter``/``time.monotonic``;
wall clock is only for *labeling* a moment (log stamps, export
metadata), never for differencing.

Heuristic, per scope (module body or one function):

- collect names assigned directly from a ``time.time()`` (or
  ``datetime.datetime.now()`` / ``datetime.utcnow()``) call;
- flag any binary subtraction where either operand is such a call or
  such a name.

Subtraction is the tell: a stamp that is stored, compared for ordering,
or exported stays silent — only stamp-minus-stamp arithmetic (a
duration) fires.
"""

from __future__ import annotations

import ast
from typing import Iterator

from chiaswarm_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    register,
)
from chiaswarm_tpu.analysis.rules import FUNC_NODES, own_nodes, resolves_to

#: call targets that read the wall clock
_WALL_CALLS = (
    "time.time",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.now",
    "datetime.utcnow",
)


@register
class WallclockDuration(Rule):
    code = "R8"
    name = "wallclock-duration"
    description = ("durations must come from time.perf_counter/"
                   "time.monotonic — subtracting time.time() stamps "
                   "breaks under NTP slew and clock steps")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        # module body is a scope; every function is its own scope (a
        # name assigned from time.time() in one function says nothing
        # about a same-named local elsewhere)
        yield from self._check_scope(ctx, self._module_nodes(ctx.tree))
        for info in ctx.functions:
            yield from self._check_scope(ctx, list(own_nodes(info.node)))

    @staticmethod
    def _module_nodes(tree: ast.Module) -> list[ast.AST]:
        nodes: list[ast.AST] = []
        todo = list(tree.body)
        while todo:
            node = todo.pop()
            if isinstance(node, FUNC_NODES):
                continue  # separate scope (checked via ctx.functions)
            nodes.append(node)
            todo.extend(ast.iter_child_nodes(node))
        return nodes

    def _check_scope(self, ctx: ModuleContext,
                     nodes: list[ast.AST]) -> Iterator[Finding]:
        wall_names: set[str] = set()
        for node in nodes:
            if isinstance(node, ast.Assign) and \
                    self._is_wall_call(ctx, node.value):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        wall_names.add(target.id)
        for node in nodes:
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            for operand in (node.left, node.right):
                if self._is_wall(ctx, operand, wall_names):
                    yield self.finding(
                        ctx, node,
                        "duration computed by subtracting wall-clock "
                        "stamps (time.time() jumps under NTP/clock "
                        "steps); use time.perf_counter() or "
                        "time.monotonic()")
                    break

    @classmethod
    def _is_wall(cls, ctx: ModuleContext, expr: ast.AST,
                 wall_names: set[str]) -> bool:
        if cls._is_wall_call(ctx, expr):
            return True
        return isinstance(expr, ast.Name) and expr.id in wall_names

    @staticmethod
    def _is_wall_call(ctx: ModuleContext, expr: ast.AST) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        target = ctx.resolve_call(expr)
        return bool(target) and any(resolves_to(target, w)
                                    for w in _WALL_CALLS)
