"""R11/R12/R13 — the shardflow value-semantics rules (swarmproof).

R10 checks that axis names are *spelled* right; these three check that
sharding is *meant* right, via the abstract vma interpreter in
``analysis/shardflow.py`` (see its module docstring for the domain and
transfer functions):

- **R11 replicated-psum** — a ``psum``/``psum_scatter`` over an axis the
  operand is provably replicated on: the product is already complete on
  every shard, so the all-reduce multiplies it by the axis size. This is
  the static face of the r06-bisected seq-parallel divergence (K/V
  projections of a replicated text ctx coming out exactly ``seq``× too
  large under a two-axis shard_map).
- **R12 unreduced-out-spec** — a shard_map ``out_specs`` claiming
  replication over an axis the returned value still (provably) varies
  on: a per-shard partial value escapes the boundary mislabeled as
  replicated.
- **R13 donation-drift** — a buffer donated at a jit-wrapper call site
  (``donate_argnums``/``donate_argnames``, wrapper possibly built in
  another module and followed through re-exports) that the caller reads
  after the call: XLA has reused its memory. The compiled-side half
  (declared donation the lowered HLO shows undonated) lives in
  ``analysis/hlocheck.py`` / ``tools/shard_audit.py`` and reports under
  the same rule name.

All three are conservative: unresolvable specs, meshes, axes or callees
are silent — a lint must not invent semantics it cannot defend.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator

from chiaswarm_tpu.analysis.core import Finding, ProjectRule, register

if TYPE_CHECKING:  # the index arrives at check time; no runtime dep
    from chiaswarm_tpu.analysis.project import ProjectIndex


@register
class ReplicatedPsum(ProjectRule):
    code = "R11"
    name = "replicated-psum"
    description = ("psum/psum_scatter over an axis the operand is "
                   "provably replicated on multiplies by the axis size "
                   "(abstract vma interpretation, whole-program)")

    def check_project(self, index: "ProjectIndex") -> Iterator[Finding]:
        from chiaswarm_tpu.analysis import shardflow

        for f in shardflow.results(index).findings:
            if f.rule == self.name:
                yield f


@register
class UnreducedOutSpec(ProjectRule):
    code = "R12"
    name = "unreduced-out-spec"
    description = ("shard_map out_specs claiming replication over an "
                   "axis the returned value still varies on — a partial "
                   "sum escapes the boundary (abstract vma "
                   "interpretation)")

    def check_project(self, index: "ProjectIndex") -> Iterator[Finding]:
        from chiaswarm_tpu.analysis import shardflow

        for f in shardflow.results(index).findings:
            if f.rule == self.name:
                yield f


@register
class DonationDrift(ProjectRule):
    code = "R13"
    name = "donation-drift"
    description = ("a buffer donated to a jitted wrapper "
                   "(donate_argnums, wrapper resolved across modules) "
                   "is read after the call — XLA reused its memory")

    def check_project(self, index: "ProjectIndex") -> Iterator[Finding]:
        from chiaswarm_tpu.analysis import shardflow

        yield from shardflow.donation_findings(index)
