"""R3 compat-import: JAX API churn crosses through core/compat.py only.

The repo pins jax 0.4.37; JAX moves public surface between minors
(``shard_map`` graduated out of experimental, ``lax.axis_size`` did not
exist yet, ...). The seed paid for this twice: ``from jax import
shard_map`` in a test poisoned the whole tier-1 collection, and
``lax.axis_size`` broke every sequence-parallel path at runtime.

Policy, driven by the pinned table in ``chiaswarm_tpu/core/compat.py``:

- importing a symbol listed in ``COMPAT_TABLE`` (e.g. ``from jax import
  shard_map``, ``from jax.experimental.shard_map import shard_map``) is a
  finding anywhere outside compat.py — even inside try/except, because
  every hand-rolled fallback is one more site to migrate on the next pin
  bump;
- calling an attribute path listed there (``jax.lax.axis_size(...)``) is
  likewise a finding;
- any other ``jax.experimental.*`` import must be either in
  ``ALLOWED_EXPERIMENTAL`` or guarded by try/except ImportError — the
  experimental namespace carries no stability promise.
"""

from __future__ import annotations

import ast
import sys
from typing import Iterator

from chiaswarm_tpu.analysis.core import Finding, ModuleContext, Rule, register


def _load_compat():
    """The compat table, WITHOUT importing chiaswarm_tpu.core.

    ``chiaswarm_tpu/core/__init__.py`` imports jax; the linter must stay
    stdlib-only AND seconds-fast (it runs in CI jobs and hooks with no
    jax installed), so load compat.py directly by path — never through
    the package, which would drag in the whole jax runtime."""
    if "chiaswarm_tpu.core.compat" in sys.modules:
        return sys.modules["chiaswarm_tpu.core.compat"]
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "core", "compat.py")
    spec = importlib.util.spec_from_file_location(
        "_swarmlint_compat", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod  # dataclasses resolves __module__
    spec.loader.exec_module(mod)
    return mod


_COMPAT = _load_compat()
ALLOWED_EXPERIMENTAL = _COMPAT.ALLOWED_EXPERIMENTAL
COMPAT_TABLE = _COMPAT.COMPAT_TABLE

_EXEMPT_SUFFIX = "chiaswarm_tpu/core/compat.py"
_FORBIDDEN_CALLS = {key.replace(":", "."): entry
                    for key, entry in COMPAT_TABLE.items()}


def _experimental_allowed(module: str) -> bool:
    return any(module == allowed or module.startswith(allowed + ".")
               for allowed in ALLOWED_EXPERIMENTAL)


@register
class CompatImport(Rule):
    code = "R3"
    name = "compat-import"
    description = ("version-sensitive jax imports must route through "
                   "chiaswarm_tpu.core.compat (pinned jax "
                   "compatibility table)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.relpath.endswith(_EXEMPT_SUFFIX):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                yield from self._check_import_from(ctx, node)
            elif isinstance(node, ast.Import):
                yield from self._check_import(ctx, node)
            elif isinstance(node, ast.Call):
                resolved = ctx.resolve_call(node)
                if resolved in _FORBIDDEN_CALLS:
                    entry = _FORBIDDEN_CALLS[resolved]
                    yield self.finding(
                        ctx, node,
                        f"'{resolved}' is not available on the pinned jax "
                        f"{_pinned()}; use chiaswarm_tpu.core.compat."
                        f"{entry.symbol} ({entry.note})")

    def _check_import_from(self, ctx: ModuleContext,
                           node: ast.ImportFrom) -> Iterator[Finding]:
        module = node.module or ""
        for alias in node.names:
            key = f"{module}:{alias.name}"
            if key in COMPAT_TABLE:
                entry = COMPAT_TABLE[key]
                yield self.finding(
                    ctx, node,
                    f"'from {module} import {alias.name}' is version-"
                    f"sensitive (modern: {entry.modern}, pinned jax "
                    f"{_pinned()}: {entry.pinned}); import "
                    f"chiaswarm_tpu.core.compat.{entry.symbol} instead")
                continue
            if module.startswith("jax.experimental"):
                # `from jax.experimental import pallas` targets the
                # pallas SUBMODULE — judge the full dotted path
                yield from self._check_experimental(
                    ctx, node, f"{module}.{alias.name}")

    def _check_import(self, ctx: ModuleContext,
                      node: ast.Import) -> Iterator[Finding]:
        for alias in node.names:
            if alias.name.startswith("jax.experimental"):
                yield from self._check_experimental(ctx, node, alias.name)

    def _check_experimental(self, ctx: ModuleContext, node: ast.AST,
                            module: str) -> Iterator[Finding]:
        if _experimental_allowed(module):
            return
        if ctx.in_import_guard(node):
            return
        yield self.finding(
            ctx, node,
            f"unguarded '{module}' import: jax.experimental carries no "
            f"stability promise across the pin — wrap in try/except "
            f"ImportError, or add a shim to chiaswarm_tpu.core.compat "
            f"(allowed without a guard: "
            f"{', '.join(sorted(ALLOWED_EXPERIMENTAL))})")


def _pinned() -> str:
    return _COMPAT.PINNED_JAX
