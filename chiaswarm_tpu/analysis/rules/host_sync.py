"""R1 host-sync-in-jit: no host<->device synchronization reachable from
jitted or traced code.

``.item()``, ``float(jnp_value)``, ``np.asarray``, ``jax.device_get`` and
``block_until_ready`` each force a device->host transfer. Outside jit they
merely serialize the async dispatch queue (bad enough in the denoise
loop); *inside* jit/scan/vmap they fail at trace time or, worse, silently
fall back to recompile-per-value patterns. The reference never cared —
CUDA sync is cheap relative to its Python overhead; on TPU a single sync
in the per-step path stalls the ICI pipeline.

Reachability is intra-module: a function is "jit-reachable" when it is
decorated with / passed to a jit or tracing wrapper, or is called (by
simple name or ``self.method``) from a reachable function in the same
file. Cross-module reachability is out of scope — module boundaries in
this repo coincide with the host/device split (pipelines postprocess on
host), so per-file analysis matches the architecture.

Host-callback escapes (``jax.pure_callback``/``io_callback``/
``jax.debug.*``) are exempt: their bodies run on host by design.

Sanctioned-sync allowlist (swarmlens, ISSUE 11): a sync site whose
source line — or whose immediately preceding comment line — carries the
marker ``swarmlens: allow-host-sync`` is skipped by BOTH R1 and R9 (the
rules share :func:`sync_sites`, so they cannot disagree). The marker
exists for the numerics flight recorder's host-side callback bodies:
an ``io_callback`` tap's receiver legitimately converts its tiny
summary payload on host, and without the marker every sanctioned tap
would become permanent baseline noise. Use it ONLY for code that runs
on host by design; the marker is grep-able precisely so reviews can
audit every use.
"""

from __future__ import annotations

import ast
from typing import Iterator

from chiaswarm_tpu.analysis.core import (
    Finding, FunctionInfo, ModuleContext, Rule, register,
)
from chiaswarm_tpu.analysis.rules import (
    CALLBACK_WRAPPERS, JIT_WRAPPERS, TRACED_WRAPPERS, own_nodes,
    resolves_to,
)

_SYNC_CALLS = ("jax.device_get", "jax.block_until_ready",
               "numpy.asarray", "numpy.array", "numpy.copy")
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})

#: sanctioned-host-sync marker (swarmlens taps): on the sync line or the
#: comment line directly above it
ALLOW_MARKER = "swarmlens: allow-host-sync"


def _allowed_lines(ctx: ModuleContext) -> set[int]:
    """1-based line numbers whose sync sites are sanctioned: marker on
    the line itself, or on a standalone comment line directly above
    (the marker then covers the next code line)."""
    allowed: set[int] = set()
    lines = ctx.source.splitlines()
    for i, text in enumerate(lines, start=1):
        if ALLOW_MARKER not in text:
            continue
        allowed.add(i)
        if text.lstrip().startswith("#"):
            allowed.add(i + 1)
    return allowed


@register
class HostSyncInJit(Rule):
    code = "R1"
    name = "host-sync-in-jit"
    description = ("no .item()/float()/np.asarray/device_get/"
                   "block_until_ready reachable from jitted/traced code")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        roots = _jit_roots(ctx)
        if not roots:
            return
        reachable = _reachable(ctx, roots)
        seen: set[tuple[int, int]] = set()
        for info in reachable:
            for node, what in sync_sites(ctx, info):
                loc = (node.lineno, node.col_offset)
                if loc in seen:
                    continue
                seen.add(loc)
                yield self.finding(
                    ctx, node,
                    f"host sync {what} is reachable from jitted/traced "
                    f"code; hoist it outside the compiled region (or use "
                    f"jax.pure_callback if the host round-trip is "
                    f"intentional)")


def _jit_roots(ctx: ModuleContext) -> set[FunctionInfo]:
    """Functions directly entering trace: decorated with, or passed to,
    a jit/tracing wrapper."""
    wrappers = JIT_WRAPPERS + TRACED_WRAPPERS
    roots: set[FunctionInfo] = set()
    by_name: dict[str, list[FunctionInfo]] = {}
    for info in ctx.functions:
        if isinstance(info.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(info.node.name, []).append(info)
            for dec in info.node.decorator_list:
                if resolves_to(ctx.callable_target(dec), *wrappers):
                    roots.add(info)
    by_node = {info.node: info for info in ctx.functions}

    for call in ast.walk(ctx.tree):
        if not isinstance(call, ast.Call):
            continue
        if not resolves_to(ctx.resolve_call(call), *wrappers):
            continue
        args = list(call.args) + [kw.value for kw in call.keywords]
        for arg in args:
            if isinstance(arg, ast.Lambda) and arg in by_node:
                roots.add(by_node[arg])
            elif isinstance(arg, ast.Name):
                roots.update(by_name.get(arg.id, []))
            elif isinstance(arg, ast.Attribute):  # self._step, cls.body
                roots.update(by_name.get(arg.attr, []))
    return roots


def _callees(info: FunctionInfo) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(info.node):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name):
                out.add(node.func.id)
            elif isinstance(node.func, ast.Attribute) and isinstance(
                    node.func.value, ast.Name) and node.func.value.id in (
                    "self", "cls"):
                out.add(node.func.attr)
    return out


def _reachable(ctx: ModuleContext,
               roots: set[FunctionInfo]) -> set[FunctionInfo]:
    by_name: dict[str, list[FunctionInfo]] = {}
    for info in ctx.functions:
        if isinstance(info.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(info.node.name, []).append(info)
    seen = set(roots)
    frontier = list(roots)
    while frontier:
        info = frontier.pop()
        for name in _callees(info):
            for callee in by_name.get(name, []):
                if callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
    return seen


def _in_callback(ctx: ModuleContext, node: ast.AST) -> bool:
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Call) and resolves_to(
                ctx.resolve_call(cur), *CALLBACK_WRAPPERS):
            return True
        cur = ctx.parents.get(cur)
    return False


_ARRAY_REDUCERS = frozenset({"sum", "mean", "max", "min", "all", "any",
                             "prod", "std", "var", "argmax", "argmin"})


def _is_array_expr(ctx: ModuleContext, node: ast.AST,
                   array_names: set[str]) -> bool:
    if isinstance(node, ast.Name):
        return node.id in array_names
    if isinstance(node, ast.Call):
        inner = ctx.resolve_call(node)
        if inner and (inner.startswith("jax.numpy.")
                      or inner.startswith("jax.lax.")):
            return True
        return (isinstance(node.func, ast.Attribute)
                and node.func.attr in _ARRAY_REDUCERS)
    return False


def _local_array_names(ctx: ModuleContext, info: FunctionInfo) -> set[str]:
    """Names assigned from an obviously-array expression in this function
    (one dataflow hop: enough for the `loss = x.sum(); float(loss)`
    pattern)."""
    names: set[str] = set()
    for _ in range(2):  # second pass resolves name-to-name chains
        for node in own_nodes(info.node):
            if isinstance(node, ast.Assign) and _is_array_expr(
                    ctx, node.value, names):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def sync_sites(ctx: ModuleContext, info: FunctionInfo):
    """Host-forcing operations in one function (shared with R9: the
    project-level reachability pass taints the same sites, so the two
    rules can never disagree on what counts as a sync — including the
    sanctioned-tap allowlist marker, honored here for both)."""
    array_names = _local_array_names(ctx, info)
    allowed = _allowed_lines(ctx)
    for node in own_nodes(info.node):
        if not isinstance(node, ast.Call):
            continue
        if node.lineno in allowed:
            continue
        if _in_callback(ctx, node):
            continue
        resolved = ctx.resolve_call(node)
        # exact match: suffix matching would catch device-side
        # jax.numpy.asarray with the host numpy.asarray pattern
        if resolved in _SYNC_CALLS:
            yield node, f"'{resolved}'"
            continue
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS
                and not node.args and not node.keywords):
            yield node, f"'.{node.func.attr}()'"
            continue
        # float(jnp.sum(x)) / int(x.mean()) / float(loss) where loss was
        # assigned from an array expression — definite array-to-scalar
        if (isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and len(node.args) == 1
                and _is_array_expr(ctx, node.args[0], array_names)):
            yield node, f"'{node.func.id}()' on an array expression"
