"""R14–R17 — the raceflow concurrency rules (swarmrace).

R1–R13 prove what the *values* do; these four prove what the *threads*
do, via the thread-topology + lock-discipline interpreter in
``analysis/raceflow.py`` (see its module docstring for the domain):

- **R14 cross-thread-device-handoff** — an in-flight device value
  (produced by a jit/lane dispatch) published to shared state one root
  writes and another consumes, with no ``block_until_ready``/``.copy()``
  on the producing path: PR 3's two container hazards as lint findings.
- **R15 unguarded-shared-mutation** — mostly-locked state mutated
  lock-free on a concurrent root's path (the PR-10 fired-vs-condemn
  shape), RacerD-style.
- **R16 lock-order-inversion** — ABBA cycles in the lock-order graph
  across concurrent roots.
- **R17 await-or-blocking-under-lock** — a ``threading`` lock held
  across ``await``, or ``time.sleep``/socket I/O on the event loop.

All four are conservative: single-rooted programs, unresolvable spawn
targets and unknown locks are silent — a lint must not invent a thread
topology it cannot defend.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator

from chiaswarm_tpu.analysis.core import Finding, ProjectRule, register

if TYPE_CHECKING:  # the index arrives at check time; no runtime dep
    from chiaswarm_tpu.analysis.project import ProjectIndex


class _RaceflowRule(ProjectRule):
    def check_project(self, index: "ProjectIndex") -> Iterator[Finding]:
        from chiaswarm_tpu.analysis import raceflow

        for f in raceflow.results(index).findings:
            if f.rule == self.name:
                yield f


@register
class CrossThreadDeviceHandoff(_RaceflowRule):
    code = "R14"
    name = "cross-thread-device-handoff"
    description = ("a device value still in flight is published to "
                   "shared state consumed on another execution root — "
                   "sync (block_until_ready/.copy()) before publishing")


@register
class UnguardedSharedMutation(_RaceflowRule):
    code = "R15"
    name = "unguarded-shared-mutation"
    description = ("state written under a lock on some paths but "
                   "mutated lock-free on a concurrent root's path "
                   "(mostly-locked inference)")


@register
class LockOrderInversion(_RaceflowRule):
    code = "R16"
    name = "lock-order-inversion"
    description = ("two locks taken in opposite orders on concurrent "
                   "roots (ABBA) — a deadlock waiting for load")


@register
class AwaitOrBlockingUnderLock(_RaceflowRule):
    code = "R17"
    name = "await-or-blocking-under-lock"
    description = ("a threading lock held across await, or blocking "
                   "sleep/IO inside a coroutine — parks the event loop "
                   "(and everyone contending for the lock)")
