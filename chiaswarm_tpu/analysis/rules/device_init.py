"""R4 import-time-device-init: no backend initialization at module scope.

``jax.devices()`` / ``jax.device_count()`` / ``jax.default_backend()``
at import time pins the backend before the process has a chance to set
``JAX_PLATFORMS`` / distributed init — exactly the failure mode
``tests/conftest.py`` works around for the container's TPU-plugin
sitecustomize. It also makes ``import chiaswarm_tpu.x`` require working
accelerator plumbing, which breaks host-only tools and the import-health
test.

Module scope means anything executed at import: module body, class
bodies, decorator expressions, and default-argument values. Function and
lambda bodies only run when called and are fine.
"""

from __future__ import annotations

import ast
from typing import Iterator

from chiaswarm_tpu.analysis.core import Finding, ModuleContext, Rule, register
from chiaswarm_tpu.analysis.rules import resolves_to

_DEVICE_INIT = (
    "jax.devices",
    "jax.local_devices",
    "jax.device_count",
    "jax.local_device_count",
    "jax.default_backend",
    "jax.process_index",
    "jax.process_count",
    "jax.extend.backend.get_backend",
)


@register
class ImportTimeDeviceInit(Rule):
    code = "R4"
    name = "import-time-device-init"
    description = ("jax.devices()/device_count()/default_backend() must "
                   "not run at module import time")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        yield from self._visit(ctx, ctx.tree)

    def _visit(self, ctx: ModuleContext, node: ast.AST) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # decorators and default values DO execute at import
                for dec in child.decorator_list:
                    yield from self._scan_expr(ctx, dec)
                for default in (child.args.defaults
                                + [d for d in child.args.kw_defaults if d]):
                    yield from self._scan_expr(ctx, default)
                continue  # body runs at call time
            if isinstance(child, ast.Lambda):
                # the body runs at call time, but default values of a
                # module-scope lambda execute at import like a def's
                for default in (child.args.defaults
                                + [d for d in child.args.kw_defaults if d]):
                    yield from self._scan_expr(ctx, default)
                continue
            yield from self._visit(ctx, child)
            if isinstance(child, ast.Call):
                yield from self._check_call(ctx, child)

    def _scan_expr(self, ctx: ModuleContext,
                   expr: ast.AST) -> Iterator[Finding]:
        # manual walk: ast.walk would descend into Lambda bodies, which
        # do NOT execute at import time
        todo = [expr]
        while todo:
            node = todo.pop()
            if isinstance(node, ast.Lambda):
                continue
            if isinstance(node, ast.Call):
                yield from self._check_call(ctx, node)
            todo.extend(ast.iter_child_nodes(node))

    def _check_call(self, ctx: ModuleContext,
                    call: ast.Call) -> Iterator[Finding]:
        resolved = ctx.resolve_call(call)
        if resolves_to(resolved, *_DEVICE_INIT):
            yield self.finding(
                ctx, call,
                f"'{resolved}()' at module scope initializes the jax "
                f"backend at import time; defer it into the function that "
                f"needs it so JAX_PLATFORMS / distributed init still win")
