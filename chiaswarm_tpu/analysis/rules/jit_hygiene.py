"""R5 jit-hygiene: serving-path jits go through compile_cache.toplevel_jit
and never donate the cache-resident param tree.

Two checks:

1. In the top-level program layer (``chiaswarm_tpu/pipelines/``,
   ``chiaswarm_tpu/workloads/``), a raw ``jax.jit`` call/decorator is a
   finding: the sanctioned wrapper is
   ``compile_cache.toplevel_jit``, which applies the operator's
   ``CHIASWARM_XLA_OPTIONS`` compiler options (scoped-VMEM budget etc.) to
   exactly the top-level executables — raw jax.jit silently drops them.
   Exempt: one-shot parameter initialization (``jax.jit(module.init)`` or
   a lambda that calls ``.init``) — init executables are built once per
   model load, never sit in the serving loop, and MUST NOT carry
   production compiler options tuned for the denoise path.

2. Anywhere: ``donate_argnums``/``donate_argnames`` pointing at a
   parameter named ``params`` is a finding. The issue text asks for the
   opposite polarity ("missing donate_argnums on param-tree args"), but in
   this architecture param trees are *resident* in CompileCache across
   jobs — donating them hands their buffers to XLA and invalidates the
   cached tree after the first call. What SHOULD be donated (per-call
   latents/noise buffers) cannot be identified reliably by name, so the
   rule enforces the invariant that is always true here: never donate
   ``params``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from chiaswarm_tpu.analysis.core import Finding, ModuleContext, Rule, register
from chiaswarm_tpu.analysis.rules import resolves_to

_TOPLEVEL_PACKAGES = ("chiaswarm_tpu/pipelines/", "chiaswarm_tpu/workloads/")
_RAW_JIT = ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")
_ANY_JIT = _RAW_JIT + ("compile_cache.toplevel_jit", "toplevel_jit")


def _is_init_target(node: ast.AST | None) -> bool:
    """True for one-shot init jits: ``jax.jit(mod.init)`` or
    ``jax.jit(lambda k: mod.init(k, ...))`` / eval_shape plumbing."""
    if node is None:
        return False
    if isinstance(node, ast.Attribute) and node.attr in ("init",
                                                         "init_with_output"):
        return True
    if isinstance(node, ast.Lambda):
        for sub in ast.walk(node.body):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in ("init", "init_with_output")):
                return True
    return False


@register
class JitHygiene(Rule):
    code = "R5"
    name = "jit-hygiene"
    description = ("serving-path jits use compile_cache.toplevel_jit "
                   "(CHIASWARM_XLA_OPTIONS) and never donate the resident "
                   "param tree")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        in_toplevel = any(p in ctx.relpath for p in _TOPLEVEL_PACKAGES)
        # decorators are reported via _check_decorated; skip their Call
        # nodes in the generic walk so they are not double-flagged
        decorator_calls: set[int] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                decorator_calls.update(
                    id(d) for d in node.decorator_list
                    if isinstance(d, ast.Call))
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_decorated(ctx, node, in_toplevel)
            elif isinstance(node, ast.Call) \
                    and id(node) not in decorator_calls:
                yield from self._check_call(ctx, node, in_toplevel)

    # ---- raw jax.jit in the program layer --------------------------------
    def _check_call(self, ctx: ModuleContext, call: ast.Call,
                    in_toplevel: bool) -> Iterator[Finding]:
        # callable_target unwraps partial(jax.jit, ...) so the curried
        # spelling cannot smuggle a raw jit past the rule
        resolved = ctx.callable_target(call)
        if in_toplevel and resolves_to(resolved, *_RAW_JIT):
            target = call.args[0] if call.args else None
            if not _is_init_target(target):
                yield self.finding(
                    ctx, call,
                    "raw jax.jit in the top-level program layer bypasses "
                    "compile_cache.toplevel_jit — CHIASWARM_XLA_OPTIONS "
                    "compiler options (scoped-VMEM budget, ...) will not "
                    "apply to this executable")
        if resolves_to(resolved, *_ANY_JIT):
            yield from self._check_donate(ctx, call)

    def _check_decorated(self, ctx: ModuleContext, fn: ast.FunctionDef,
                         in_toplevel: bool) -> Iterator[Finding]:
        for dec in fn.decorator_list:
            target = ctx.callable_target(dec)
            if not resolves_to(target, *_ANY_JIT):
                continue
            if in_toplevel and resolves_to(target, *_RAW_JIT):
                yield self.finding(
                    ctx, dec,
                    f"raw @jax.jit on '{fn.name}' in the top-level program "
                    f"layer bypasses compile_cache.toplevel_jit — "
                    f"CHIASWARM_XLA_OPTIONS compiler options will not "
                    f"apply to this executable")
            if isinstance(dec, ast.Call):
                yield from self._check_donate(ctx, dec, fn)

    # ---- donated resident params -----------------------------------------
    def _check_donate(self, ctx: ModuleContext, call: ast.Call,
                      fn: ast.FunctionDef | None = None) -> Iterator[Finding]:
        donated_names: set[str] = set()
        donate_nums: list[int] = []
        for kw in call.keywords:
            if kw.arg == "donate_argnames":
                donated_names.update(_str_elems(kw.value))
            elif kw.arg == "donate_argnums":
                donate_nums.extend(_int_elems(kw.value))
        if donate_nums:
            params = _positional_params(fn) if fn is not None else \
                _positional_params(_local_def(ctx, call))
            for i in donate_nums:
                if params and 0 <= i < len(params):
                    donated_names.add(params[i])
        if "params" in donated_names:
            yield self.finding(
                ctx, call,
                "donate_argnums/donate_argnames donates 'params': the "
                "param tree is resident in CompileCache across jobs — "
                "donation hands its buffers to XLA and corrupts the "
                "cached tree after the first call")


def _local_def(ctx: ModuleContext,
               call: ast.Call) -> ast.FunctionDef | None:
    """Resolve ``jax.jit(fn, ...)``'s first arg to a module-local def."""
    if not call.args or not isinstance(call.args[0], ast.Name):
        return None
    name = call.args[0].id
    for info in ctx.functions:
        node = info.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _positional_params(fn) -> list[str]:
    if fn is None:
        return []
    args = fn.args
    return [a.arg for a in (args.posonlyargs + args.args)]


def _str_elems(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_str_elems(e))
        return out
    return []


def _int_elems(node: ast.AST) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_int_elems(e))
        return out
    return []
