"""Rule registry: importing this package registers every rule.

Shared vocabulary for "things that trace/compile" lives here so R1/R5/R6
agree on what counts as entering XLA.
"""

from __future__ import annotations

#: Callables that produce a compiled/traced callable from a function.
JIT_WRAPPERS = (
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
    "compile_cache.toplevel_jit",
    "toplevel_jit",
)

#: Callables whose function arguments are traced (host syncs inside them
#: fail at trace time even without an explicit jit).
TRACED_WRAPPERS = (
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.map",
    "jax.lax.associative_scan",
    "jax.vmap",
    "jax.pmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.named_call",
)

#: Host-callback escapes: code inside these legitimately runs on host.
CALLBACK_WRAPPERS = (
    "jax.pure_callback",
    "jax.experimental.io_callback",
    "jax.debug.callback",
    "jax.debug.print",
)


import ast

#: nodes that open a new function scope
FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def resolves_to(path: str | None, *targets: str) -> bool:
    """Suffix-tolerant dotted-path match, so relative imports
    (``from .compile_cache import toplevel_jit``) still resolve."""
    if not path:
        return False
    return any(path == t or path.endswith("." + t) for t in targets)


def own_nodes(func_node: ast.AST):
    """Walk a function's own subtree, stopping at nested functions —
    they are separate scopes (and separate call-graph entries)."""
    todo = (list(func_node.body)
            if isinstance(func_node, (ast.FunctionDef, ast.AsyncFunctionDef))
            else [func_node.body])  # Lambda body is one expression
    while todo:
        node = todo.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FUNC_NODES):
                continue
            todo.append(child)


from chiaswarm_tpu.analysis.rules import (  # noqa: E402,F401  (registration)
    compat_imports,
    device_init,
    host_sync,
    jit_hygiene,
    keyflow_rules,
    prng,
    raceflow_rules,
    recompile,
    scan_carry,
    sharding_drift,
    shardflow_rules,
    sync_reach,
    wallclock,
)
