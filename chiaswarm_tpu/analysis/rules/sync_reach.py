"""R9 host-sync-reachability: R1 across module boundaries.

R1's reachability stops at the file edge — a jitted function that calls a
helper in another module which does ``.item()`` is invisible to it (the
rule's docstring even says so). R9 closes the gap over the swarmflow
project index: taint every host-forcing operation (the exact
``sync_sites`` vocabulary R1 uses, so the two rules can never disagree),
then walk the whole-program call graph from every function that enters
trace (``toplevel_jit``/``jax.jit`` decorations and registrations, scan/
vmap bodies — the lane executables included) and report any tainted
function it reaches.

Findings carry the full call chain (entry point -> ... -> sink) as
:attr:`Finding.chain` evidence, rendered in text and JSON, so a
cross-module report is actionable without re-deriving the path by hand.

Division of labor with R1: chains that stay inside one module are R1's
jurisdiction (it additionally understands callback escapes and local
array dataflow at the root site) — R9 only reports chains that cross at
least one module boundary, so the two rules never double-report a site.
"""

from __future__ import annotations

from typing import Iterator

from chiaswarm_tpu.analysis.core import Finding, ProjectRule, register
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # the index arrives at check time; no runtime dep
    from chiaswarm_tpu.analysis.project import ProjectIndex


@register
class HostSyncReachability(ProjectRule):
    code = "R9"
    name = "host-sync-reachability"
    description = ("no host sync reachable from jitted/traced code through "
                   "cross-module call chains (whole-program call graph)")

    def check_project(self, index: "ProjectIndex") -> Iterator[Finding]:
        roots = index.jit_entry_points()
        if not roots:
            return
        parent = index.reach_with_parents(roots)
        seen: set[tuple[str, int, int]] = set()
        for node in sorted(parent):
            func = index.funcs[node]
            if not func["sync"]:
                continue
            # walk back to the root to find the modules on the path
            path_nodes = [node]
            while parent.get(path_nodes[-1]) is not None:
                path_nodes.append(parent[path_nodes[-1]])
            chain_modules = {m for m, _ in path_nodes}
            root_node = path_nodes[-1]
            regs = roots.get(root_node, [])
            reg_modules = {r["module"] for r in regs}
            if len(chain_modules) == 1 and \
                    next(iter(chain_modules)) in reg_modules:
                # chain AND registration in one file: R1's jurisdiction
                continue
            chain = index.chain(parent, node)
            root = chain[0][2]
            if chain_modules == {root_node[0]} and regs:
                # single-module chain rooted at a body REGISTERED from
                # another module: the registration site IS the missing
                # cross-module hop — prepend it so the evidence (and the
                # --changed-only chain filter) sees the registering file
                reg = next((r for r in regs
                            if r["module"] != root_node[0]), regs[0])
                chain = ((reg["relpath"], reg["line"],
                          f"{reg['module']}.{reg['symbol']}"),) + chain
            rel = index.modules[node[0]]
            for site in func["sync"]:
                key = (rel, site["line"], site["col"])
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    rule=self.name,
                    path=rel,
                    line=site["line"],
                    col=site["col"],
                    message=(f"host sync {site['what']} is reachable from "
                             f"jit-traced '{root}' through a cross-module "
                             f"call chain; hoist it out of the compiled "
                             f"region (or use jax.pure_callback if the "
                             f"host round-trip is intentional)"),
                    symbol=node[1],
                    chain=chain,
                )
