"""Visitor framework for swarmlint: findings, rule registry, module context.

Everything here is pure stdlib ``ast``. A :class:`ModuleContext` is built
once per file and shared by all rules; it pre-computes the things every
TPU-invariant rule needs — import-alias resolution (so ``jnp.zeros`` and
``jax.numpy.zeros`` look the same to a rule), a parent map, the function
table with qualnames, and try/except-guard detection for imports.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Callable, Iterable, Iterator


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``symbol`` is the enclosing function's qualname (or ``<module>``): the
    baseline matches on (rule, path, symbol, message) — NOT on line
    numbers — so grandfathered findings survive unrelated edits to the
    same file.

    ``chain`` is interprocedural evidence (R9/R10): (path, line, qualname)
    hops from the entry point to the sink, rendered in text and carried in
    JSON/SARIF output. It is deliberately NOT part of the baseline key —
    an unrelated edit that reroutes an intermediate hop must not resurface
    a grandfathered finding.
    """

    rule: str
    path: str
    line: int
    col: int
    message: str
    symbol: str = "<module>"
    chain: tuple[tuple[str, int, str], ...] = ()

    @property
    def baseline_key(self) -> str:
        return f"{self.rule}::{self.path}::{self.symbol}::{self.message}"

    def render(self) -> str:
        text = (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message} (in {self.symbol})")
        if self.chain:
            hops = " -> ".join(f"{qual} ({path}:{line})"
                               for path, line, qual in self.chain)
            text += f"\n    chain: {hops}"
        return text


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass(eq=False)  # identity semantics: usable in sets
class FunctionInfo:
    node: ast.AST                 # FunctionDef / AsyncFunctionDef / Lambda
    qualname: str
    parent: "FunctionInfo | None"


class ModuleContext:
    """Per-file facts shared by every rule."""

    def __init__(self, relpath: str, source: str, tree: ast.Module):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tree = tree
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.imports = self._collect_imports(tree)
        self.functions = self._collect_functions(tree)
        self._func_by_node = {f.node: f for f in self.functions}

    # ---- imports ---------------------------------------------------------
    @staticmethod
    def _collect_imports(tree: ast.Module) -> dict[str, str]:
        aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        aliases[a.asname] = a.name
                    else:
                        top = a.name.split(".", 1)[0]
                        aliases[top] = top
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    # ---- functions -------------------------------------------------------
    def _collect_functions(self, tree: ast.Module) -> list[FunctionInfo]:
        out: list[FunctionInfo] = []
        # lambdas are numbered by order of appearance within their scope,
        # NOT by line number: baseline keys embed the qualname and must
        # survive unrelated edits that shift lines
        counters: dict[str, int] = {}

        def visit(node: ast.AST, prefix: str, parent: FunctionInfo | None):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_NODES):
                    qn = f"{prefix}{child.name}"
                    info = FunctionInfo(child, qn, parent)
                    out.append(info)
                    visit(child, qn + ".", info)
                elif isinstance(child, ast.Lambda):
                    counters[prefix] = counters.get(prefix, 0) + 1
                    qn = f"{prefix}<lambda#{counters[prefix]}>"
                    info = FunctionInfo(child, qn, parent)
                    out.append(info)
                    visit(child, qn + ".", info)
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}{child.name}.", parent)
                else:
                    visit(child, prefix, parent)

        visit(tree, "", None)
        return out

    def enclosing_function(self, node: ast.AST) -> FunctionInfo | None:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, _FUNC_NODES + (ast.Lambda,)):
                return self._func_by_node.get(cur)
            cur = self.parents.get(cur)
        return None

    def symbol_for(self, node: ast.AST) -> str:
        info = self.enclosing_function(node)
        return info.qualname if info else "<module>"

    # ---- name resolution -------------------------------------------------
    def resolve(self, node: ast.AST) -> str | None:
        """Dotted path of a Name/Attribute chain, import aliases applied.

        ``jnp.zeros`` -> ``jax.numpy.zeros`` when the module did
        ``import jax.numpy as jnp``; plain locals resolve to their bare
        name (``float(...)`` -> ``float``).
        """
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.imports.get(node.id, node.id)
        parts.append(base)
        return ".".join(reversed(parts))

    def resolve_call(self, call: ast.Call) -> str | None:
        return self.resolve(call.func)

    def callable_target(self, node: ast.AST) -> str | None:
        """Resolve a node used as a callable, unwrapping functools.partial:
        ``partial(jax.jit, static_argnums=1)`` resolves to ``jax.jit``."""
        if isinstance(node, ast.Call):
            fn = self.resolve(node.func)
            if fn in ("functools.partial", "partial") and node.args:
                return self.callable_target(node.args[0])
            return fn
        return self.resolve(node)

    def in_import_guard(self, node: ast.AST) -> bool:
        """True when ``node`` sits in the body of a ``try`` that catches
        ImportError/ModuleNotFoundError/Exception — the sanctioned pattern
        for feature-probing an API that may be absent on some jax."""
        cur = self.parents.get(node)
        prev = node
        while cur is not None:
            if isinstance(cur, ast.Try) and prev in cur.body:
                for handler in cur.handlers:
                    names = _handler_names(handler)
                    if names & {"ImportError", "ModuleNotFoundError",
                                "Exception", "AttributeError"}:
                        return True
            prev, cur = cur, self.parents.get(cur)
        return False

    def line(self, node: ast.AST) -> int:
        return getattr(node, "lineno", 0)


def _handler_names(handler: ast.ExceptHandler) -> set[str]:
    t = handler.type
    if t is None:  # bare `except:`
        return {"Exception"}
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    out = set()
    for n in nodes:
        if isinstance(n, ast.Attribute):  # builtins.ImportError
            out.add(n.attr)
        elif isinstance(n, ast.Name):
            out.add(n.id)
    return out


class Rule:
    """Base class: subclasses set ``code``/``name``/``description`` and
    implement :meth:`check`, yielding findings for one module."""

    code: str = ""
    name: str = ""
    description: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST,
                message: str) -> Finding:
        return Finding(
            rule=self.name,
            path=ctx.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
            symbol=ctx.symbol_for(node),
        )


class ProjectRule(Rule):
    """A whole-program rule: sees the :class:`~.project.ProjectIndex`
    (module graph, symbol resolution, call graph) instead of one module.

    Per-module ``check`` is a no-op; the driver calls
    :meth:`check_project` exactly once per run with the index built over
    every linted file.
    """

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        return iter(())

    def check_project(self, index) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError


def _rule_order(rule: Rule) -> tuple:
    # numeric by code (R2 before R10); string codes sort after
    tail = rule.code[1:]
    return ((0, int(tail)) if tail.isdigit() else (1, 0), rule.code)


_REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    inst = cls()
    assert inst.name and inst.code, cls
    _REGISTRY[inst.name] = inst
    return cls


def _ensure_rules_loaded() -> None:
    from chiaswarm_tpu.analysis import rules  # noqa: F401  (registers all)


def all_rules() -> list[Rule]:
    _ensure_rules_loaded()
    return sorted(_REGISTRY.values(), key=_rule_order)


def get_rule(name: str) -> Rule:
    _ensure_rules_loaded()
    if name in _REGISTRY:
        return _REGISTRY[name]
    by_code = {r.code: r for r in _REGISTRY.values()}
    if name in by_code:
        return by_code[name]
    raise KeyError(f"unknown rule {name!r}; have "
                   f"{sorted(_REGISTRY)} / {sorted(by_code)}")


# ---- drivers -------------------------------------------------------------

def analyze_source(source: str, relpath: str = "<string>.py",
                   rules: Iterable[Rule] | None = None) -> list[Finding]:
    tree = ast.parse(source, filename=relpath)
    ctx = ModuleContext(relpath, source, tree)
    findings: list[Finding] = []
    project_rules: list[ProjectRule] = []
    for rule in (rules if rules is not None else all_rules()):
        if isinstance(rule, ProjectRule):
            project_rules.append(rule)
        else:
            findings.extend(rule.check(ctx))
    if project_rules:
        # a one-module "project": lets rule-fixture tests feed project
        # rules the same way they feed per-file rules
        from chiaswarm_tpu.analysis.project import ProjectIndex

        index = ProjectIndex.from_sources([(relpath, source, tree)])
        for rule in project_rules:
            findings.extend(rule.check_project(index))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(paths: Iterable[str],
                      root: str | None = None) -> Iterator[tuple[str, str]]:
    """Yield (abspath, root-relative posix path) for every .py under paths."""
    root = os.path.abspath(root or os.getcwd())
    seen: set[str] = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            files = [p]
        else:
            files = []
            for dirpath, dirnames, filenames in os.walk(p):
                # prune caches, dot-dirs (.venv/.git/...), vendor trees
                # (foreign code is neither ours to lint nor safe to
                # parse) and test-fixture trees — fixture packages under
                # tests/fixtures/ are deliberately-violating inputs the
                # analysis tests copy out and lint hermetically
                dirnames[:] = [d for d in dirnames
                               if not d.startswith(".")
                               and d not in ("__pycache__", "node_modules",
                                             "venv", "site-packages",
                                             "fixtures")]
                files.extend(os.path.join(dirpath, fn)
                             for fn in filenames if fn.endswith(".py"))
            files.sort()
        for f in files:
            if f in seen:
                continue
            seen.add(f)
            rel = os.path.relpath(f, root).replace(os.sep, "/")
            yield f, rel


def analyze_paths(paths: Iterable[str],
                  rules: Iterable[Rule] | None = None,
                  root: str | None = None,
                  on_error: Callable[[str, Exception], None] | None = None,
                  only_files: set[str] | None = None,
                  ) -> list[Finding]:
    """Run per-file rules over every .py under ``paths``.

    ``only_files`` (absolute paths) restricts which files are actually
    linted WITHOUT relaxing path validation — the ``--changed-only``
    fast path uses it so a typo'd path still fails loudly."""
    rules = list(rules if rules is not None else all_rules())
    findings: list[Finding] = []
    rootdir = os.path.abspath(root or os.getcwd())

    def err(rel: str, exc: Exception) -> None:
        if on_error is not None:
            on_error(rel, exc)
        else:
            raise exc

    seen: set[str] = set()
    for p in paths:
        ap = os.path.abspath(p)
        rel0 = os.path.relpath(ap, rootdir).replace(os.sep, "/")
        if not os.path.exists(ap):
            # a typo'd path must FAIL the run, not lint nothing and pass
            err(rel0, FileNotFoundError("path does not exist"))
            continue
        count = 0
        for abspath, rel in iter_python_files([ap], root=rootdir):
            # count BEFORE dedup: a path fully covered by an earlier
            # overlapping argument is not an empty path
            count += 1
            if abspath in seen:
                continue
            seen.add(abspath)
            if only_files is not None and abspath not in only_files:
                continue
            try:
                with open(abspath, "r", encoding="utf-8") as fh:
                    source = fh.read()
                findings.extend(analyze_source(source, rel, rules))
            except (SyntaxError, UnicodeDecodeError, OSError) as exc:
                err(rel, exc)
        if count == 0:
            err(rel0, ValueError("no Python files found under path"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
