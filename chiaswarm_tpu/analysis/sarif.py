"""SARIF 2.1.0 output: swarmlint findings as GitHub code-scanning input.

One run, one tool ("swarmlint"), results from the NEW (non-baselined)
findings only — grandfathered entries are suppressions, not PR
annotations. Interprocedural findings (R9/R10) export their ``chain`` as
a SARIF codeFlow so the caller -> ... -> sink path renders inline in the
code-scanning UI. ``partialFingerprints`` carries the baseline key, which
is line-number-free by construction — GitHub's alert dedup then survives
unrelated edits exactly like the baseline does.
"""

from __future__ import annotations

from typing import Iterable

from chiaswarm_tpu.analysis.core import Finding, Rule

SARIF_VERSION = "2.1.0"
_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
           "Schemata/sarif-schema-2.1.0.json")


def _location(path: str, line: int, col: int, message: str | None = None):
    loc = {
        "physicalLocation": {
            "artifactLocation": {"uri": path,
                                 "uriBaseId": "%SRCROOT%"},
            "region": {"startLine": max(1, line),
                       "startColumn": max(1, col + 1)},
        },
    }
    if message:
        loc["message"] = {"text": message}
    return loc


def _code_flow(finding: Finding) -> dict:
    return {
        "threadFlows": [{
            "locations": [
                {"location": _location(path, line, 0, qual)}
                for path, line, qual in finding.chain
            ],
        }],
    }


def to_sarif(findings: Iterable[Finding], rules: Iterable[Rule]) -> dict:
    """The SARIF document (a JSON-able dict) for one lint run."""
    rule_list = sorted({r.name: r for r in rules}.values(),
                       key=lambda r: r.code)
    rule_index = {r.name: i for i, r in enumerate(rule_list)}
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "ruleIndex": rule_index.get(f.rule, -1),
            "level": "error",
            "message": {"text": f.message},
            "locations": [_location(f.path, f.line, f.col)],
            "partialFingerprints": {
                "swarmlintBaselineKey/v1": f.baseline_key,
            },
        }
        if f.chain:
            result["codeFlows"] = [_code_flow(f)]
        results.append(result)
    return {
        "$schema": _SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "swarmlint",
                    "informationUri":
                        "https://github.com/Jsewill/chiaSWARM",
                    "rules": [
                        {
                            "id": r.name,
                            "name": r.code,
                            "shortDescription": {"text": r.description},
                            "defaultConfiguration": {"level": "error"},
                        }
                        for r in rule_list
                    ],
                },
            },
            "results": results,
        }],
    }
