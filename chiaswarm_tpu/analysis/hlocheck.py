"""hlocheck — compiled-program contract checker (swarmproof, compiled side).

``analysis/shardflow.py`` proves sharding value-semantics over *source*;
this module audits what GSPMD/XLA actually *lowered*, because the r06
divergence family is precisely a case where correct-looking source
compiles to a wrong collective: an ``all-reduce`` over an
already-complete product is invisible in Python and one grep away in the
scheduled HLO. Reuses ``obs/hlocost.py``'s HLO walker — pure stdlib,
text in, facts out, no jax import (callers that *build* programs, like
``tools/shard_audit.py`` and ``benchmark.py``, bring their own).

Three checks against a declared per-program **contract** (JSON):

- **collective budget** — observed collective counts by op
  (``all-reduce`` / ``all-gather`` / ``reduce-scatter`` /
  ``collective-permute`` / ``all-to-all``, async ``-start`` forms folded
  in, ``-done`` halves skipped) vs ``{"collectives": {op: {"min", "max"},
  "max_total": n}}``. An unexpected ``all-reduce`` in a ring program is
  the runtime face of R11 ``replicated-psum``; a missing
  ``collective-permute`` means the ring never lowered at all.
- **dtype drift** — matmul/conv result-dtype census vs
  ``{"dtype": {"forbid": ["f32"], "allow_ops": n}}``: f32 upcasts inside
  a bf16 program burn double HBM and MXU throughput silently.
- **donation** — declared donated parameter indices vs the lowered
  ``input_output_alias`` table (``{"donation": {"require_params": [...]}}``):
  XLA silently DROPS donation on layout/sharding mismatch, which is rule
  R13 ``donation-drift``'s compiled face — the buffer the source
  promised to reuse quietly doubles peak HBM.

Every absent contract key is record-only: :func:`census` always reports
the observed facts so BENCH can stamp them per config, and CI pins only
what is stable on the host it runs on (donation is not implemented on
CPU backends, so the CPU contract pins collectives and dtype, and
records donation).
"""

from __future__ import annotations

import re
from typing import Any

from chiaswarm_tpu.obs.hlocost import (
    _SHAPE_RE,
    iter_instruction_lines,
)

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

_COLLECTIVE_RE = re.compile(
    r"\b(" + "|".join(COLLECTIVE_OPS) + r")(-start)?\(")
_MATMUL_RE = re.compile(
    r"=\s*(" + _SHAPE_RE.pattern + r")[^=]*?\b(convolution|dot)\(")
#: the alias table nests exactly one level ({output index}: (param, {}))
_ALIAS_BLOCK_RE = re.compile(
    r"input_output_alias=\{((?:[^{}]|\{[^{}]*\})*)\}")
_ALIAS_PARAM_RE = re.compile(r"\(\s*(\d+)\s*[,)]")
_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[\d,{}\s]*\}\}|\[\d+,\d+\]<=\[[\d,]+\])")


# ---------------------------------------------------------------------------
# census: observed facts of one lowered program


def collective_census(text: str) -> dict[str, dict]:
    """op -> {"count", "group_sizes"} over a scheduled-HLO dump. Async
    pairs count once (the ``-start``; the ``-done`` carries no new
    collective). ``group_sizes`` are the replica-group sizes seen — the
    static fingerprint of WHICH mesh axis a collective runs over (a
    ``seq``=4 axis shows groups of 4)."""
    out: dict[str, dict] = {}
    for _, line in iter_instruction_lines(text):
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        entry = out.setdefault(m.group(1),
                               {"count": 0, "group_sizes": []})
        entry["count"] += 1
        g = _REPLICA_GROUPS_RE.search(line)
        if g:
            size = _group_size(g.group(1))
            if size and size not in entry["group_sizes"]:
                entry["group_sizes"].append(size)
    for entry in out.values():
        entry["group_sizes"].sort()
    return out


def _group_size(spec: str) -> int | None:
    if spec.startswith("{{"):
        first = spec[2:].split("}", 1)[0]
        ids = [t for t in first.split(",") if t.strip() != ""]
        return len(ids) or None
    m = re.match(r"\[(\d+),(\d+)\]<=", spec)  # iota form: G groups of S
    if m:
        return int(m.group(2))
    return None


def matmul_dtype_census(text: str) -> dict[str, int]:
    """Result-dtype histogram of every convolution/dot instruction
    (fused computations included — an f32 dot inside a fusion is still
    f32 MXU work)."""
    out: dict[str, int] = {}
    for _, line in iter_instruction_lines(text):
        m = _MATMUL_RE.search(line)
        if m:
            dtype = _SHAPE_RE.search(m.group(1)).group(1)
            out[dtype] = out.get(dtype, 0) + 1
    return out


def donated_param_indices(text: str) -> list[int]:
    """Parameter indices the lowered program actually aliases to outputs
    (the ``input_output_alias`` table on the HloModule line) — what
    XLA *kept* of the source's donation declarations."""
    m = _ALIAS_BLOCK_RE.search(text)
    if not m:
        return []
    return sorted({int(p) for p in _ALIAS_PARAM_RE.findall(m.group(1))})


def census(text: str) -> dict[str, Any]:
    """All observed contract-relevant facts of one program — the BENCH
    stamp and the record-only half of an audit."""
    return {
        "collectives": collective_census(text),
        "matmul_dtypes": matmul_dtype_census(text),
        "donated_params": donated_param_indices(text),
    }


# ---------------------------------------------------------------------------
# audit: observed facts vs a declared contract


def audit_hlo(text: str, contract: dict,
              program: str = "program",
              obs: dict | None = None) -> list[dict]:
    """Violations of ``contract`` by one lowered program. Each violation
    is ``{"check", "rule", "program", "message"}`` — ``rule`` names the
    swarmlint rule whose runtime face the violation is (R11
    ``replicated-psum`` for collective overruns, R13 ``donation-drift``
    for dropped donation, ``dtype-drift`` for precision upcasts). Pass a
    precomputed ``obs`` (:func:`census` output) to skip re-walking the
    text — real UNet dumps are tens of MB."""
    violations: list[dict] = []
    if obs is None:
        obs = census(text)

    budget = contract.get("collectives") or {}
    total = sum(e["count"] for e in obs["collectives"].values())
    if "max_total" in budget and total > budget["max_total"]:
        ops = ", ".join(f"{op} x{e['count']}"
                        for op, e in sorted(obs["collectives"].items()))
        violations.append({
            "check": "collective-budget", "rule": "replicated-psum",
            "program": program,
            "message": (f"{total} collective(s) lowered "
                        f"({ops or 'none'}) but the contract allows at "
                        f"most {budget['max_total']} — an unexpected "
                        f"all-reduce over a complete product is the "
                        f"runtime face of R11"),
        })
    for op, limits in budget.items():
        if op == "max_total" or not isinstance(limits, dict):
            continue
        got = obs["collectives"].get(op, {}).get("count", 0)
        if "max" in limits and got > limits["max"]:
            violations.append({
                "check": "collective-budget", "rule": "replicated-psum",
                "program": program,
                "message": (f"{got} {op}(s) lowered but the contract "
                            f"allows at most {limits['max']}"),
            })
        if "min" in limits and got < limits["min"]:
            violations.append({
                "check": "collective-budget", "rule": "replicated-psum",
                "program": program,
                "message": (f"only {got} {op}(s) lowered but the "
                            f"contract requires at least "
                            f"{limits['min']} — the collective the "
                            f"program is built around never made it "
                            f"into the executable"),
            })

    dtype = contract.get("dtype") or {}
    allow = int(dtype.get("allow_ops", 0))
    for forbidden in dtype.get("forbid", ()):
        got = obs["matmul_dtypes"].get(forbidden, 0)
        if got > allow:
            violations.append({
                "check": "dtype-drift", "rule": "dtype-drift",
                "program": program,
                "message": (f"{got} {forbidden} matmul/conv op(s) in a "
                            f"program contracted to forbid {forbidden} "
                            f"(allow_ops={allow}) — silent precision "
                            f"upcast doubles HBM traffic and halves "
                            f"MXU throughput"),
            })

    donation = contract.get("donation") or {}
    required = donation.get("require_params", [])
    missing = sorted(set(required) - set(obs["donated_params"]))
    if missing:
        violations.append({
            "check": "donation", "rule": "donation-drift",
            "program": program,
            "message": (f"parameter(s) {missing} declared donated but "
                        f"the lowered program's input_output_alias "
                        f"table does not alias them — XLA dropped the "
                        f"donation (layout/sharding mismatch), peak "
                        f"HBM silently doubles (R13's compiled face)"),
        })
    return violations


def audit_programs(programs: dict[str, str],
                   contract: dict) -> dict[str, Any]:
    """Audit a set of named programs against a contract file of the
    shape ``{"programs": {name: {…}}}``; unknown program names audit
    against an empty (record-only) contract."""
    per = contract.get("programs") or {}
    report: dict[str, Any] = {"programs": {}, "violations": []}
    for name, text in sorted(programs.items()):
        obs = census(text)
        report["programs"][name] = obs
        report["violations"].extend(
            audit_hlo(text, per.get(name) or {}, program=name, obs=obs))
    report["ok"] = not report["violations"]
    return report
