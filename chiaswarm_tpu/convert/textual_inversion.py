"""Textual inversion: learned concept embeddings merged into the encoder.

Capability parity with swarm/diffusion/diffusion_func.py:48-54 — the
reference calls ``pipeline.load_textual_inversion(model_name)`` per job and
treats an incompatible embedding as a FATAL error (``ValueError`` so the
hive stops retrying). TPU-first redesign: the learned vectors append as new
rows to the resident text encoder's token-embedding matrix ONCE at load
time and the placeholder token registers with the tokenizer
(models/tokenizer.py AddedTokenMixin); jit retraces automatically for the
one-row-larger embedding shape, and generation runs the standard program.

Supported file formats:
- diffusers ``learned_embeds`` dicts: ``{"<token>": tensor(D) | (n, D)}``
- kohya/A1111 ``.pt``: ``{"string_to_param": {"*": tensor}, "name": ...}``
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

log = logging.getLogger("chiaswarm.textual_inversion")


def _read_raw(path: Path) -> Mapping[str, Any]:
    """Read one embeddings file WITHOUT the tensor-only assumptions of
    torch_to_flax.read_torch_weights — A1111 ``.pt`` files carry nested
    dicts and strings next to the tensors."""
    if path.suffix == ".safetensors":
        from safetensors import safe_open

        with safe_open(str(path), framework="np") as fh:
            return {k: fh.get_tensor(k) for k in fh.keys()}
    import torch

    return torch.load(str(path), map_location="cpu", weights_only=True)


def _to_array(t: Any) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t.astype(np.float32)
    return np.asarray(t.detach().to("cpu").float().numpy(), np.float32)


def pick_adapter_file(path: str | Path, what: str) -> Path:
    """Resolve a file-or-dir adapter path to ONE weights file: safetensors
    preferred, then .bin/.pt alphabetically. ValueError when empty (fatal
    — the hive must not retry, swarm/generator.py:34-41). Shared by the
    textual-inversion and LoRA loaders."""
    path = Path(path)
    if not path.is_dir():
        return path
    files = (sorted(path.glob("*.safetensors"))
             or sorted(list(path.glob("*.bin")) + list(path.glob("*.pt"))))
    if not files:
        raise ValueError(f"no {what} files under {path}")
    return files[0]


def load_embeddings(path: str | Path) -> dict[str, np.ndarray]:
    """Read a textual-inversion file/dir -> {placeholder_token: (n, D)}.

    Malformed files raise ``ValueError`` (fatal — the hive must not retry,
    swarm/generator.py:34-41)."""
    path = pick_adapter_file(path, "embedding")
    try:
        state = _read_raw(path)
    except Exception as exc:
        raise ValueError(f"unreadable textual inversion {path}: {exc}")

    if "string_to_param" in state:  # A1111 .pt layout
        token = str(state.get("name", "<concept>"))
        tensor = _to_array(list(state["string_to_param"].values())[0])
        return {token: np.atleast_2d(tensor)}

    out: dict[str, np.ndarray] = {}
    for token, tensor in state.items():
        if token.startswith("string_to_") or isinstance(tensor, (str, dict,
                                                                 int, float)):
            continue
        arr = _to_array(tensor)
        if arr.ndim in (1, 2):
            out[token] = np.atleast_2d(arr)
    if not out:
        raise ValueError(f"no embeddings found in {path}")
    return out


def apply_textual_inversion(components, embeddings: dict[str, np.ndarray],
                            ) -> list[str]:
    """Append embedding rows + register placeholder tokens. Mutates the
    Components bundle IN PLACE (callers own a private copy via the
    registry's per-(model, inversion) cache key).

    Raises ``ValueError`` on hidden-size mismatch — the fatal-error parity
    with the reference's incompatible-inversion path (diffusion_func.py:
    48-54, surfaced as fatal at swarm/generator.py:34-41).
    """
    import dataclasses

    from chiaswarm_tpu.models.clip import ClipTextEncoder

    added: list[str] = []
    for token, vectors in embeddings.items():
        vectors = np.atleast_2d(np.asarray(vectors, np.float32))
        for i, te in enumerate(components.text_encoders):
            tree = components.params[f"text_encoder_{i}"]["params"]
            table = tree["token_embedding"]["embedding"]
            if vectors.shape[1] != table.shape[1]:
                raise ValueError(
                    f"textual inversion {token!r} has dimension "
                    f"{vectors.shape[1]}, but the text encoder embeds at "
                    f"{table.shape[1]} — incompatible with this model"
                )
            start = table.shape[0]
            tree["token_embedding"]["embedding"] = jnp.concatenate(
                [jnp.asarray(table), jnp.asarray(vectors)], axis=0)
            # the module's static vocab size must match the enlarged table
            # (flax validates param shapes at apply time)
            components.text_encoders[i] = ClipTextEncoder(
                dataclasses.replace(te.config,
                                    vocab_size=start + vectors.shape[0]))
            ids = list(range(start, start + vectors.shape[0]))
            components.tokenizers[i].add_token(token, ids)
        added.append(token)
        log.info("textual inversion %r registered (%d vector(s))",
                 token, vectors.shape[0])
    return added
