"""Checkpoint conversion: HF-diffusers torch layouts -> Flax param trees.

Replaces the role of the reference's per-job ``from_pretrained`` weight
loading (swarm/diffusion/diffusion_func.py:41-46) and the initialize-time
warm cache (swarm/initialize.py:62-94): checkpoints convert ONCE into the
framework's native layout (NHWC convs, (in, out) dense kernels) and stay
resident on device.
"""

from chiaswarm_tpu.convert.torch_to_flax import (
    convert_text_encoder,
    convert_unet,
    convert_vae,
    load_checkpoint,
    read_torch_weights,
)
from chiaswarm_tpu.convert.lora import load_lora, merge_lora
from chiaswarm_tpu.convert.quantize import (
    dequantize_tree,
    int8_enabled,
    maybe_quantize_params,
    quantize_tree,
)

__all__ = [
    "convert_text_encoder",
    "convert_unet",
    "convert_vae",
    "dequantize_tree",
    "int8_enabled",
    "load_checkpoint",
    "load_lora",
    "maybe_quantize_params",
    "quantize_tree",
    "read_torch_weights",
    "merge_lora",
]
