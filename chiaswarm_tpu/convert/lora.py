"""LoRA adapter merging.

Capability parity with the reference's LoRA path (swarm/diffusion/
diffusion_func.py:58-68: ``unet.load_attn_procs`` + runtime
``cross_attention_kwargs={"scale": s}``, which also forces xformers OFF).
TPU-first redesign: runtime low-rank side-paths would add two extra matmuls
per projection per step and a new executable per scale; instead the deltas
**merge into the resident kernels once at load time**
(W <- W + scale * (up @ down)^T), so generation runs the unmodified jitted
program at full flash-attention speed and any scale is just a different
cached param tree.

Supported file formats:
- diffusers attn-procs: ``...attn1.processor.to_q_lora.down.weight`` /
  ``.up.weight``
- peft/kohya: ``...to_q.lora_A.weight`` / ``.lora_B.weight``
  (also ``lora_down``/``lora_up`` aliases)
"""

from __future__ import annotations

import logging
import re
from pathlib import Path
from typing import Any, Mapping

import jax
import numpy as np

log = logging.getLogger("chiaswarm.lora")


def load_lora(path: str | Path) -> dict[str, np.ndarray]:
    """Read a LoRA adapter file/dir -> flat {torch_key: array} state.

    Shares textual_inversion's adapter-file resolution (safetensors
    preferred); an unreadable file raises ``ValueError`` — fatal, so the
    hive must not retry (swarm/generator.py:34-41; the reference's
    load_attn_procs failure is likewise re-raised as ValueError,
    diffusion_func.py:58-68)."""
    from chiaswarm_tpu.convert.textual_inversion import (
        _read_raw,
        _to_array,
        pick_adapter_file,
    )

    path = pick_adapter_file(path, "LoRA adapter")
    try:
        state = _read_raw(path)
    except Exception as exc:
        raise ValueError(f"unreadable LoRA adapter {path}: {exc}")
    out: dict[str, np.ndarray] = {}
    for key, tensor in state.items():
        if isinstance(tensor, (str, dict, int, float)):
            continue
        out[str(key)] = _to_array(tensor)
    if not out:
        raise ValueError(f"LoRA adapter {path} contains no tensors")
    return out

_PAIR_RES = (
    # diffusers attn-procs format
    re.compile(r"^(?P<base>.+)\.processor\.(?P<proj>to_q|to_k|to_v|to_out)"
               r"_lora\.(?P<half>down|up)\.weight$"),
    # peft / kohya formats
    re.compile(r"^(?P<base>.+)\.(?P<proj>to_q|to_k|to_v|to_out)(?:\.0)?"
               r"\.lora_(?P<half>A|B|down|up)\.weight$"),
)

_HALF_DOWN = {"down", "A"}


def _collect_pairs(state: Mapping[str, np.ndarray]):
    """-> {(base_path, proj): {"down": arr, "up": arr}}"""
    pairs: dict[tuple[str, str], dict[str, np.ndarray]] = {}
    for key, value in state.items():
        clean = key[5:] if key.startswith("unet.") else key
        for pattern in _PAIR_RES:
            m = pattern.match(clean)
            if m:
                half = "down" if m.group("half") in _HALF_DOWN else "up"
                pairs.setdefault((m.group("base"), m.group("proj")), {})[
                    half] = np.asarray(value, np.float32)
                break
    return pairs


def merge_lora(unet_params: dict, lora_state: Mapping[str, np.ndarray],
               scale: float = 1.0, *, n_levels: int = 4) -> tuple[dict, int]:
    """Return (new unet param tree, merged-projection count).

    ``unet_params`` is the Flax tree from convert.torch_to_flax; unmatched
    LoRA keys are counted and logged, never silently dropped.
    """
    from chiaswarm_tpu.convert.torch_to_flax import _unet_path

    flat = dict(_flatten(unet_params["params"]))
    merged = 0
    missed = []
    for (base, proj), halves in _collect_pairs(lora_state).items():
        if "down" not in halves or "up" not in halves:
            missed.append(base)
            continue
        body = f"{base}.{proj}".split(".")
        path = _unet_path(body, n_levels)
        if path is None or f"{path}/kernel" not in flat:
            missed.append(f"{base}.{proj}")
            continue
        down, up = halves["down"], halves["up"]   # (r, I), (O, r)
        delta = (up @ down).T * float(scale)      # flax kernel layout (I, O)
        kernel = flat[f"{path}/kernel"]
        flat[f"{path}/kernel"] = (
            np.asarray(kernel, np.float32) + delta
        ).astype(np.asarray(kernel).dtype)
        merged += 1
    if missed:
        log.warning("lora: %d projections did not match the unet (e.g. %s)",
                    len(missed), missed[0])
    if merged == 0:
        raise ValueError("LoRA file matched no UNet projections "
                         "(incompatible adapter)")

    tree: dict = {}
    for path, value in flat.items():
        node = tree
        parts = path.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return {"params": tree}, merged


def _flatten(tree: Any, prefix: str = ""):
    for key, value in tree.items():
        path = f"{prefix}/{key}" if prefix else key
        if isinstance(value, dict):
            yield from _flatten(value, path)
        else:
            yield path, value
