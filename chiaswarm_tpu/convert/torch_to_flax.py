"""HF-diffusers/transformers torch checkpoints -> this framework's Flax trees.

Covers the three module classes of the SD families (models/configs.py):

- UNet:          diffusers ``UNet2DConditionModel`` state dicts
- VAE:           diffusers ``AutoencoderKL`` state dicts (old ``query``/
                 ``proj_attn`` and new ``to_q``/``to_out.0`` attention names)
- Text encoder:  transformers ``CLIPTextModel(WithProjection)``

Layout transforms (torch -> flax):
- conv weight (O, I, kH, kW) -> kernel (kH, kW, I, O)
- linear weight (O, I)       -> kernel (I, O)
- norm weight/bias           -> scale/bias
- embedding weight           -> embedding

Directory layout is the HF pipeline snapshot the reference's initializer
fills (swarm/initialize.py:73-89): ``unet/``, ``vae/``, ``text_encoder/``
(+ ``text_encoder_2/`` for SDXL), each holding ``*.safetensors`` or
``*.bin``.
"""

from __future__ import annotations

import logging
import re
from pathlib import Path
from typing import Any, Mapping

import numpy as np

from chiaswarm_tpu.models.configs import ModelFamily, UNetConfig, VAEConfig

log = logging.getLogger("chiaswarm.convert")


# ---------------------------------------------------------------- reading

def read_torch_weights(path: str | Path) -> dict[str, np.ndarray]:
    """Read every tensor under ``path`` (a module subdir or a single file)."""
    path = Path(path)
    files: list[Path] = []
    if path.is_file():
        files = [path]
    else:
        for pattern in ("*.safetensors", "*.bin", "*.pt", "*.pth",
                        "*.ckpt"):
            files.extend(sorted(path.glob(pattern)))
    if not files:
        raise FileNotFoundError(f"no weight files under {path}")

    state: dict[str, np.ndarray] = {}
    for file in files:
        if file.suffix == ".safetensors":
            from safetensors import safe_open

            with safe_open(str(file), framework="np") as fh:
                for key in fh.keys():
                    state[key] = _to_numpy(fh.get_tensor(key))
        else:
            import torch

            raw = torch.load(str(file), map_location="cpu",
                             weights_only=True)
            if isinstance(raw, dict) and "state_dict" in raw:
                raw = raw["state_dict"]
            for key, value in raw.items():
                state[key] = _to_numpy(value)
    return state


def _to_numpy(t: Any) -> np.ndarray:
    if isinstance(t, np.ndarray):
        arr = t
    else:  # torch tensor
        arr = t.detach().to("cpu").float().numpy()
    if arr.dtype not in (np.float32, np.float64, np.int32, np.int64):
        arr = arr.astype(np.float32)
    return np.asarray(arr, dtype=np.float32 if arr.dtype.kind == "f" else arr.dtype)


# ------------------------------------------------------------- tree utils

def _nest(flat: Mapping[str, np.ndarray]) -> dict:
    tree: dict = {}
    for path, value in flat.items():
        node = tree
        parts = path.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return {"params": tree}


_NORM_HINTS = ("norm", "layer_norm", "group_norm")


def _place(flat: dict[str, np.ndarray], flax_path: str, name: str,
           value: np.ndarray) -> None:
    """Append one torch leaf under ``flax_path`` with layout transform."""
    if name == "weight":
        if value.ndim == 5:    # Conv3d (O,I,kT,kH,kW) -> (kT,kH,kW,I,O)
            # (the video UNets' frame-axis (3,1,1) convs)
            flat[f"{flax_path}/kernel"] = value.transpose(2, 3, 4, 1, 0)
        elif value.ndim == 4:  # conv OIHW -> HWIO
            flat[f"{flax_path}/kernel"] = value.transpose(2, 3, 1, 0)
        elif value.ndim == 2:  # linear (O,I) -> (I,O)
            flat[f"{flax_path}/kernel"] = value.T
        else:                  # norm gamma
            flat[f"{flax_path}/scale"] = value
    elif name == "bias":
        flat[f"{flax_path}/bias"] = value
    else:
        flat[f"{flax_path}/{name}"] = value


# ----------------------------------------------------------------- UNet

def convert_unet(state: Mapping[str, np.ndarray],
                 config: UNetConfig) -> dict:
    n_levels = len(config.block_out_channels)
    flat: dict[str, np.ndarray] = {}
    skipped: list[str] = []

    for key, value in state.items():
        if key == "class_embedding.weight":
            if config.class_proj_dim is not None:
                # simple_projection (AudioLDM): an nn.Linear over float
                # class labels -> normal (O, I) -> (I, O) transpose
                flat["class_embedding/kernel"] = value.T
            else:
                # nn.Embedding table (x4-upscaler noise level): (N, dim)
                # used as-is — NOT a linear, bypasses _place's transpose
                flat["class_embedding/embedding"] = value
            continue
        if key == "class_embedding.bias":
            flat["class_embedding/bias"] = value
            continue
        parts = key.split(".")
        name = parts[-1]
        body = parts[:-1]
        path = _unet_path(body, n_levels)
        if path is None:
            skipped.append(key)
            continue
        _place(flat, path, name, value)

    if skipped:
        log.info("unet conversion skipped %d non-module keys (e.g. %s)",
                 len(skipped), skipped[0])
    return _nest(flat)


def _attention_inner(rest: list[str]) -> str | None:
    """Names inside a SpatialTransformer (diffusers Transformer2DModel)."""
    if not rest:
        return None
    head = rest[0]
    if head in ("norm", "proj_in", "proj_out"):
        return head
    if head == "transformer_blocks":
        i, inner = rest[1], rest[2:]
        if not inner:
            return None
        sub = inner[0]
        if sub in ("norm1", "norm2", "norm3"):
            return f"transformer_blocks_{i}/{sub}"
        if sub in ("attn1", "attn2"):
            proj = inner[1]
            if proj == "to_out":  # HF: to_out.0 (ModuleList w/ dropout)
                return f"transformer_blocks_{i}/{sub}/to_out"
            if proj in ("to_q", "to_k", "to_v"):
                return f"transformer_blocks_{i}/{sub}/{proj}"
            return None
        if sub == "ff":  # ff.net.0.proj (GEGLU up) / ff.net.2 (down)
            if inner[1] == "net" and inner[2] == "0" and inner[3] == "proj":
                return f"transformer_blocks_{i}/ff/proj_in"
            if inner[1] == "net" and inner[2] == "2":
                return f"transformer_blocks_{i}/ff/proj_out"
            return None
    return None


_RESNET_LEAVES = {"norm1", "conv1", "time_emb_proj", "norm2", "conv2",
                  "conv_shortcut"}


def _unet_path(body: list[str], n_levels: int) -> str | None:
    joined = ".".join(body)
    # top-level singletons
    if joined in ("conv_in", "conv_norm_out", "conv_out"):
        return joined
    if body[0] in ("time_embedding", "add_embedding") and \
            body[1] in ("linear_1", "linear_2"):
        return f"{body[0]}/{body[1]}"

    if body[0] in ("down_blocks", "up_blocks"):
        level = int(body[1])
        if body[0] == "up_blocks":
            level = n_levels - 1 - level  # HF counts top-down; we bottom-up
        kind = body[2]
        if kind == "resnets" and body[4] in _RESNET_LEAVES:
            return f"{body[0][:-7]}_{level}_resnets_{body[3]}/{body[4]}"
        if kind == "attentions":
            inner = _attention_inner(body[4:])
            if inner is not None:
                prefix = "down" if body[0] == "down_blocks" else "up"
                return f"{prefix}_{level}_attentions_{body[3]}/{inner}"
        if kind == "downsamplers" and body[4] == "conv":
            return f"down_{level}_downsample/conv"
        if kind == "upsamplers" and body[4] == "conv":
            return f"up_{level}_upsample/conv"
        return None

    if body[0] == "mid_block":
        if body[1] == "resnets" and body[3] in _RESNET_LEAVES:
            return f"mid_resnets_{body[2]}/{body[3]}"
        if body[1] == "attentions" and body[2] == "0":
            inner = _attention_inner(body[3:])
            if inner is not None:
                return f"mid_attention/{inner}"
    return None


# ------------------------------------------------------------ video UNets

def _temp_conv_inner(rest: list[str]) -> str | None:
    """Names inside diffusers' ``TemporalConvLayer``: each of conv1..conv4
    is an nn.Sequential whose index 0 is the GroupNorm and whose last
    entry is the Conv3d (index 2, or 3 behind a Dropout)."""
    if len(rest) < 2:
        return None
    m = re.fullmatch(r"conv([1-4])", rest[0])
    if not m:
        return None
    return f"norm{m.group(1)}" if rest[1] == "0" else f"conv{m.group(1)}"


def _unet3d_path(body: list[str], n_levels: int) -> str | None:
    """ModelScope ``UNet3DConditionModel`` keys -> models/video_unet.py
    UNet3D paths. Spatial modules reuse the 2D rules (_unet_path); the
    temporal additions are ``transformer_in``, per-block ``temp_convs``
    and ``temp_attentions`` (both TransformerTemporalModel layouts map
    through _attention_inner — same proj/block naming)."""
    if body[0] == "transformer_in":
        inner = _attention_inner(body[1:])
        return f"transformer_in/{inner}" if inner else None
    if body[0] in ("down_blocks", "up_blocks") and len(body) > 4:
        level = int(body[1])
        side = "down" if body[0] == "down_blocks" else "up"
        if side == "up":
            level = n_levels - 1 - level
        if body[2] == "temp_convs":
            inner = _temp_conv_inner(body[4:])
            return (f"{side}_{level}_tconvs_{body[3]}/{inner}"
                    if inner else None)
        if body[2] == "temp_attentions":
            inner = _attention_inner(body[4:])
            return (f"{side}_{level}_tattns_{body[3]}/{inner}"
                    if inner else None)
    if body[0] == "mid_block" and len(body) > 2:
        if body[1] == "temp_convs":
            inner = _temp_conv_inner(body[3:])
            return f"mid_tconvs_{body[2]}/{inner}" if inner else None
        if body[1] == "temp_attentions" and body[2] == "0":
            inner = _attention_inner(body[3:])
            return f"mid_tattn/{inner}" if inner else None
    return _unet_path(body, n_levels)


def convert_unet3d(state: Mapping[str, np.ndarray],
                   config: UNetConfig) -> dict:
    """diffusers ``UNet3DConditionModel`` state dict (the layout of
    text-to-video-ms-1.7b, the snapshot the reference serves —
    swarm/video/tx2vid.py:24-27) -> UNet3D params."""
    n_levels = len(config.block_out_channels)
    flat: dict[str, np.ndarray] = {}
    skipped: list[str] = []
    for key, value in state.items():
        parts = key.split(".")
        path = _unet3d_path(parts[:-1], n_levels)
        if path is None:
            skipped.append(key)
            continue
        _place(flat, path, parts[-1], value)
    if skipped:
        log.info("unet3d conversion skipped %d keys (e.g. %s)",
                 len(skipped), skipped[0])
    return _nest(flat)


def _temporal_block_inner(rest: list[str]) -> str | None:
    """Names inside diffusers' ``TemporalBasicTransformerBlock``."""
    if not rest:
        return None
    head = rest[0]
    if head in ("norm_in", "norm1", "norm2", "norm3"):
        return head
    if head in ("ff_in", "ff") and len(rest) >= 3 and rest[1] == "net":
        if rest[2] == "0" and len(rest) > 3 and rest[3] == "proj":
            return f"{head}/proj_in"
        if rest[2] == "2":
            return f"{head}/proj_out"
        return None
    if head in ("attn1", "attn2") and len(rest) > 1:
        proj = rest[1]
        if proj == "to_out":       # to_out.0 (ModuleList with dropout)
            return f"{head}/to_out"
        if proj in ("to_q", "to_k", "to_v"):
            return f"{head}/{proj}"
    return None


def _st_attention_inner(rest: list[str]) -> str | None:
    """Names inside diffusers' ``TransformerSpatioTemporalModel``: the
    spatial transformer_blocks reuse _attention_inner; the temporal side
    adds temporal_transformer_blocks, time_pos_embed and the time_mixer's
    scalar blend weight."""
    if not rest:
        return None
    head = rest[0]
    if head == "temporal_transformer_blocks" and len(rest) > 2:
        inner = _temporal_block_inner(rest[2:])
        return f"temporal_blocks_{rest[1]}/{inner}" if inner else None
    if head == "time_pos_embed" and len(rest) > 1 and \
            rest[1] in ("linear_1", "linear_2"):
        return f"time_pos_embed/{rest[1]}"
    if head == "time_mixer":
        return ""                  # mix_factor sits at the module root
    return _attention_inner(rest)


def _unet_st_path(body: list[str], n_levels: int) -> str | None:
    """SVD ``UNetSpatioTemporalConditionModel`` keys ->
    models/video_unet.py UNetSpatioTemporal paths."""
    if body[0] in ("down_blocks", "up_blocks") and len(body) > 4:
        level = int(body[1])
        side = "down" if body[0] == "down_blocks" else "up"
        if side == "up":
            level = n_levels - 1 - level
        kind, j = body[2], body[3]
        if kind == "resnets":
            root = f"{side}_{level}_resnets_{j}"
            sub = body[4]
            if sub == "spatial_res_block" and body[5] in _RESNET_LEAVES:
                return f"{root}/spatial/{body[5]}"
            if sub == "temporal_res_block" and body[5] in _RESNET_LEAVES:
                return f"{root}/temporal/{body[5]}"
            if sub == "time_mixer":
                return root        # leaf name is mix_factor
            return None
        if kind == "attentions":
            inner = _st_attention_inner(body[4:])
            if inner is None:
                return None
            root = f"{side}_{level}_attentions_{j}"
            return f"{root}/{inner}" if inner else root
        if kind == "downsamplers" and body[4] == "conv":
            return f"down_{level}_downsample/conv"
        if kind == "upsamplers" and body[4] == "conv":
            return f"up_{level}_upsample/conv"
        return None
    if body[0] == "mid_block" and len(body) > 3:
        if body[1] == "resnets":
            root = f"mid_resnets_{body[2]}"
            sub = body[3]
            if sub == "spatial_res_block" and body[4] in _RESNET_LEAVES:
                return f"{root}/spatial/{body[4]}"
            if sub == "temporal_res_block" and body[4] in _RESNET_LEAVES:
                return f"{root}/temporal/{body[4]}"
            if sub == "time_mixer":
                return root
            return None
        if body[1] == "attentions" and body[2] == "0":
            inner = _st_attention_inner(body[3:])
            if inner is None:
                return None
            return f"mid_attention/{inner}" if inner else "mid_attention"
    return _unet_path(body, n_levels)


def convert_unet_spatio_temporal(state: Mapping[str, np.ndarray],
                                 config: UNetConfig) -> dict:
    """diffusers ``UNetSpatioTemporalConditionModel`` state dict (the
    published SVD img2vid layout) -> UNetSpatioTemporal params."""
    n_levels = len(config.block_out_channels)
    flat: dict[str, np.ndarray] = {}
    skipped: list[str] = []
    for key, value in state.items():
        parts = key.split(".")
        path = _unet_st_path(parts[:-1], n_levels)
        if path is None:
            skipped.append(key)
            continue
        _place(flat, path, parts[-1], value)
    if skipped:
        log.info("spatio-temporal unet conversion skipped %d keys "
                 "(e.g. %s)", len(skipped), skipped[0])
    return _nest(flat)


# ------------------------------------------------------------- ControlNet

def convert_controlnet(state: Mapping[str, np.ndarray],
                       config: UNetConfig) -> dict:
    """diffusers ``ControlNetModel`` state dict -> ControlNetBundle.params
    (``{"net": ..., "embed": ...}``, models/controlnet.py). The trunk
    (conv_in/time_embedding/down_blocks/mid_block) reuses the UNet path
    rules; the controlnet-specific heads are the zero convs and the hint
    embedder."""
    n_levels = len(config.block_out_channels)
    net_flat: dict[str, np.ndarray] = {}
    embed_flat: dict[str, np.ndarray] = {}
    skipped: list[str] = []

    for key, value in state.items():
        parts = key.split(".")
        name = parts[-1]
        body = parts[:-1]
        if body[0] == "controlnet_cond_embedding":
            if body[1] in ("conv_in", "conv_out"):
                _place(embed_flat, body[1], name, value)
            elif body[1] == "blocks":
                _place(embed_flat, f"blocks_{body[2]}", name, value)
            else:
                skipped.append(key)
            continue
        if body[0] == "controlnet_down_blocks":
            _place(net_flat, f"controlnet_down_blocks_{body[1]}", name, value)
            continue
        if body[0] == "controlnet_mid_block":
            _place(net_flat, "controlnet_mid_block", name, value)
            continue
        path = _unet_path(body, n_levels)
        if path is None:
            skipped.append(key)
            continue
        _place(net_flat, path, name, value)

    if skipped:
        log.info("controlnet conversion skipped %d keys (e.g. %s)",
                 len(skipped), skipped[0])
    return {"net": _nest(net_flat), "embed": _nest(embed_flat)}


# ------------------------------------------------------------------ VAE

# old diffusers VAE attention names -> canonical
_VAE_ATTN_ALIASES = {"query": "to_q", "key": "to_k", "value": "to_v",
                     "proj_attn": "to_out"}


def convert_vae(state: Mapping[str, np.ndarray], config: VAEConfig) -> dict:
    n_levels = len(config.block_out_channels)
    flat: dict[str, np.ndarray] = {}

    for key, value in state.items():
        parts = key.split(".")
        name = parts[-1]
        body = parts[:-1]
        path = _vae_path(body, n_levels)
        if path is None:
            log.debug("vae conversion skipped %s", key)
            continue
        # old-layout attention projections are stored (O, I, 1, 1)
        if value.ndim == 4 and value.shape[2:] == (1, 1) and \
                any(p in path for p in ("to_q", "to_k", "to_v", "to_out")):
            value = value[:, :, 0, 0]
        _place(flat, path, name, value)
    return _nest(flat)


def _vae_path(body: list[str], n_levels: int) -> str | None:
    if body[0] == "quant_conv":
        return "encoder/quant_conv"
    if body[0] == "post_quant_conv":
        return "decoder/post_quant_conv"
    if body[0] not in ("encoder", "decoder"):
        return None
    side = body[0]
    rest = body[1:]
    joined = ".".join(rest)
    if joined in ("conv_in", "conv_norm_out", "conv_out"):
        return f"{side}/{rest[0]}"
    if rest[0] in ("down_blocks", "up_blocks"):
        level = int(rest[1])
        if rest[0] == "up_blocks":
            level = n_levels - 1 - level
        if rest[2] == "resnets" and rest[4] in _RESNET_LEAVES:
            prefix = "down" if rest[0] == "down_blocks" else "up"
            return f"{side}/{prefix}_{level}_resnets_{rest[3]}/{rest[4]}"
        if rest[2] == "downsamplers" and rest[4] == "conv":
            return f"{side}/down_{level}_downsample"
        if rest[2] == "upsamplers" and rest[4] == "conv":
            return f"{side}/up_{level}_upsample"
        return None
    if rest[0] == "mid_block":
        if rest[1] == "resnets" and rest[3] in _RESNET_LEAVES:
            return f"{side}/mid/resnets_{rest[2]}/{rest[3]}"
        if rest[1] == "attentions" and rest[2] == "0":
            leaf = _VAE_ATTN_ALIASES.get(rest[3], rest[3])
            if leaf == "to_out" and len(rest) > 4:  # to_out.0
                pass
            if leaf in ("to_q", "to_k", "to_v", "to_out", "group_norm"):
                return f"{side}/mid/attentions_0/{leaf}"
    return None


# ----------------------------------------------------- temporal VAE (SVD)

def _temporal_vae_decoder_path(rest: list[str],
                               n_levels: int) -> str | None:
    """``TemporalDecoder`` keys (under ``decoder.``) ->
    models/vae.py TemporalVaeDecoder paths."""
    joined = ".".join(rest)
    if joined in ("conv_in", "conv_norm_out", "conv_out", "time_conv_out"):
        return f"decoder/{rest[0]}"
    if rest[0] == "mid_block":
        if rest[1] == "resnets":
            root = f"decoder/mid_resnets_{rest[2]}"
            if rest[3] == "spatial_res_block" and rest[4] in _RESNET_LEAVES:
                return f"{root}/spatial/{rest[4]}"
            if rest[3] == "temporal_res_block" and \
                    rest[4] in _RESNET_LEAVES:
                return f"{root}/temporal/{rest[4]}"
            if rest[3] == "time_mixer":
                return root               # leaf mix_factor
            return None
        if rest[1] == "attentions" and rest[2] == "0":
            leaf = _VAE_ATTN_ALIASES.get(rest[3], rest[3])
            if leaf in ("to_q", "to_k", "to_v", "to_out", "group_norm"):
                return f"decoder/mid_attention/{leaf}"
            return None
    if rest[0] == "up_blocks":
        level = n_levels - 1 - int(rest[1])
        if rest[2] == "resnets":
            root = f"decoder/up_{level}_resnets_{rest[3]}"
            if rest[4] == "spatial_res_block" and rest[5] in _RESNET_LEAVES:
                return f"{root}/spatial/{rest[5]}"
            if rest[4] == "temporal_res_block" and \
                    rest[5] in _RESNET_LEAVES:
                return f"{root}/temporal/{rest[5]}"
            if rest[4] == "time_mixer":
                return root
            return None
        if rest[2] == "upsamplers" and rest[4] == "conv":
            return f"decoder/up_{level}_upsample"
    return None


def convert_temporal_vae(state: Mapping[str, np.ndarray],
                         config: VAEConfig) -> dict:
    """``AutoencoderKLTemporalDecoder`` state dict (the VAE real SVD
    snapshots ship) -> AutoencoderKLTemporalDecoder params: standard
    encoder (+ quant_conv) through the 2D VAE rules, the TemporalDecoder
    through its own. There is no post_quant_conv in this layout."""
    n_levels = len(config.block_out_channels)
    flat: dict[str, np.ndarray] = {}
    skipped: list[str] = []
    for key, value in state.items():
        parts = key.split(".")
        name = parts[-1]
        body = parts[:-1]
        if body and body[0] == "decoder":
            path = _temporal_vae_decoder_path(body[1:], n_levels)
        else:
            path = _vae_path(body, n_levels)
        if path is None:
            skipped.append(key)
            continue
        _place(flat, path, name, value)
    if skipped:
        log.info("temporal vae conversion skipped %d keys (e.g. %s)",
                 len(skipped), skipped[0])
    return _nest(flat)


# ---------------------------------------------------------- text encoder

def convert_text_encoder(state: Mapping[str, np.ndarray]) -> dict:
    flat: dict[str, np.ndarray] = {}
    for key, value in state.items():
        k = key
        if k.startswith("text_model."):
            k = k[len("text_model."):]
        parts = k.split(".")
        name = parts[-1]
        body = parts[:-1]

        if body[:2] == ["embeddings", "token_embedding"]:
            flat["token_embedding/embedding"] = value
        elif body[:2] == ["embeddings", "position_embedding"]:
            flat["position_embedding/embedding"] = value
        elif body[:2] == ["encoder", "layers"]:
            i = body[2]
            sub = body[3]
            if sub == "self_attn":
                flat_key = f"layers_{i}/self_attn/{body[4]}"
            elif sub in ("layer_norm1", "layer_norm2"):
                flat_key = f"layers_{i}/{sub}"
            elif sub == "mlp":
                flat_key = f"layers_{i}/{body[4]}"
            else:
                continue
            _place(flat, flat_key, name, value)
            continue
        elif body == ["final_layer_norm"]:
            _place(flat, "final_layer_norm", name, value)
        elif body == ["text_projection"]:
            _place(flat, "text_projection", name, value)
        else:
            log.debug("text encoder conversion skipped %s", key)
    return _nest(flat)


# ------------------------------------------------------------------ CLAP

def convert_clap_text(state: Mapping[str, np.ndarray]) -> dict:
    """transformers ``ClapTextModelWithProjection`` state dict ->
    models/clap.py tree (RoBERTa layout; ref swarm/audio/audioldm.py:12-24
    loads this tower inside AudioLDMPipeline)."""
    flat: dict[str, np.ndarray] = {}
    for key, value in state.items():
        k = key
        if k.startswith("text_model."):
            k = k[len("text_model."):]
        parts = k.split(".")
        name = parts[-1]
        body = parts[:-1]
        if body == ["embeddings"]:          # position_ids / token_type_ids
            continue                        # non-parameter buffers
        if body[:1] == ["embeddings"]:
            if body[1] == "LayerNorm":
                _place(flat, "embed_norm", name, value)
            else:                           # word/position/token_type
                flat[f"{body[1]}/embedding"] = value
        elif body[:2] == ["encoder", "layer"]:
            i = body[2]
            sub = body[3]
            if sub == "attention":
                if body[4] == "self":       # query/key/value
                    _place(flat, f"layer_{i}/{body[5]}", name, value)
                elif body[5] == "dense":    # attention.output.dense
                    _place(flat, f"layer_{i}/attn_out", name, value)
                else:                       # attention.output.LayerNorm
                    _place(flat, f"layer_{i}/attn_norm", name, value)
            elif sub == "intermediate":
                _place(flat, f"layer_{i}/intermediate", name, value)
            elif sub == "output":
                if body[4] == "dense":
                    _place(flat, f"layer_{i}/output", name, value)
                else:                       # output.LayerNorm
                    _place(flat, f"layer_{i}/out_norm", name, value)
        elif body == ["pooler", "dense"]:
            _place(flat, "pooler", name, value)
        elif body[:1] == ["text_projection"] and len(body) > 1:
            _place(flat, "proj1" if body[1] == "linear1" else "proj2",
                   name, value)
        else:
            log.debug("clap text conversion skipped %s", key)
    return _nest(flat)


# ------------------------------------------------------------------- T5

def convert_t5(state: Mapping[str, np.ndarray]) -> dict:
    """transformers ``T5EncoderModel`` state dict -> models/t5.py tree."""
    flat: dict[str, np.ndarray] = {}
    for key, value in state.items():
        parts = key.split(".")
        if parts[0] == "shared" or parts[:2] == ["encoder", "embed_tokens"]:
            flat["token_embedding/embedding"] = value
            continue
        if parts[0] != "encoder":
            log.debug("t5 conversion skipped %s", key)
            continue
        rest = parts[1:]
        if rest[0] == "final_layer_norm":
            flat["final_layer_norm/scale"] = value
            continue
        if rest[0] != "block":
            log.debug("t5 conversion skipped %s", key)
            continue
        i = rest[1]
        layer, sub = rest[3], rest[4]
        if sub == "SelfAttention":
            leaf = rest[5]
            if leaf == "relative_attention_bias":
                flat[f"block_{i}/attention/relative_attention_bias"] = value
            else:
                flat[f"block_{i}/attention/{leaf}/kernel"] = value.T
        elif sub == "DenseReluDense":
            flat[f"block_{i}/{rest[5]}/kernel"] = value.T
        elif sub == "layer_norm":
            which = "attn_norm" if layer == "0" else "ff_norm"
            flat[f"block_{i}/{which}/scale"] = value
    return _nest(flat)


def load_cascade_checkpoint(checkpoint_dir: str | Path, model_name: str,
                            family) -> "Any":
    """IF-class cascade snapshot -> CascadeComponents.

    Expected layout (assembled by the node initializer, since the
    reference's three stages live in separate HF repos,
    swarm/diffusion/diffusion_func_if.py:16-40):
    ``text_encoder/`` (T5), ``unet/`` (stage 1), ``unet_sr/`` (stage 2).
    """
    from chiaswarm_tpu.models.t5 import T5Encoder
    from chiaswarm_tpu.models.tokenizer import HashTokenizer, load_tokenizer
    from chiaswarm_tpu.models.unet import UNet
    from chiaswarm_tpu.pipelines.cascade import CascadeComponents

    checkpoint_dir = Path(checkpoint_dir)
    params = {
        "t5": convert_t5(read_torch_weights(checkpoint_dir / "text_encoder")),
        "unet1": convert_unet(read_torch_weights(checkpoint_dir / "unet"),
                              family.stage1),
        "unet2": convert_unet(read_torch_weights(checkpoint_dir / "unet_sr"),
                              family.stage2),
    }
    tokenizer = load_tokenizer(checkpoint_dir, family.t5.vocab_size,
                               family.t5.eos_token_id, family.t5.max_length,
                               pad_id=family.t5.pad_token_id, add_bos=False)
    return CascadeComponents(
        family=family, model_name=model_name, tokenizer=tokenizer,
        t5=T5Encoder(family.t5), unet1=UNet(family.stage1),
        unet2=UNet(family.stage2), params=params,
    )


# -------------------------------------------------------------- vocoder

def _fold_norm_pairs(state: Mapping[str, np.ndarray], v_suffix: str,
                     g_suffix: str) -> dict[str, np.ndarray]:
    """Fold torch weight-norm pairs (g, v) into plain ``weight`` tensors:
    w = g * v / ||v|| (norm over non-dim-0 axes, torch's default dim=0)."""
    out: dict[str, np.ndarray] = {}
    for key, value in state.items():
        if key.endswith(v_suffix):
            base = key[: -len(v_suffix)]
            g = state[base + g_suffix]
            v = value
            axes = tuple(range(1, v.ndim))
            norm = np.sqrt((v * v).sum(axis=axes, keepdims=True))
            out[base + ".weight"] = g * v / np.maximum(norm, 1e-12)
        elif key.endswith(g_suffix):
            continue
        else:
            out[key] = value
    return out


def _fold_weight_norm(state: Mapping[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Classic ``weight_g``/``weight_v`` spelling."""
    return _fold_norm_pairs(state, ".weight_v", ".weight_g")


def convert_hifigan(state: Mapping[str, np.ndarray],
                    num_resblock_kernels: int) -> dict:
    """transformers ``SpeechT5HifiGan`` state dict -> models/vocoder.py tree.

    Torch layouts: Conv1d (O, I, K) -> (K, I, O); ConvTranspose1d
    (I, O, K) -> (K, I, O). The flat ``resblocks.{k}`` list unrolls to
    ``resblocks_{k // K}_{k % K}`` (K = number of resblock kernel sizes)."""
    state = _fold_weight_norm(state)
    flat: dict[str, np.ndarray] = {}
    for key, value in state.items():
        parts = key.split(".")
        name = parts[-1]
        body = parts[:-1]
        if body[0] in ("conv_pre", "conv_post"):
            path = body[0]
        elif body[0] == "upsampler":
            path = f"upsampler_{body[1]}"
        elif body[0] == "resblocks":
            k = int(body[1])
            up, kern = divmod(k, num_resblock_kernels)
            path = f"resblocks_{up}_{kern}/{body[2]}_{body[3]}"
        else:
            log.debug("hifigan conversion skipped %s", key)
            continue
        if name == "weight":
            if body[0] == "upsampler":
                # ConvTranspose1d (I, O, K) -> (K, I, O), spatially flipped:
                # torch conv_transpose is the conv gradient (flipped kernel),
                # flax ConvTranspose is a plain dilated correlation
                flat[f"{path}/kernel"] = value.transpose(2, 0, 1)[::-1]
            else:                        # Conv1d (O, I, K)
                flat[f"{path}/kernel"] = value.transpose(2, 1, 0)
        elif name == "bias":
            flat[f"{path}/bias"] = value
    return _nest(flat)


def load_audio_checkpoint(checkpoint_dir: str | Path, model_name: str,
                          family) -> "Any":
    """AudioLDM-class snapshot -> AudioComponents. Layout: ``text_encoder/``
    (ClapTextModelWithProjection — RoBERTa tower, convert_clap_text),
    ``unet/``, ``vae/``, ``vocoder/`` (SpeechT5HifiGan)."""
    from chiaswarm_tpu.models.clap import ClapTextEncoder
    from chiaswarm_tpu.models.tokenizer import load_tokenizer
    from chiaswarm_tpu.models.unet import UNet
    from chiaswarm_tpu.models.vae import AutoencoderKL
    from chiaswarm_tpu.models.vocoder import HifiGan
    from chiaswarm_tpu.pipelines.audio import AudioComponents

    checkpoint_dir = Path(checkpoint_dir)
    params = {
        "text_encoder": convert_clap_text(
            read_torch_weights(checkpoint_dir / "text_encoder")),
        "unet": convert_unet(read_torch_weights(checkpoint_dir / "unet"),
                             family.unet),
        "vae": convert_vae(read_torch_weights(checkpoint_dir / "vae"),
                           family.vae),
        "vocoder": convert_hifigan(
            read_torch_weights(checkpoint_dir / "vocoder"),
            len(family.vocoder.resblock_kernel_sizes)),
    }
    tokenizer = load_tokenizer(checkpoint_dir,
                               family.text_encoder.vocab_size,
                               family.text_encoder.eos_token_id,
                               family.text_encoder.max_length,
                               bos_id=family.text_encoder.bos_token_id,
                               pad_id=family.text_encoder.pad_token_id)
    return AudioComponents(
        family=family, model_name=model_name, tokenizer=tokenizer,
        text_encoder=ClapTextEncoder(family.text_encoder),
        unet=UNet(family.unet), vae=AutoencoderKL(family.vae),
        vocoder=HifiGan(family.vocoder), params=params,
    )


# ------------------------------------------------------- safety checker

def convert_clip_vision(state: Mapping[str, np.ndarray]) -> dict:
    """transformers ``CLIPVisionModelWithProjection`` state dict ->
    ClipVisionEncoder params (models/clip.py). The image-conditioning
    tower of SVD-class img2vid (the trunk nests under ``vision_model.``;
    the safety checker's nests one level deeper — convert_safety_checker)."""
    flat: dict[str, np.ndarray] = {}
    trunk = "vision_model."
    for key, value in state.items():
        if key == "visual_projection.weight":
            flat["visual_projection/kernel"] = value.T
            continue
        if not key.startswith(trunk):
            log.debug("clip vision conversion skipped %s", key)
            continue
        rest = key[len(trunk):]
        parts = rest.split(".")
        name = parts[-1]
        body = parts[:-1]
        if body[:2] == ["embeddings", "class_embedding"] or \
                rest == "embeddings.class_embedding":
            flat["class_embedding"] = value
        elif body[:2] == ["embeddings", "patch_embedding"]:
            flat["patch_embedding/kernel"] = value.transpose(2, 3, 1, 0)
        elif body[:2] == ["embeddings", "position_embedding"]:
            flat["position_embedding/embedding"] = value
        elif body[:1] == ["pre_layrnorm"]:
            _place(flat, "pre_layrnorm", name, value)
        elif body[:1] == ["post_layernorm"]:
            _place(flat, "post_layernorm", name, value)
        elif body[:2] == ["encoder", "layers"]:
            i, sub = body[2], body[3]
            if sub == "self_attn":
                _place(flat, f"layers_{i}/self_attn/{body[4]}", name, value)
            elif sub in ("layer_norm1", "layer_norm2"):
                _place(flat, f"layers_{i}/{sub}", name, value)
            elif sub == "mlp":
                _place(flat, f"layers_{i}/{body[4]}", name, value)
        else:
            log.debug("clip vision conversion skipped %s", key)
    return _nest(flat)


def convert_safety_checker(state: Mapping[str, np.ndarray],
                           ) -> tuple[dict, dict[str, np.ndarray]]:
    """``StableDiffusionSafetyChecker`` state dict -> (ClipVisionEncoder
    params, concept buffers). ONE pass over the file: the CLIP vision
    trunk (nested under ``vision_model.vision_model.``), the visual
    projection, and the four concept-embedding buffers."""
    flat: dict[str, np.ndarray] = {}
    buffers: dict[str, np.ndarray] = {}
    trunk = "vision_model.vision_model."
    for key, value in state.items():
        if key in ("concept_embeds", "concept_embeds_weights",
                   "special_care_embeds", "special_care_embeds_weights"):
            buffers[key] = value
            continue
        if key == "visual_projection.weight":
            flat["visual_projection/kernel"] = value.T
            continue
        if not key.startswith(trunk):
            log.debug("safety checker conversion skipped %s", key)
            continue
        rest = key[len(trunk):]
        parts = rest.split(".")
        name = parts[-1]
        body = parts[:-1]
        if body[:2] == ["embeddings", "class_embedding"] or \
                rest == "embeddings.class_embedding":
            flat["class_embedding"] = value
        elif body[:2] == ["embeddings", "patch_embedding"]:
            flat["patch_embedding/kernel"] = value.transpose(2, 3, 1, 0)
        elif body[:2] == ["embeddings", "position_embedding"]:
            flat["position_embedding/embedding"] = value
        elif body[:1] == ["pre_layrnorm"]:
            _place(flat, "pre_layrnorm", name, value)
        elif body[:1] == ["post_layernorm"]:
            _place(flat, "post_layernorm", name, value)
        elif body[:2] == ["encoder", "layers"]:
            i, sub = body[2], body[3]
            if sub == "self_attn":
                _place(flat, f"layers_{i}/self_attn/{body[4]}", name, value)
            elif sub in ("layer_norm1", "layer_norm2"):
                _place(flat, f"layers_{i}/{sub}", name, value)
            elif sub == "mlp":
                _place(flat, f"layers_{i}/{body[4]}", name, value)
    missing = [k for k in ("concept_embeds", "concept_embeds_weights",
                           "special_care_embeds",
                           "special_care_embeds_weights")
               if k not in buffers]
    if missing:
        raise ValueError(f"safety checker state is missing {missing}")
    return _nest(flat), buffers


# ------------------------------------------------------------- top level

_SUBDIR_CANDIDATES = {
    "unet": ("unet",),
    "vae": ("vae",),
    "text_encoder_0": ("text_encoder",),
    "text_encoder_1": ("text_encoder_2",),
}


def load_checkpoint(checkpoint_dir: str | Path,
                    family: ModelFamily) -> dict[str, Any]:
    """HF pipeline snapshot dir -> Components.params tree (float32 host)."""
    checkpoint_dir = Path(checkpoint_dir)
    params: dict[str, Any] = {}

    params["unet"] = convert_unet(
        read_torch_weights(checkpoint_dir / "unet"), family.unet
    )
    params["vae"] = convert_vae(
        read_torch_weights(checkpoint_dir / "vae"), family.vae
    )
    for i in range(len(family.text_encoders)):
        sub = _SUBDIR_CANDIDATES[f"text_encoder_{i}"][0]
        params[f"text_encoder_{i}"] = convert_text_encoder(
            read_torch_weights(checkpoint_dir / sub)
        )
    return params


# ------------------------------------------------------------------ BLIP

def _blip_linear(flat: dict, state: Mapping[str, np.ndarray],
                 torch_key: str, name: str) -> None:
    flat[f"{name}/kernel"] = np.ascontiguousarray(state[f"{torch_key}.weight"].T)
    if f"{torch_key}.bias" in state:
        flat[f"{name}/bias"] = state[f"{torch_key}.bias"]


def _blip_ln(flat: dict, state: Mapping[str, np.ndarray],
             torch_key: str, name: str) -> None:
    flat[f"{name}/scale"] = state[f"{torch_key}.weight"]
    flat[f"{name}/bias"] = state[f"{torch_key}.bias"]


def convert_blip_vision(state: Mapping[str, np.ndarray],
                        prefix: str = "vision_model.") -> dict:
    """HF ``BlipVisionModel`` state dict -> models/blip.py vision tree."""
    s = {k[len(prefix):]: v for k, v in state.items() if k.startswith(prefix)}
    flat: dict[str, np.ndarray] = {}
    flat["class_embedding"] = s["embeddings.class_embedding"].reshape(-1)
    flat["position_embedding"] = s["embeddings.position_embedding"].reshape(
        s["embeddings.position_embedding"].shape[-2:])
    flat["patch_embedding/kernel"] = s[
        "embeddings.patch_embedding.weight"].transpose(2, 3, 1, 0)
    if "embeddings.patch_embedding.bias" in s:
        flat["patch_embedding/bias"] = s["embeddings.patch_embedding.bias"]
    n_layers = 1 + max(int(k.split(".")[2]) for k in s
                       if k.startswith("encoder.layers."))
    for i in range(n_layers):
        t = f"encoder.layers.{i}"
        f = f"layers_{i}"
        _blip_ln(flat, s, f"{t}.layer_norm1", f"{f}/layer_norm1")
        _blip_ln(flat, s, f"{t}.layer_norm2", f"{f}/layer_norm2")
        _blip_linear(flat, s, f"{t}.self_attn.qkv", f"{f}/qkv")
        _blip_linear(flat, s, f"{t}.self_attn.projection", f"{f}/projection")
        _blip_linear(flat, s, f"{t}.mlp.fc1", f"{f}/fc1")
        _blip_linear(flat, s, f"{t}.mlp.fc2", f"{f}/fc2")
    _blip_ln(flat, s, "post_layernorm", "post_layernorm")
    return _nest(flat)


def convert_blip_text(state: Mapping[str, np.ndarray], prefix: str,
                      with_lm_head: bool = True) -> dict:
    """HF ``BlipTextModel``/``BlipTextLMHeadModel`` -> models/blip.py text
    tree. ``prefix`` is e.g. ``"text_decoder."`` (caption head) or
    ``"text_encoder."`` (VQA question tower, ``with_lm_head=False``)."""
    s = {k[len(prefix):]: v for k, v in state.items() if k.startswith(prefix)}
    # LM-head models nest the trunk under "bert."
    if any(k.startswith("bert.") for k in s):
        trunk = {k[len("bert."):]: v for k, v in s.items()
                 if k.startswith("bert.")}
    else:
        trunk = s
    flat: dict[str, np.ndarray] = {}
    flat["word_embeddings/embedding"] = trunk["embeddings.word_embeddings.weight"]
    flat["position_embeddings"] = trunk["embeddings.position_embeddings.weight"]
    _blip_ln(flat, trunk, "embeddings.LayerNorm", "embed_ln")
    n_layers = 1 + max(int(k.split(".")[2]) for k in trunk
                       if k.startswith("encoder.layer."))
    for i in range(n_layers):
        t = f"encoder.layer.{i}"
        f = f"layer_{i}"
        _blip_linear(flat, trunk, f"{t}.attention.self.query", f"{f}/self_query")
        _blip_linear(flat, trunk, f"{t}.attention.self.key", f"{f}/self_key")
        _blip_linear(flat, trunk, f"{t}.attention.self.value", f"{f}/self_value")
        _blip_linear(flat, trunk, f"{t}.attention.output.dense", f"{f}/self_out")
        _blip_ln(flat, trunk, f"{t}.attention.output.LayerNorm", f"{f}/self_ln")
        if f"{t}.crossattention.self.query.weight" in trunk:
            _blip_linear(flat, trunk, f"{t}.crossattention.self.query",
                         f"{f}/cross_query")
            _blip_linear(flat, trunk, f"{t}.crossattention.self.key",
                         f"{f}/cross_key")
            _blip_linear(flat, trunk, f"{t}.crossattention.self.value",
                         f"{f}/cross_value")
            _blip_linear(flat, trunk, f"{t}.crossattention.output.dense",
                         f"{f}/cross_out")
            _blip_ln(flat, trunk, f"{t}.crossattention.output.LayerNorm",
                     f"{f}/cross_ln")
        _blip_linear(flat, trunk, f"{t}.intermediate.dense",
                     f"{f}/intermediate")
        _blip_linear(flat, trunk, f"{t}.output.dense", f"{f}/output")
        _blip_ln(flat, trunk, f"{t}.output.LayerNorm", f"{f}/output_ln")
    if with_lm_head:
        _blip_linear(flat, s, "cls.predictions.transform.dense",
                     "head_transform")
        _blip_ln(flat, s, "cls.predictions.transform.LayerNorm", "head_ln")
        # decoder weight may be tied to the word embeddings and absent
        # from the serialized state (tie_word_embeddings)
        dec_w = s.get("cls.predictions.decoder.weight",
                      trunk["embeddings.word_embeddings.weight"])
        flat["decoder/kernel"] = np.ascontiguousarray(dec_w.T)
        flat["decoder/bias"] = s.get("cls.predictions.decoder.bias",
                                     s["cls.predictions.bias"])
    return _nest(flat)


# -------------------------------------------------------------- OpenPose

def convert_openpose(state: Mapping[str, np.ndarray]) -> dict:
    """CMU ``body_pose_model.pth`` (controlnet_aux layout: ``model0.conv1_1
    .weight`` / ``model2_1.Mconv1_stage2_L1.weight`` ...) -> the
    models/openpose.py BodyPoseNet tree. Conv names are globally unique in
    the CMU graph, so the torch submodule prefix is dropped."""
    flat: dict[str, np.ndarray] = {}
    for key, value in state.items():
        parts = key.split(".")
        if len(parts) < 2 or parts[-1] not in ("weight", "bias"):
            continue
        name = parts[-2]
        if not (name.startswith("conv") or name.startswith("Mconv")):
            continue
        if parts[-1] == "weight":
            flat[f"{name}/kernel"] = value.transpose(2, 3, 1, 0)
        else:
            flat[f"{name}/bias"] = value
    n_convs = len({k.split("/")[0] for k in flat})
    if n_convs != 92:  # 12 trunk + 2x5 stage-1 + 5x2x7 refinement convs
        raise ValueError(
            f"openpose state has {n_convs} convs, expected 92 — not a CMU "
            f"body_pose_model checkpoint")
    return _nest(flat)


# ------------------------------------------------------------------ Bark

def _fold_parametrizations(state: Mapping[str, np.ndarray]
                           ) -> dict[str, np.ndarray]:
    """Newer torch spells weight norm as ``parametrizations.weight
    .original0`` (g) / ``original1`` (v); same fold."""
    return _fold_norm_pairs(state, ".parametrizations.weight.original1",
                            ".parametrizations.weight.original0")


def _bark_layer_map(flat: dict, s: Mapping[str, np.ndarray]) -> None:
    """Shared per-layer mapping for bark's causal and fine stages (both
    use the same block layout). bark builds every linear AND layernorm
    without bias (config.bias=False); flax LayerNorm always carries one,
    so absent biases become zeros."""
    flat["wpe"] = s["position_embeds_layer.weight"]
    n_layers = 1 + max(int(k.split(".")[1]) for k in s
                       if k.startswith("layers."))
    for i in range(n_layers):
        t = f"layers.{i}"
        f = f"h_{i}"
        for ln_t, ln_f in ((f"{t}.layernorm_1", f"{f}/ln_1"),
                           (f"{t}.layernorm_2", f"{f}/ln_2")):
            flat[f"{ln_f}/scale"] = s[f"{ln_t}.weight"]
            flat[f"{ln_f}/bias"] = s.get(
                f"{ln_t}.bias", np.zeros_like(s[f"{ln_t}.weight"]))
        # HF names the attention submodule "attn"; some exports use
        # "attention" (the causal-mask buffer "attn.bias" is skipped)
        a = f"{t}.attn" if f"{t}.attn.att_proj.weight" in s \
            else f"{t}.attention"
        flat[f"{f}/attn_qkv/kernel"] = s[f"{a}.att_proj.weight"].T
        flat[f"{f}/attn_proj/kernel"] = s[f"{a}.out_proj.weight"].T
        flat[f"{f}/mlp_fc/kernel"] = s[f"{t}.mlp.in_proj.weight"].T
        flat[f"{f}/mlp_proj/kernel"] = s[f"{t}.mlp.out_proj.weight"].T
    flat["ln_f/scale"] = s["layernorm_final.weight"]
    flat["ln_f/bias"] = s.get("layernorm_final.bias",
                              np.zeros_like(s["layernorm_final.weight"]))


def _convert_bark_gpt(s: Mapping[str, np.ndarray]) -> dict:
    """One bark causal stage (HF BarkCausalModel keys) -> models/gpt.py
    GPT tree."""
    flat: dict[str, np.ndarray] = {}
    flat["wte/embedding"] = s["input_embeds_layer.weight"]
    _bark_layer_map(flat, s)
    flat["lm_head/kernel"] = s["lm_head.weight"].T
    return _nest(flat)


def _convert_bark_fine(s: Mapping[str, np.ndarray], n_codes_total: int,
                       n_codes_given: int) -> dict:
    """HF BarkFineModel keys -> models/gpt.py FineGPT tree. Absent (tied)
    lm_heads fall back to ``input_embeds_layers[k + 1]``."""
    flat: dict[str, np.ndarray] = {}
    for k in range(n_codes_total):
        flat[f"wte_{k}/embedding"] = s[f"input_embeds_layers.{k}.weight"]
    _bark_layer_map(flat, s)
    for k in range(n_codes_total - n_codes_given):
        head = s.get(f"lm_heads.{k}.weight",
                     s[f"input_embeds_layers.{k + 1}.weight"])
        flat[f"lm_head_{k}/kernel"] = head.T
    return _nest(flat)


def convert_encodec_decoder(s: Mapping[str, np.ndarray],
                            codec_config) -> dict:
    """HF ``EncodecModel`` quantizer + decoder keys (weight norm already
    folded) -> models/codec.py CodecDecoder tree. Layer indices are
    positional (ELUs occupy torch ModuleList slots, mirrored flax-side);
    the transposed-conv slots are derived from the config's layer
    structure (idx 0 conv, idx 1 lstm, then per upsampling ratio:
    ELU, ConvTranspose, num_residual_layers resnet units)."""
    nres = codec_config.num_residual_layers
    transpose_slots = {2 + r * (2 + nres) + 1
                       for r in range(len(codec_config.upsampling_ratios))}
    flat: dict[str, np.ndarray] = {}
    for key, value in s.items():
        parts = key.split(".")
        if parts[0] == "quantizer":
            # quantizer.layers.{k}.codebook.embed
            if parts[-1] == "embed":
                flat[f"codebook_{parts[2]}/embedding"] = value
            continue
        if parts[0] != "decoder":
            continue
        idx = parts[2]
        rest = parts[3:]
        base = f"layers_{idx}"
        if rest[0] == "lstm":
            flat[f"{base}/{rest[1]}"] = value
        elif rest[0] == "conv":
            if rest[-1] == "weight":
                if value.ndim != 3:
                    continue  # buffers (stride etc.)
                # decoder ConvTranspose weights are (in, out, k); plain
                # convs are (out, in, k) — both land as (k, in, out)
                # (ConvTranspose orientation validated by the torch
                # fidelity test)
                if int(idx) in transpose_slots:
                    flat[f"{base}/conv/kernel"] = value.transpose(2, 0, 1)
                else:
                    flat[f"{base}/conv/kernel"] = value.transpose(2, 1, 0)
            elif rest[-1] == "bias":
                flat[f"{base}/conv/bias"] = value
        elif rest[0] in ("block", "shortcut"):
            sub = "shortcut" if rest[0] == "shortcut" else f"block_{rest[1]}"
            leaf = rest[-1]
            inner = f"{base}/{sub}/conv"
            if leaf == "weight" and value.ndim == 3:
                flat[f"{inner}/kernel"] = value.transpose(2, 1, 0)
            elif leaf == "bias":
                flat[f"{inner}/bias"] = value
    return _nest(flat)


def convert_bark(state: Mapping[str, np.ndarray], family) -> dict:
    """Full HF ``BarkModel`` state dict -> TTSComponents.params
    (semantic / coarse / fine / codec trees)."""
    state = _fold_parametrizations(_fold_weight_norm(state))

    def sub(prefix: str) -> dict[str, np.ndarray]:
        return {k[len(prefix):]: v for k, v in state.items()
                if k.startswith(prefix)}

    return {
        "semantic": _convert_bark_gpt(sub("semantic.")),
        "coarse": _convert_bark_gpt(sub("coarse_acoustics.")),
        "fine": _convert_bark_fine(sub("fine_acoustics."),
                                   family.n_fine, 1),
        "codec": convert_encodec_decoder(sub("codec_model."),
                                         family.codec),
    }


# ------------------------------------------------------------------- HED

def convert_hed(state: Mapping[str, np.ndarray]) -> dict:
    """``ControlNetHED.pth`` (controlnet_aux layout: ``norm`` (1,3,1,1),
    ``block{b}.convs.{i}.weight``, ``block{b}.projection.weight``) ->
    models/hed.py HEDNetwork tree."""
    flat: dict[str, np.ndarray] = {}
    n_blocks = 0
    for key, value in state.items():
        parts = key.split(".")
        if parts[-1] not in ("weight", "bias") and key != "norm":
            continue
        if key == "norm":
            flat["norm"] = value.reshape(-1)
            continue
        block = parts[0]
        if not re.fullmatch(r"block\d+", block) or len(parts) < 3:
            continue
        n_blocks = max(n_blocks, int(block[5:]))
        if parts[1] == "convs":
            name = f"{block}/convs_{parts[2]}"
        elif parts[1] == "projection":
            name = f"{block}/projection"
        else:
            continue
        if parts[-1] == "weight":
            flat[f"{name}/kernel"] = value.transpose(2, 3, 1, 0)
        else:
            flat[f"{name}/bias"] = value
    if n_blocks != 5 or "norm" not in flat:
        raise ValueError(
            f"state has {n_blocks} HED blocks (expected 5)"
            + ("" if "norm" in flat else " and no 'norm' parameter")
            + " — not a ControlNetHED checkpoint")
    return _nest(flat)


# ------------------------------------------------------------------ MLSD

def convert_mlsd(state: Mapping[str, np.ndarray]) -> dict:
    """mlsd_pytorch ``MobileV2_MLSD_Large`` state (``mlsd_large_512_fp32``
    via controlnet_aux MLSDdetector: ``backbone.features.{i}`` MobileNetV2
    trunk + ``block15..block23`` decoder) -> models/mlsd.py MLSDNetwork
    tree."""
    flat: dict[str, np.ndarray] = {}

    def conv(v: np.ndarray) -> np.ndarray:
        return v.transpose(2, 3, 1, 0)  # OIHW -> HWIO (dw convs included)

    bn_leaf = {"weight": "scale", "bias": "bias",
               "running_mean": "mean", "running_var": "var"}

    def put_bn(prefix: str, leaf: str, v: np.ndarray) -> None:
        if leaf in bn_leaf:
            flat[f"{prefix}/{bn_leaf[leaf]}"] = v

    n_ir = 0
    for key, value in state.items():
        parts = key.split(".")
        leaf = parts[-1]
        if leaf == "num_batches_tracked":
            continue
        if key.startswith("backbone.features."):
            i = int(parts[2])
            if i == 0:  # stem ConvBNReLU
                if parts[3] == "0":
                    flat["stem/conv/kernel"] = conv(value)
                else:
                    put_bn("stem/bn", leaf, value)
                continue
            n_ir = max(n_ir, i)
            sub = parts[4]  # index inside .conv Sequential
            # t=1 block (features.1) has no expand stage: [dw, bn] at
            # conv.0, project at conv.1, bn at conv.2; t=6 blocks add the
            # expand ConvBNReLU at conv.0 and shift everything down
            expanded = f"backbone.features.{i}.conv.3.weight" in state \
                or f"backbone.features.{i}.conv.3.running_mean" in state
            seq = {"0": ("layer_0", True), "1": ("layer_1", True),
                   "2": ("project", False), "3": ("project_bn", None)} \
                if expanded else \
                  {"0": ("layer_0", True), "1": ("project", False),
                   "2": ("project_bn", None)}
            name, is_cbr = seq[sub]
            if is_cbr:  # ConvBNReLU: .0 conv / .1 bn below it
                if parts[5] == "0":
                    flat[f"ir_{i}/{name}/conv/kernel"] = conv(value)
                else:
                    put_bn(f"ir_{i}/{name}/bn", leaf, value)
            elif is_cbr is False:  # plain projection conv
                flat[f"ir_{i}/{name}/kernel"] = conv(value)
            else:  # projection BN
                put_bn(f"ir_{i}/{name}", leaf, value)
        elif parts[0].startswith("block"):
            block = parts[0]
            if parts[1] == "conv3":  # BlockTypeC head conv (with bias)
                flat[f"{block}/conv3/kernel" if leaf == "weight"
                     else f"{block}/conv3/bias"] = (
                    conv(value) if leaf == "weight" else value)
                continue
            which, idx = parts[1], parts[2]
            if idx == "0":  # conv
                if leaf == "weight":
                    flat[f"{block}/{which}/conv/kernel"] = conv(value)
                else:
                    flat[f"{block}/{which}/conv/bias"] = value
            else:  # bn
                put_bn(f"{block}/{which}/bn", leaf, value)
    if n_ir != 13 or "block23/conv3/kernel" not in flat:
        raise ValueError(
            f"state has {n_ir} inverted-residual blocks (expected 13)"
            + ("" if "block23/conv3/kernel" in flat
               else " and no block23 head")
            + " — not a MobileV2_MLSD_Large checkpoint")
    return _nest(flat)


# --------------------------------------------------------------- Lineart

def convert_lineart(state: Mapping[str, np.ndarray]) -> dict:
    """informative-drawings ``Generator`` state (``sk_model.pth`` via
    controlnet_aux LineartDetector: ``model0.1`` stem conv, ``model1.{0,3}``
    downsamples, ``model2.{i}.conv_block.{1,5}`` residual convs,
    ``model3.{0,3}`` transposed convs, ``model4.1`` head) ->
    models/lineart.py LineartGenerator tree.

    ConvTranspose2d weights (in, out, kh, kw) are stored pre-flipped as
    (kh, kw, in, out) so runtime is a plain lhs-dilated conv
    (models/lineart.py TorchConvTranspose)."""
    flat: dict[str, np.ndarray] = {}
    n_res = 0

    def conv(value: np.ndarray) -> np.ndarray:
        return value.transpose(2, 3, 1, 0)  # OIHW -> HWIO

    def convt(value: np.ndarray) -> np.ndarray:
        return value.transpose(2, 3, 0, 1)[::-1, ::-1].copy()  # + flip

    for key, value in state.items():
        parts = key.split(".")
        if parts[-1] not in ("weight", "bias"):
            continue
        leaf = "kernel" if parts[-1] == "weight" else "bias"
        w = value.ndim == 4
        if key.startswith("model0.1."):
            flat[f"stem/conv/{leaf}"] = conv(value) if w else value
        elif key.startswith("model1."):
            idx = {"0": 0, "3": 1}.get(parts[1])
            if idx is not None:
                flat[f"down_{idx}/{leaf}"] = conv(value) if w else value
        elif key.startswith("model2.") and parts[2] == "conv_block":
            i = int(parts[1])
            n_res = max(n_res, i + 1)
            which = {"1": "conv_a", "5": "conv_b"}.get(parts[3])
            if which is not None:
                flat[f"res_{i}/{which}/conv/{leaf}"] = (conv(value)
                                                        if w else value)
        elif key.startswith("model3."):
            idx = {"0": 0, "3": 1}.get(parts[1])
            if idx is not None:
                flat[f"up_{idx}/{leaf}"] = convt(value) if w else value
        elif key.startswith("model4.1."):
            flat[f"head/conv/{leaf}"] = conv(value) if w else value
    if n_res == 0 or "stem/conv/kernel" not in flat:
        raise ValueError("state is not an informative-drawings Generator "
                         "(no model2 residual blocks / model0 stem)")
    return _nest(flat)


# ------------------------------------------------------------------- DPT

def convert_dpt(state: Mapping[str, np.ndarray]) -> dict:
    """HF ``DPTForDepthEstimation`` (plain-ViT backbone) state dict ->
    models/dpt.py DPTDepth tree."""
    flat: dict[str, np.ndarray] = {}
    s = state
    flat["cls_token"] = s["dpt.embeddings.cls_token"]
    flat["position_embeddings"] = s["dpt.embeddings.position_embeddings"][0]
    _place(flat, "patch_embedding", "weight",
           s["dpt.embeddings.patch_embeddings.projection.weight"])
    flat["patch_embedding/bias"] = s[
        "dpt.embeddings.patch_embeddings.projection.bias"]

    n_layers = 1 + max(
        int(k.split(".")[3]) for k in s if k.startswith("dpt.encoder.layer."))
    for i in range(n_layers):
        t = f"dpt.encoder.layer.{i}"
        f = f"layer_{i}"
        for name, torch_name in (
                ("query", "attention.attention.query"),
                ("key", "attention.attention.key"),
                ("value", "attention.attention.value"),
                ("attn_out", "attention.output.dense"),
                ("intermediate", "intermediate.dense"),
                ("output", "output.dense")):
            _blip_linear(flat, s, f"{t}.{torch_name}", f"{f}/{name}")
        for ln in ("layernorm_before", "layernorm_after"):
            _blip_ln(flat, s, f"{t}.{ln}", f"{f}/{ln}")

    n_stages = 1 + max(
        int(k.split(".")[2]) for k in s if k.startswith("neck.convs."))
    for i in range(n_stages):
        _blip_linear(flat, s, f"neck.reassemble_stage.readout_projects.{i}.0",
                     f"readout_{i}")
        _place(flat, f"reassemble_proj_{i}", "weight",
               s[f"neck.reassemble_stage.layers.{i}.projection.weight"])
        flat[f"reassemble_proj_{i}/bias"] = s[
            f"neck.reassemble_stage.layers.{i}.projection.bias"]
        rkey = f"neck.reassemble_stage.layers.{i}.resize.weight"
        if rkey in s:
            w = s[rkey]
            bias = s[f"neck.reassemble_stage.layers.{i}.resize.bias"]
            if w.shape[-1] == 3:  # 3x3 stride-2 downsample conv (O,I,3,3)
                flat[f"reassemble_resize_{i}/kernel"] = w.transpose(
                    2, 3, 1, 0)
            else:                 # ConvTranspose2d (I,O,k,k) -> (k,k,I,O)
                # SPATIALLY FLIPPED: torch conv_transpose is the conv
                # gradient (flipped kernel); flax ConvTranspose is a plain
                # fractionally-strided correlation. The tiny harness hid
                # the orientation error under its 0.05-scale weights —
                # caught by the published-config DPT-large run.
                flat[f"reassemble_resize_{i}/kernel"] = np.ascontiguousarray(
                    w.transpose(2, 3, 0, 1)[::-1, ::-1])
            flat[f"reassemble_resize_{i}/bias"] = bias
        _place(flat, f"neck_conv_{i}", "weight",
               s[f"neck.convs.{i}.weight"])

        t = f"neck.fusion_stage.layers.{i}"
        _place(flat, f"fusion_{i}_proj", "weight",
               s[f"{t}.projection.weight"])
        flat[f"fusion_{i}_proj/bias"] = s[f"{t}.projection.bias"]
        for res, fres in (("residual_layer1", "res1"),
                          ("residual_layer2", "res2")):
            if i == 0 and fres == "res1":
                continue  # first fusion layer is called without a residual
            for conv in ("convolution1", "convolution2"):
                key = f"{t}.{res}.{conv}.weight"
                name = f"fusion_{i}_{fres}_conv{conv[-1]}"
                _place(flat, name, "weight", s[key])
                bkey = f"{t}.{res}.{conv}.bias"
                if bkey in s:
                    flat[f"{name}/bias"] = s[bkey]

    for idx, name in ((0, "head_conv1"), (2, "head_conv2"),
                      (4, "head_conv3")):
        _place(flat, name, "weight", s[f"head.head.{idx}.weight"])
        flat[f"{name}/bias"] = s[f"head.head.{idx}.bias"]
    return _nest(flat)


# --------------------------------------------------------------- UperNet

def _bnconv(flat: dict, s: Mapping[str, np.ndarray], torch_base: str,
            name: str) -> None:
    _place(flat, f"{name}/conv", "weight", s[f"{torch_base}.conv.weight"])
    flat[f"{name}/bn_scale"] = s[f"{torch_base}.batch_norm.weight"]
    flat[f"{name}/bn_bias"] = s[f"{torch_base}.batch_norm.bias"]
    flat[f"{name}/bn_mean"] = s[f"{torch_base}.batch_norm.running_mean"]
    flat[f"{name}/bn_var"] = s[f"{torch_base}.batch_norm.running_var"]


def convert_upernet(state: Mapping[str, np.ndarray]) -> dict:
    """HF ``UperNetForSemanticSegmentation`` (ConvNeXt backbone) state
    dict -> models/upernet.py UperNetSeg tree (auxiliary FCN head keys
    are ignored — inference uses the decode head only)."""
    s = state
    flat: dict[str, np.ndarray] = {}
    _place(flat, "patch_embed", "weight",
           s["backbone.embeddings.patch_embeddings.weight"])
    flat["patch_embed/bias"] = s["backbone.embeddings.patch_embeddings.bias"]
    flat["embed_norm/scale"] = s["backbone.embeddings.layernorm.weight"]
    flat["embed_norm/bias"] = s["backbone.embeddings.layernorm.bias"]

    n_stages = 1 + max(int(k.split(".")[3]) for k in s
                       if k.startswith("backbone.encoder.stages."))
    for st in range(n_stages):
        t = f"backbone.encoder.stages.{st}"
        if f"{t}.downsampling_layer.0.weight" in s:
            flat[f"down_norm_{st}/scale"] = s[
                f"{t}.downsampling_layer.0.weight"]
            flat[f"down_norm_{st}/bias"] = s[
                f"{t}.downsampling_layer.0.bias"]
            _place(flat, f"down_conv_{st}", "weight",
                   s[f"{t}.downsampling_layer.1.weight"])
            flat[f"down_conv_{st}/bias"] = s[
                f"{t}.downsampling_layer.1.bias"]
        n_layers = 1 + max(int(k.split(".")[5]) for k in s
                           if k.startswith(f"{t}.layers."))
        for i in range(n_layers):
            lt = f"{t}.layers.{i}"
            f = f"stage{st}_layer{i}"
            # torch depthwise conv weight (C, 1, 7, 7) -> flax grouped
            # conv kernel (7, 7, 1, C)
            flat[f"{f}/dwconv/kernel"] = s[f"{lt}.dwconv.weight"
                                           ].transpose(2, 3, 1, 0)
            flat[f"{f}/dwconv/bias"] = s[f"{lt}.dwconv.bias"]
            flat[f"{f}/layernorm/scale"] = s[f"{lt}.layernorm.weight"]
            flat[f"{f}/layernorm/bias"] = s[f"{lt}.layernorm.bias"]
            _blip_linear(flat, s, f"{lt}.pwconv1", f"{f}/pwconv1")
            _blip_linear(flat, s, f"{lt}.pwconv2", f"{f}/pwconv2")
            if f"{lt}.layer_scale_parameter" in s:
                flat[f"{f}/layer_scale_parameter"] = s[
                    f"{lt}.layer_scale_parameter"]
        flat[f"out_norm_{st}/scale"] = s[
            f"backbone.hidden_states_norms.stage{st + 1}.weight"]
        flat[f"out_norm_{st}/bias"] = s[
            f"backbone.hidden_states_norms.stage{st + 1}.bias"]

    n_psp = 1 + max(int(k.split(".")[2]) for k in s
                    if k.startswith("decode_head.psp_modules."))
    for k in range(n_psp):
        _bnconv(flat, s, f"decode_head.psp_modules.{k}.1", f"psp_{k}")
    _bnconv(flat, s, "decode_head.bottleneck", "bottleneck")
    for i in range(n_stages - 1):
        _bnconv(flat, s, f"decode_head.lateral_convs.{i}", f"lateral_{i}")
        _bnconv(flat, s, f"decode_head.fpn_convs.{i}", f"fpn_{i}")
    _bnconv(flat, s, "decode_head.fpn_bottleneck", "fpn_bottleneck")
    _place(flat, "classifier", "weight", s["decode_head.classifier.weight"])
    flat["classifier/bias"] = s["decode_head.classifier.bias"]
    return _nest(flat)
