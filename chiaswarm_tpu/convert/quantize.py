"""int8 scale-per-channel weight residency (``CHIASWARM_WEIGHTS=int8``).

Half of the ISSUE-8 capacity lever: the residency ledger decides WHICH
models stay in HBM; this module multiplies HOW MANY fit by storing the
big weight matrices as int8 codes plus one float scale per output
channel (~4x smaller than fp32 checkpoints, ~2x smaller than the bf16
serving default). Dequantization happens AT USE, inside the jitted
programs: each quantized leaf rides the param tree as an
:class:`Int8Param` pytree node (children ``q`` int8 + ``scale`` f32, so
jit treats them as ordinary inputs and HBM holds the int8 bytes), and
the pipelines' traced functions call :func:`dequantize_tree` first —
XLA fuses the ``convert * scale`` into the consuming matmul/conv where
it can, and the bf16 copies are transient program temporaries, never
residency.

Scope: the diffusion families (``kind == "sd"``) and their ControlNet
bundles — the checkpoint classes the catalog multiplies — gated by the
forward-parity tests in tests/test_residency.py. Multi-chip (sharded)
placements stay fp: the sharding rules match fp param paths
(parallel/sharding.py), so :func:`maybe_quantize_params` declines when
the target mesh has more than one device.

Quantization rule: per-OUTPUT-channel absmax scaling over every other
axis (dense kernels are ``(in, out)``, NHWC convs ``(kh, kw, in, out)``
— the last axis is the output channel everywhere in this stack), codes
clipped to [-127, 127]. Leaves below :data:`MIN_QUANT_SIZE` elements or
with ndim < 2 (biases, norm gains, time embeddings) stay fp — they are
noise in the byte count and precision-critical.

Activations (ISSUE 18, the other half of the low-precision arc):
``CHIASWARM_ACTIVATIONS=int8|fp8`` routes the attention q/k/v operands
(ops/attention.py) and the UNet block inputs (pipelines/diffusion.py)
through :func:`fake_quant_activation` — per-TENSOR dynamic absmax
scaling computed inside the traced program (activations have no ahead-
of-time calibration moment the way weights do), quantize + dequant at
use so the surrounding program stays in its serving dtype while XLA is
free to keep the int8/fp8 codes feeding the matmul on hardware that
eats them. fp8 engages only where :func:`core.compat.fp8_supported`
says the chip has it; elsewhere the knob degrades to int8 with a
one-time warning. Default off: the knob reads at TRACE time and
``core.compile_cache.static_cache_key`` folds the format in only when
enabled, so default-off executables stay byte-identical.
"""

from __future__ import annotations

import logging
import os
from typing import Any

import jax
import jax.numpy as jnp

log = logging.getLogger("chiaswarm.quantize")

ENV_WEIGHTS = "CHIASWARM_WEIGHTS"

#: leaves smaller than this many elements stay fp (biases, layer norms)
MIN_QUANT_SIZE = 4096


def weights_format() -> str:
    """Serving weight format: ``bf16`` (default) or ``int8``."""
    raw = os.environ.get(ENV_WEIGHTS, "").strip().lower()
    return raw or "bf16"


def int8_enabled() -> bool:
    return weights_format() == "int8"


# ---------------------------------------------------------------------------
# activation quantization (CHIASWARM_ACTIVATIONS, ISSUE 18)

ENV_ACTIVATIONS = "CHIASWARM_ACTIVATIONS"

#: int8 symmetric code range and the float8_e4m3fn finite max
_INT8_MAX = 127.0
_FP8_E4M3_MAX = 448.0

_warned_fp8 = False


def activations_format() -> str:
    """Activation precision: ``off`` (default) | ``int8`` | ``fp8``.
    Read at TRACE time; fp8 degrades to int8 (warn once) when
    :func:`chiaswarm_tpu.core.compat.fp8_supported` says the backend
    has no fp8 units, so a fleet-wide env roll stays safe on mixed
    generations."""
    global _warned_fp8
    raw = os.environ.get(ENV_ACTIVATIONS, "").strip().lower()
    if raw in ("", "0", "off", "none", "bf16", "fp32"):
        return "off"
    if raw == "fp8":
        from chiaswarm_tpu.core import compat

        if not compat.fp8_supported():
            if not _warned_fp8:
                _warned_fp8 = True
                log.warning(
                    "%s=fp8 requested but this backend has no fp8 "
                    "support (compat.fp8_supported() is False); "
                    "degrading to int8 activations", ENV_ACTIVATIONS)
            return "int8"
        return "fp8"
    if raw == "int8":
        return "int8"
    log.warning("%s=%r not understood (off|int8|fp8); activations stay fp",
                ENV_ACTIVATIONS, raw)
    return "off"


def activations_enabled() -> bool:
    return activations_format() != "off"


def fake_quant_activation(x: Any, *, tag: str | None = None) -> Any:
    """Per-tensor dynamic-absmax quantize + dequant-at-use for one
    activation tensor, applied INSIDE the traced program. Identity when
    the knob is off (the default serving path traces unchanged) or the
    input is not a float tensor.

    int8: symmetric round-to-nearest onto [-127, 127]; fp8: scale the
    tensor so its absmax lands at the e4m3 finite max, cast through the
    fp8 dtype, and rescale — the standard per-tensor recipe. The absmax
    is computed on the live values (a traced reduction XLA fuses into
    the producer), so there is no calibration state to manage and the
    seam composes with lanes/batching of any width. When swarmlens is
    recording, the dequantized tensor is tapped as ``act.<tag>`` — the
    drill-down instrument for a quantized-vs-fp bisect pair."""
    fmt = activations_format()
    if fmt == "off":
        return x
    dtype = getattr(x, "dtype", None)
    if dtype is None or not jnp.issubdtype(dtype, jnp.floating):
        return x
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf))
    if fmt == "int8":
        scale = jnp.maximum(absmax, 1e-12) / _INT8_MAX
        q = jnp.clip(jnp.round(xf / scale), -_INT8_MAX, _INT8_MAX)
        out = (q.astype(jnp.int8).astype(jnp.float32) * scale).astype(dtype)
    else:
        from chiaswarm_tpu.core import compat

        f8 = compat.float8_dtype()
        scale = jnp.maximum(absmax, 1e-12) / _FP8_E4M3_MAX
        out = ((xf / scale).astype(f8).astype(jnp.float32)
               * scale).astype(dtype)
    if tag is not None:
        from chiaswarm_tpu.obs import numerics as _numerics

        if _numerics.enabled_for("act"):
            out = _numerics.tap(f"act.{tag}", out)
    return out


def bytes_per_param() -> int:
    """Planning density for footprint estimates (node/registry.py):
    int8 stores ~1 byte/param (scales are negligible), bf16 stores 2."""
    return 1 if int8_enabled() else 2


class Int8Param:
    """One quantized weight leaf: ``q`` int8 codes, ``scale`` f32 per
    output channel (keepdims, so ``q * scale`` broadcasts), and the
    original dtype string to dequantize back into. Registered as a jax
    pytree node: tree utilities (placement, flatten-at-jit, byte
    accounting via ``jax.tree.leaves``) see the two arrays."""

    __slots__ = ("q", "scale", "dtype")

    def __init__(self, q: Any, scale: Any, dtype: str) -> None:
        self.q = q
        self.scale = scale
        self.dtype = str(dtype)

    def dequantize(self) -> Any:
        w = self.q.astype(jnp.float32) * self.scale
        return w.astype(jnp.dtype(self.dtype))

    def __repr__(self) -> str:  # debugging/test readability
        shape = tuple(getattr(self.q, "shape", ()))
        return f"Int8Param(shape={shape}, dtype={self.dtype})"


jax.tree_util.register_pytree_node(
    Int8Param,
    lambda p: ((p.q, p.scale), p.dtype),
    lambda dtype, children: Int8Param(children[0], children[1], dtype),
)


def _is_quant(x: Any) -> bool:
    return isinstance(x, Int8Param)


def quantize_leaf(w: Any) -> Any:
    """Quantize one weight leaf (or return it unchanged when it is not
    a big float matrix). Round-to-nearest with per-output-channel
    absmax scales: |dequant - w| <= scale/2 elementwise, the bound the
    parity tests assert."""
    if _is_quant(w):
        return w
    dtype = getattr(w, "dtype", None)
    ndim = getattr(w, "ndim", 0)
    size = getattr(w, "size", 0)
    if dtype is None or not jnp.issubdtype(dtype, jnp.floating):
        return w
    if ndim < 2 or size < MIN_QUANT_SIZE:
        return w
    wf = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=tuple(range(ndim - 1)),
                     keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return Int8Param(q, scale, str(dtype))


def quantize_tree(tree: Any) -> Any:
    return jax.tree.map(quantize_leaf, tree, is_leaf=_is_quant)


def dequantize_tree(tree: Any) -> Any:
    """Inverse, called INSIDE the jitted programs (pipelines/diffusion.py)
    — a no-op identity map on fp trees, so the fp path traces
    unchanged."""
    return jax.tree.map(
        lambda x: x.dequantize() if _is_quant(x) else x, tree,
        is_leaf=_is_quant)


def quantized_leaf_count(tree: Any) -> int:
    return sum(1 for x in jax.tree.leaves(tree, is_leaf=_is_quant)
               if _is_quant(x))


def maybe_quantize_params(params: Any, *, family: Any = None,
                          mesh: Any = None) -> Any:
    """The registry's load-time gate: quantize when ``CHIASWARM_WEIGHTS=
    int8``, the family is a diffusion ("sd") family — the class the
    parity tests cover — and placement is single-device (sharded
    placements match fp param paths)."""
    if not int8_enabled():
        return params
    kind = getattr(family, "kind", "sd")
    if kind != "sd":
        return params
    if mesh is not None and getattr(mesh.devices, "size", 1) > 1:
        log.warning("CHIASWARM_WEIGHTS=int8 skipped for a %d-chip "
                    "placement (sharding specs are fp-tree-shaped); "
                    "params stay %s", mesh.devices.size,
                    "bf16/fp32")
        return params
    quantized = quantize_tree(params)
    log.info("quantized %d weight leaves to int8 scale-per-channel",
             quantized_leaf_count(quantized))
    return quantized
