"""Host-side utilities: settings, logging, image/video/audio IO, guarded fetch."""
