"""AutoencoderKL (Flax, NHWC): latent encode/decode for all SD families.

Replaces the diffusers VAE the reference runs inside its pipelines, including
the memory-pressure features it toggles on small GPUs
(swarm/diffusion/diffusion_func.py:89-92 ``enable_vae_slicing`` /
``enable_vae_tiling``): here decode can run *tiled* as a jitted scan over
fixed-size latent tiles with overlap blending — bounded VMEM/HBM at any
resolution, no Python-loop fallback.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from chiaswarm_tpu.models.configs import VAEConfig
from chiaswarm_tpu.models.common import num_groups as _num_groups
from chiaswarm_tpu.models.common import upsample2x_nearest
from chiaswarm_tpu.ops.attention import attention


class VaeResnetBlock(nn.Module):
    out_channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]), epsilon=1e-6, dtype=jnp.float32,
                         name="norm1")(x)
        h = nn.silu(h).astype(self.dtype)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    name="conv1")(h)
        h = nn.GroupNorm(num_groups=_num_groups(h.shape[-1]), epsilon=1e-6, dtype=jnp.float32,
                         name="norm2")(h)
        h = nn.silu(h).astype(self.dtype)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                        name="conv_shortcut")(x)
        return x + h


class VaeAttention(nn.Module):
    """Single-head spatial attention in the VAE mid block."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, h, w, c = x.shape
        residual = x
        x = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]), epsilon=1e-6, dtype=jnp.float32,
                         name="group_norm")(x).astype(self.dtype)
        x = x.reshape(b, h * w, c)
        q = nn.Dense(c, dtype=self.dtype, name="to_q")(x)
        k = nn.Dense(c, dtype=self.dtype, name="to_k")(x)
        v = nn.Dense(c, dtype=self.dtype, name="to_v")(x)
        out = attention(q[:, :, None, :], k[:, :, None, :], v[:, :, None, :],
                        impl="xla")[:, :, 0, :]
        out = nn.Dense(c, dtype=self.dtype, name="to_out")(out)
        return out.reshape(b, h, w, c) + residual


class VaeMid(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = VaeResnetBlock(self.channels, self.dtype, name="resnets_0")(x)
        x = VaeAttention(self.dtype, name="attentions_0")(x)
        return VaeResnetBlock(self.channels, self.dtype, name="resnets_1")(x)


class Encoder(nn.Module):
    config: VAEConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        chans = list(cfg.block_out_channels)
        x = nn.Conv(chans[0], (3, 3), padding=1, dtype=self.dtype,
                    name="conv_in")(x.astype(self.dtype))
        for level, ch in enumerate(chans):
            for j in range(cfg.layers_per_block):
                x = VaeResnetBlock(ch, self.dtype,
                                   name=f"down_{level}_resnets_{j}")(x)
            if level < len(chans) - 1:
                x = nn.Conv(ch, (3, 3), strides=(2, 2), padding=((0, 1), (0, 1)),
                            dtype=self.dtype, name=f"down_{level}_downsample")(x)
        x = VaeMid(chans[-1], self.dtype, name="mid")(x)
        x = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]), epsilon=1e-6, dtype=jnp.float32,
                         name="conv_norm_out")(x)
        x = nn.silu(x).astype(self.dtype)
        # 2x latent channels: mean + logvar moments
        x = nn.Conv(2 * cfg.latent_channels, (3, 3), padding=1,
                    dtype=jnp.float32, name="conv_out")(x)
        return nn.Conv(2 * cfg.latent_channels, (1, 1), dtype=jnp.float32,
                       name="quant_conv")(x)


class Decoder(nn.Module):
    config: VAEConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, z: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        chans = list(cfg.block_out_channels)
        z = nn.Conv(cfg.latent_channels, (1, 1), dtype=self.dtype,
                    name="post_quant_conv")(z.astype(self.dtype))
        x = nn.Conv(chans[-1], (3, 3), padding=1, dtype=self.dtype,
                    name="conv_in")(z)
        x = VaeMid(chans[-1], self.dtype, name="mid")(x)
        for rev, ch in enumerate(reversed(chans)):
            level = len(chans) - 1 - rev
            for j in range(cfg.layers_per_block + 1):
                x = VaeResnetBlock(ch, self.dtype,
                                   name=f"up_{level}_resnets_{j}")(x)
            if level > 0:
                x = upsample2x_nearest(x)
                x = nn.Conv(ch, (3, 3), padding=1, dtype=self.dtype,
                            name=f"up_{level}_upsample")(x)
        x = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]), epsilon=1e-6, dtype=jnp.float32,
                         name="conv_norm_out")(x)
        x = nn.silu(x).astype(self.dtype)
        return nn.Conv(cfg.in_channels, (3, 3), padding=1, dtype=jnp.float32,
                       name="conv_out")(x)


class VaeSpatioTemporalResBlock(nn.Module):
    """The temb-free ``SpatioTemporalResBlock`` of diffusers'
    ``TemporalDecoder`` (the SVD snapshot's VAE decoder): spatial resnet
    (eps 1e-6) -> temporal resnet (eps 1e-5) -> SWITCHED learned blend
    out = (1-a)*spatial + a*temporal, a = sigmoid(mix_factor) — the
    ``merge_strategy="learned"``/``switch_spatial_to_temporal_mix`` combo
    this decoder ships (the UNet blocks use the non-switched direction)."""

    out_channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:  # (B, F, H, W, C)
        from chiaswarm_tpu.models.video_unet import TemporalResnetBlock

        b, f = x.shape[:2]
        s = VaeResnetBlock(self.out_channels, self.dtype,
                           name="spatial")(x.reshape((-1,) + x.shape[2:]))
        s = s.reshape((b, f) + s.shape[1:])
        t = TemporalResnetBlock(self.out_channels, 1e-5, self.dtype,
                                name="temporal")(s)
        a = nn.sigmoid(self.param("mix_factor",
                                  nn.initializers.constant(0.0), (1,)))
        a = a.astype(s.dtype)
        return (1.0 - a) * s + a * t


class TemporalVaeDecoder(nn.Module):
    """diffusers ``TemporalDecoder``: the published SVD VAE decoder.
    Every resnet slot is a temb-free spatio-temporal pair; one spatial
    mid attention; a final frame-axis (3,1,1) conv (``time_conv_out``)
    after conv_out. No post_quant_conv — the latents feed conv_in
    directly (the published ``AutoencoderKLTemporalDecoder`` layout)."""

    config: VAEConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, z: jnp.ndarray) -> jnp.ndarray:  # (B, F, lh, lw, C)
        cfg = self.config
        chans = list(cfg.block_out_channels)
        if cfg.layers_per_block != 2:
            # the mid block below hardcodes the published 2-resnet +
            # 1-attention shape (MidBlockTemporalDecoder at
            # num_layers=2, the only configuration SVD ships)
            raise ValueError("TemporalVaeDecoder requires "
                             "layers_per_block=2 (the published layout)")
        b, f = z.shape[:2]

        def fold(v):
            return v.reshape((-1,) + v.shape[2:])

        def unfold(v):
            return v.reshape((b, f) + v.shape[1:])

        x = nn.Conv(chans[-1], (3, 3), padding=1, dtype=self.dtype,
                    name="conv_in")(fold(z.astype(self.dtype)))
        # mid: resnets[0] -> attention -> resnets[1] (num_layers =
        # layers_per_block; per-frame spatial attention, VAE-style)
        x = VaeSpatioTemporalResBlock(chans[-1], self.dtype,
                                      name="mid_resnets_0")(unfold(x))
        x = VaeAttention(self.dtype, name="mid_attention")(fold(x))
        x = VaeSpatioTemporalResBlock(chans[-1], self.dtype,
                                      name="mid_resnets_1")(unfold(x))
        for rev, ch in enumerate(reversed(chans)):
            level = len(chans) - 1 - rev
            for j in range(cfg.layers_per_block + 1):
                x = VaeSpatioTemporalResBlock(
                    ch, self.dtype, name=f"up_{level}_resnets_{j}")(x)
            if level > 0:
                h = upsample2x_nearest(fold(x))
                h = nn.Conv(ch, (3, 3), padding=1, dtype=self.dtype,
                            name=f"up_{level}_upsample")(h)
                x = unfold(h)
        h = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]), epsilon=1e-6,
                         dtype=jnp.float32, name="conv_norm_out")(fold(x))
        h = nn.silu(h).astype(self.dtype)
        h = nn.Conv(cfg.in_channels, (3, 3), padding=1, dtype=jnp.float32,
                    name="conv_out")(h)
        # frame-axis smoothing conv on the decoded RGB
        return nn.Conv(cfg.in_channels, (3, 1, 1),
                       padding=((1, 1), (0, 0), (0, 0)), dtype=jnp.float32,
                       name="time_conv_out")(unfold(h))


class AutoencoderKLTemporalDecoder(nn.Module):
    """SVD's VAE: the standard spatial encoder + the temporal decoder.
    encode_moments matches AutoencoderKL's (the img2vid pipeline encodes
    the conditioning frame with it); decode takes (B, F, lh, lw, C)
    scaled latents and returns (B, F, H, W, 3)."""

    config: VAEConfig

    def setup(self) -> None:
        dtype = jnp.dtype(self.config.dtype)
        self.encoder = Encoder(self.config, dtype, name="encoder")
        self.decoder = TemporalVaeDecoder(self.config, dtype,
                                          name="decoder")

    def encode_moments(self, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        moments = self.encoder(x)
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def decode(self, z: jnp.ndarray) -> jnp.ndarray:
        return self.decoder(z / self.config.scaling_factor)

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # frame-folded round trip (init/tests): x (B, F, H, W, 3)
        b, f = x.shape[:2]
        mean, _ = self.encode_moments(x.reshape((-1,) + x.shape[2:]))
        z = (mean * self.config.scaling_factor).reshape(
            (b, f) + mean.shape[1:])
        return self.decode(z)


class AutoencoderKL(nn.Module):
    """encode: image (B,H,W,3) in [-1,1] -> scaled latents.
    decode: scaled latents -> image in [-1,1]."""

    config: VAEConfig

    def setup(self) -> None:
        dtype = jnp.dtype(self.config.dtype)
        self.encoder = Encoder(self.config, dtype, name="encoder")
        self.decoder = Decoder(self.config, dtype, name="decoder")

    def encode_moments(self, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        moments = self.encoder(x)
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def encode(self, x: jnp.ndarray, rng: jax.Array | None = None) -> jnp.ndarray:
        mean, logvar = self.encode_moments(x)
        if rng is not None:
            mean = mean + jnp.exp(0.5 * logvar) * jax.random.normal(
                rng, mean.shape, dtype=mean.dtype
            )
        return mean * self.config.scaling_factor

    def decode(self, z: jnp.ndarray) -> jnp.ndarray:
        return self.decoder(z / self.config.scaling_factor)

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # autoencoding round trip (used by tests/training)
        return self.decode(self.encode(x))


def tiled_decode(
    vae: AutoencoderKL,
    params,
    z: jnp.ndarray,
    *,
    tile: int = 64,
    overlap: int = 8,
) -> jnp.ndarray:
    """Memory-bounded decode: fixed-size latent tiles with linear overlap
    blending (TPU-native analog of diffusers' enable_vae_tiling, toggled by
    the reference at swarm/diffusion/diffusion_func.py:89-92).

    Tiles are decoded sequentially under one jit (XLA unrolls a static tile
    grid — shapes never change), so peak activation memory is one tile's.
    """
    b, h, w, c = z.shape
    stride = tile - overlap
    f = vae.config.downscale

    def decode_tile(zt):
        return vae.apply(params, zt, method=AutoencoderKL.decode)

    rows = max(1, -(-(h - overlap) // stride))
    cols = max(1, -(-(w - overlap) // stride))
    out_h, out_w = h * f, w * f
    canvas = jnp.zeros((b, out_h, out_w, vae.config.in_channels), jnp.float32)
    weight = jnp.zeros((1, out_h, out_w, 1), jnp.float32)

    # strictly positive crossfade ramp: (i+1)/(ov+1) so tile borders keep
    # nonzero weight (image edges are covered by exactly one tile and must
    # not be zeroed); normalization below makes overlaps sum to 1.
    ov = max(overlap * f, 1)
    idx = jnp.arange(tile * f, dtype=jnp.float32)
    ramp = jnp.minimum((idx + 1.0) / (ov + 1.0), 1.0)
    edge = jnp.minimum(ramp, ramp[::-1])
    tile_w = edge[None, :, None, None] * edge[None, None, :, None]

    for i in range(rows):
        for j in range(cols):
            y0 = min(i * stride, max(h - tile, 0))
            x0 = min(j * stride, max(w - tile, 0))
            zt = jax.lax.dynamic_slice(
                z, (0, y0, x0, 0), (b, min(tile, h), min(tile, w), c)
            )
            img = decode_tile(zt).astype(jnp.float32)
            tw = tile_w[:, : img.shape[1], : img.shape[2], :]
            canvas = jax.lax.dynamic_update_slice(
                canvas,
                jax.lax.dynamic_slice(
                    canvas, (0, y0 * f, x0 * f, 0), img.shape
                ) + img * tw,
                (0, y0 * f, x0 * f, 0),
            )
            weight = jax.lax.dynamic_update_slice(
                weight,
                jax.lax.dynamic_slice(
                    weight, (0, y0 * f, x0 * f, 0), (1, img.shape[1], img.shape[2], 1)
                ) + tw,
                (0, y0 * f, x0 * f, 0),
            )
    return canvas / jnp.maximum(weight, 1e-8)
