"""Flax model zoo: text encoders, UNet, VAE, ControlNet.

Architecture configs for the model families the reference serves through
diffusers' dynamic class loading (swarm/type_helpers.py:1-3,
swarm/job_arguments.py:143-148): Stable Diffusion 1.5 / 2.1, SDXL,
latent upscaler, plus tiny hermetic-test variants.
"""

from chiaswarm_tpu.models.configs import (
    TextEncoderConfig,
    UNetConfig,
    VAEConfig,
    ModelFamily,
    FAMILIES,
    get_family,
)

__all__ = [
    "TextEncoderConfig",
    "UNetConfig",
    "VAEConfig",
    "ModelFamily",
    "FAMILIES",
    "get_family",
]
