"""Lineart detector — the learned line-drawing preprocessor.

The reference reaches lineart conditioning through controlnet_aux's
LineartDetector (swarm/controlnet/input_processor.py:17-60 dispatch),
which wraps the informative-drawings ``Generator``: a ReflectionPad
conv stem, two stride-2 downsamples, N InstanceNorm residual blocks at
256 channels, two transposed-conv upsamples, and a 7x7 sigmoid head
producing a 1-channel drawing (dark strokes on white). Weights convert
from the public ``sk_model.pth`` / ``sk_model2.pth`` layout
(convert/torch_to_flax.py::convert_lineart).

TPU-native notes: InstanceNorm (affine-free, eps 1e-5) is a two-reduce
fusion XLA handles; the torch ``ConvTranspose2d(k=3, s=2, p=1, op=1)``
is reproduced exactly as an input-dilated conv with asymmetric (1, 2)
padding and a pre-flipped kernel (the converter bakes the spatial flip
and the (in,out) swap into the stored param, so runtime is a plain
``conv_general_dilated``). The CNN runs under jit; resize logic is
host-side like the other preprocessors (workloads/controlnet.py).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def instance_norm(x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """torch nn.InstanceNorm2d(affine=False) over NHWC."""
    mean = x.mean(axis=(1, 2), keepdims=True)
    var = x.var(axis=(1, 2), keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps)


def _reflect_pad(x: jnp.ndarray, p: int) -> jnp.ndarray:
    return jnp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)), mode="reflect")


class ReflectConv(nn.Module):
    """ReflectionPad2d(p) + Conv2d(k, VALID)."""

    features: int
    kernel: int
    pad: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = _reflect_pad(x, self.pad)
        return nn.Conv(self.features, (self.kernel, self.kernel),
                       padding="VALID", name="conv")(x)


class TorchConvTranspose(nn.Module):
    """torch ConvTranspose2d(k=3, stride=2, padding=1, output_padding=1)
    as an lhs-dilated conv. The stored kernel is (kh, kw, in, out) with
    the spatial flip already baked in (converter responsibility; random
    init is equivalent under any fixed flip)."""

    features: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        in_ch = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (3, 3, in_ch, self.features),
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        y = jax.lax.conv_general_dilated(
            x, kernel.astype(x.dtype),
            window_strides=(1, 1),
            padding=((1, 2), (1, 2)),   # (k-1-p, k-1-p+output_padding)
            lhs_dilation=(2, 2),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return y + bias.astype(y.dtype)


class ResidualBlock(nn.Module):
    features: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h = ReflectConv(self.features, 3, 1, name="conv_a")(x)
        h = nn.relu(instance_norm(h))
        h = ReflectConv(self.features, 3, 1, name="conv_b")(h)
        return x + instance_norm(h)


class LineartGenerator(nn.Module):
    """(B, H, W, 3) in [0, 1] -> (B, H, W, 1) drawing in [0, 1]
    (informative-drawings Generator(3, 1, n_blocks), sigmoid head)."""

    n_blocks: int = 3

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = ReflectConv(64, 7, 3, name="stem")(x)
        x = nn.relu(instance_norm(x))
        for i, ch in enumerate((128, 256)):
            x = nn.Conv(ch, (3, 3), strides=(2, 2), padding=1,
                        name=f"down_{i}")(x)
            x = nn.relu(instance_norm(x))
        for i in range(self.n_blocks):
            x = ResidualBlock(256, name=f"res_{i}")(x)
        for i, ch in enumerate((128, 64)):
            x = TorchConvTranspose(ch, name=f"up_{i}")(x)
            x = nn.relu(instance_norm(x))
        x = ReflectConv(1, 7, 3, name="head")(x)
        return jax.nn.sigmoid(x)


@dataclasses.dataclass
class LineartDetector:
    """Host-facing wrapper: uint8 RGB -> uint8 line map (white lines on
    black, the conditioning format the reference emits after its own
    255-minus inversion of the generator's dark-on-white drawing)."""

    params: dict
    n_blocks: int = 3
    # fixed working canvas: ONE compiled shape for every request (same
    # rationale as models/hed.py HEDDetector.canvas)
    canvas: int = 512

    def __post_init__(self) -> None:
        self._net = LineartGenerator(self.n_blocks)
        self._fwd = jax.jit(lambda p, x: self._net.apply(p, x))

    @classmethod
    def random(cls, seed: int = 0, n_blocks: int = 3,
               canvas: int = 512) -> "LineartDetector":
        net = LineartGenerator(n_blocks)
        x = jnp.zeros((1, 64, 64, 3), jnp.float32)
        return cls(params=jax.jit(net.init)(jax.random.PRNGKey(seed), x),
                   n_blocks=n_blocks, canvas=canvas)

    @classmethod
    def from_checkpoint(cls, path) -> "LineartDetector":
        from chiaswarm_tpu.convert.torch_to_flax import (
            convert_lineart,
            read_torch_weights,
        )

        state = read_torch_weights(path)
        return cls(params=convert_lineart(state),
                   n_blocks=sum(1 for k in state
                                if k.endswith("conv_block.1.weight")))

    def __call__(self, image: np.ndarray) -> np.ndarray:
        import cv2

        h, w = image.shape[:2]
        scale = self.canvas / max(h, w, 1)
        nh = max(16, min(self.canvas, round(h * scale)))
        nw = max(16, min(self.canvas, round(w * scale)))
        resized = cv2.resize(image, (nw, nh), interpolation=cv2.INTER_AREA)
        padded = cv2.copyMakeBorder(resized, 0, self.canvas - nh, 0,
                                    self.canvas - nw, cv2.BORDER_REPLICATE)
        x = jnp.asarray(padded.astype(np.float32) / 255.0)[None]
        drawing = np.asarray(jax.device_get(
            self._fwd(self.params, x)))[0, :, :, 0]
        drawing = cv2.resize(drawing[:nh, :nw], (w, h),
                             interpolation=cv2.INTER_LINEAR)
        # generator draws dark strokes on white; conditioning wants
        # white-on-black (controlnet_aux inverts the same way)
        lines = 255 - (drawing * 255.0).clip(0, 255).astype(np.uint8)
        return lines
