"""Causal GPT (Flax) with static-shape KV-cache decoding — the
autoregressive engine for bark-class TTS (workloads/audio.py).

The reference shells out to ``suno-bark`` (swarm/audio/bark.py:15-21),
whose three stages are all plain GPTs (text->semantic, semantic->coarse
codec, coarse->fine codec). TPU-first design choices:

- the KV cache is a fixed-size ring of arrays carried through a
  ``lax.scan`` — one compiled program generates the whole token stream
  (no per-token dispatch, no dynamic shapes);
- prefill (the prompt) runs as one batched forward, then decode appends
  one token per scan step via ``dynamic_update_slice``;
- sampling (temperature + top-k) happens on-chip inside the scan.

Bark quirk kept: separate input and output vocab sizes per stage (the
semantic stage reads text tokens but emits semantic tokens).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

NEG_INF = -1e9


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 129600          # bark text stage input vocab
    output_vocab_size: int | None = None  # None -> same as vocab_size
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    block_size: int = 1024            # max sequence length (cache size)
    dtype: str = "float32"

    @property
    def out_vocab(self) -> int:
        return self.output_vocab_size or self.vocab_size


class Block(nn.Module):
    config: GPTConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, cache_k, cache_v, index, valid_len,
                 ring_bias=None):
        """x: (B, T, C) new tokens at positions [index, index+T).
        cache_k/v: (B, block_size, H, D) rings. ``ring_bias`` (additive,
        broadcastable to (B, 1, T, block_size)) overrides the default
        causal ring mask — used by padded prefills. Returns (y, k, v)."""
        cfg = self.config
        head_dim = cfg.n_embd // cfg.n_head
        b, t, _ = x.shape

        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln_1")(x).astype(self.dtype)
        qkv = nn.Dense(3 * cfg.n_embd, use_bias=False, dtype=self.dtype,
                       name="attn_qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, cfg.n_head, head_dim)
        k = k.reshape(b, t, cfg.n_head, head_dim)
        v = v.reshape(b, t, cfg.n_head, head_dim)

        cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, index, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, index, 0, 0))

        scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            cache_k.astype(jnp.float32))
        scores = scores / jnp.sqrt(head_dim).astype(jnp.float32)
        if ring_bias is not None:
            scores = scores + ring_bias
        else:
            # causal mask over the ring: key j visible to query i (absolute
            # position index+i) iff j <= index+i and j < valid_len
            kpos = jnp.arange(cfg.block_size)
            qpos = index + jnp.arange(t)
            mask = (kpos[None, :] <= qpos[:, None]) & \
                   (kpos[None, :] < valid_len)
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        weights = nn.softmax(scores, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, cache_v)
        out = out.reshape(b, t, cfg.n_embd)
        x = x + nn.Dense(cfg.n_embd, use_bias=False, dtype=self.dtype,
                         name="attn_proj")(out)

        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln_2")(x).astype(self.dtype)
        h = nn.Dense(4 * cfg.n_embd, use_bias=False, dtype=self.dtype,
                     name="mlp_fc")(h)
        h = nn.gelu(h, approximate=False)  # bark uses exact-erf GELU
        x = x + nn.Dense(cfg.n_embd, use_bias=False, dtype=self.dtype,
                         name="mlp_proj")(h)
        return x, cache_k, cache_v


class GPT(nn.Module):
    """Forward over new tokens given a KV-cache ring; returns logits over
    the OUTPUT vocab plus updated caches."""

    config: GPTConfig

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(self, ids, caches, index, valid_len, *, embeds=None,
                 ring_bias=None, pos_index=None):
        """ids: (B, T) int32 (or ``embeds`` (B, T, C) directly — bark's
        semantic prefill sums two embedding lookups); caches: per-layer
        (k, v) tuple list; index: ring position of ids[0]; valid_len:
        scalar count of valid cache positions after this call.
        ``pos_index`` overrides the logical position for the position
        embeddings (padded prefills); ``ring_bias`` overrides the ring
        mask (see Block)."""
        cfg = self.config
        if embeds is None:
            tok = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=self.dtype,
                           name="wte")(ids)
        else:
            # materialize the embedding table even on the embeds path so
            # both entry modes share one param structure
            nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=self.dtype,
                     name="wte")(jnp.zeros((1, 1), jnp.int32))
            tok = embeds.astype(self.dtype)
        t = tok.shape[1]
        pos_table = self.param(
            "wpe", nn.initializers.normal(0.02),
            (cfg.block_size, cfg.n_embd))
        start = index if pos_index is None else pos_index
        pos = jax.lax.dynamic_slice(pos_table, (start, 0), (t, cfg.n_embd))
        x = tok + pos[None].astype(self.dtype)

        new_caches = []
        for i in range(cfg.n_layer):
            ck, cv = caches[i]
            x, ck, cv = Block(cfg, self.dtype, name=f"h_{i}")(
                x, ck, cv, index, valid_len, ring_bias)
            new_caches.append((ck, cv))

        x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln_f")(x)
        logits = nn.Dense(cfg.out_vocab, use_bias=False, dtype=jnp.float32,
                          name="lm_head")(x)
        return logits, new_caches


def init_caches(cfg: GPTConfig, batch: int) -> list[tuple[jnp.ndarray,
                                                          jnp.ndarray]]:
    head_dim = cfg.n_embd // cfg.n_head
    shape = (batch, cfg.block_size, cfg.n_head, head_dim)
    dtype = jnp.dtype(cfg.dtype)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(cfg.n_layer)]


def sample_token(key, logits, temperature: float, top_k: int):
    """(B, V) logits -> (B,) sampled ids, on-chip top-k + temperature."""
    logits = logits / jnp.maximum(temperature, 1e-5)
    if top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    return jax.random.categorical(key, logits, axis=-1)


@partial(jax.jit, static_argnames=("gpt", "max_new", "top_k", "prefill_len"))
def generate(gpt: GPT, params: Any, prompt_ids: jnp.ndarray,
             key: jax.Array, *, prefill_len: int, max_new: int,
             temperature: float = 0.7, top_k: int = 50,
             eos_id: int = -1) -> jnp.ndarray:
    """Prefill + scan-decode ``max_new`` tokens. ``prompt_ids`` is
    (B, prefill_len) (pad/truncate on host). Returns (B, max_new) int32;
    positions after EOS repeat EOS (trim on host). ``temperature`` is a
    TRACED operand (changing it never recompiles); ``top_k`` must stay
    static for ``lax.top_k``."""
    cfg = gpt.config
    b = prompt_ids.shape[0]
    caches = init_caches(cfg, b)
    logits, caches = gpt.apply(params, prompt_ids, caches, 0,
                               jnp.int32(prefill_len))
    key, skey = jax.random.split(key)
    first = sample_token(skey, logits[:, -1], temperature, top_k)

    def body(carry, _):
        caches, tok, idx, key, done = carry
        logits, caches = gpt.apply(params, tok[:, None], caches, idx,
                                   idx + 1)
        key, skey = jax.random.split(key)
        nxt = sample_token(skey, logits[:, 0], temperature, top_k)
        nxt = jnp.where(done, jnp.int32(eos_id), nxt)
        done = done | (nxt == eos_id)
        return (caches, nxt, idx + 1, key, done), nxt

    done0 = first == eos_id
    (_, _, _, _, _), toks = jax.lax.scan(
        body, (caches, first, jnp.int32(prefill_len), key, done0),
        None, length=max_new - 1)
    return jnp.concatenate([first[:, None], toks.swapaxes(0, 1)], axis=1)


class FineBlock(nn.Module):
    """Non-causal transformer block (bark's fine stage is a masked-LM-style
    autoencoder over the full 1024-frame window, not autoregressive).
    Layer names match Block so the bark converter maps both uniformly."""

    config: GPTConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        head_dim = cfg.n_embd // cfg.n_head
        b, t, _ = x.shape
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln_1")(x).astype(self.dtype)
        qkv = nn.Dense(3 * cfg.n_embd, use_bias=False, dtype=self.dtype,
                       name="attn_qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (b, t, cfg.n_head, head_dim)
        scores = jnp.einsum("bqhd,bkhd->bhqk",
                            q.reshape(shape).astype(jnp.float32),
                            k.reshape(shape).astype(jnp.float32))
        scores = scores / jnp.sqrt(head_dim).astype(jnp.float32)
        weights = nn.softmax(scores, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v.reshape(shape))
        x = x + nn.Dense(cfg.n_embd, use_bias=False, dtype=self.dtype,
                         name="attn_proj")(out.reshape(b, t, cfg.n_embd))
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln_2")(x).astype(self.dtype)
        h = nn.Dense(4 * cfg.n_embd, use_bias=False, dtype=self.dtype,
                     name="mlp_fc")(h)
        h = nn.gelu(h, approximate=False)
        return x + nn.Dense(cfg.n_embd, use_bias=False, dtype=self.dtype,
                            name="mlp_proj")(h)


class FineGPT(nn.Module):
    """Bark fine-acoustics model: ``n_codes_total`` embedding tables whose
    lookups sum over the codebooks known so far, a full-window non-causal
    transformer, and one LM head per predicted codebook.

    ``__call__(codes, codebook_idx)``: codes (B, T, n_codes_total) int32,
    ``codebook_idx`` static — embeds codebooks [0, codebook_idx] and
    returns logits over the output vocab for codebook ``codebook_idx``.
    """

    config: GPTConfig
    n_codes_total: int = 8
    n_codes_given: int = 1

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(self, codes: jnp.ndarray, codebook_idx: int) -> jnp.ndarray:
        cfg = self.config
        b, t, _ = codes.shape
        # materialize every table (shared param structure across
        # codebook_idx traces); only [0, codebook_idx] contribute
        tables = [nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=self.dtype,
                           name=f"wte_{k}")
                  for k in range(self.n_codes_total)]
        x = sum(tables[k](codes[:, :, k])
                for k in range(codebook_idx + 1))
        for k in range(codebook_idx + 1, self.n_codes_total):
            tables[k](jnp.zeros((1, 1), jnp.int32))
        pos_table = self.param("wpe", nn.initializers.normal(0.02),
                               (cfg.block_size, cfg.n_embd))
        x = x + pos_table[None, :t].astype(self.dtype)
        for i in range(cfg.n_layer):
            x = FineBlock(cfg, self.dtype, name=f"h_{i}")(x)
        x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln_f")(x)
        heads = [nn.Dense(cfg.out_vocab, use_bias=False, dtype=jnp.float32,
                          name=f"lm_head_{k}")
                 for k in range(self.n_codes_total - self.n_codes_given)]
        logits = heads[codebook_idx - self.n_codes_given](x)
        for k, head in enumerate(heads):
            if k != codebook_idx - self.n_codes_given:
                head(jnp.zeros((1, 1, cfg.n_embd), self.dtype))
        return logits
