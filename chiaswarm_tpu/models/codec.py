"""Neural codec decoder (Flax): RVQ code stacks -> waveform.

The last stage of bark-class TTS (pipelines/tts.py): fine acoustic codes
are EnCodec residual-vector-quantizer indices; decoding sums per-codebook
embeddings and runs the SEANet decoder. This is an EXACT port of the
EnCodec 24 kHz decoder graph (causal convs with reflect left-padding, a
2-layer residual LSTM, transposed convs with right-trim, residual units
with conv shortcuts) so weights convert 1:1 from the torch checkpoint
(convert/torch_to_flax.py::convert_encodec; weight norm folded).

TPU notes: everything except the LSTM is convs that XLA fuses onto the
MXU; the LSTM is a ``lax.scan`` over time at the code frame rate (75 Hz —
hundreds of tiny steps, negligible next to the GPT stages). Codes pad
right to a static frame bucket; causality makes trimming the decoded
tail exact.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class CodecConfig:
    n_codebooks: int = 8
    codebook_size: int = 1024
    codebook_dim: int = 128
    num_filters: int = 32
    upsampling_ratios: tuple[int, ...] = (8, 5, 4, 2)
    kernel_size: int = 7
    last_kernel_size: int = 7
    residual_kernel_size: int = 3
    dilation_growth_rate: int = 2
    num_residual_layers: int = 1
    compress: int = 2
    num_lstm_layers: int = 2
    use_conv_shortcut: bool = True
    sampling_rate: int = 24000
    dtype: str = "float32"

    @property
    def hop_length(self) -> int:
        hop = 1
        for r in self.upsampling_ratios:
            hop *= r
        return hop


def _causal_pad(x: jnp.ndarray, pad: int) -> jnp.ndarray:
    """Left reflect-pad along time (EnCodec's causal convention), with the
    zero-extension fallback for inputs shorter than the pad."""
    if pad == 0:
        return x
    t = x.shape[1]
    if t <= pad:  # EnCodec's small-input hack: zero-extend right first
        extra = pad - t + 1
        x = jnp.pad(x, ((0, 0), (0, extra), (0, 0)))
        x = jnp.pad(x, ((0, 0), (pad, 0), (0, 0)), mode="reflect")
        return x[:, : x.shape[1] - extra]
    return jnp.pad(x, ((0, 0), (pad, 0), (0, 0)), mode="reflect")


class CausalConv1d(nn.Module):
    channels: int
    kernel: int
    dilation: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        pad = (self.kernel - 1) * self.dilation
        x = _causal_pad(x, pad)
        return nn.Conv(self.channels, (self.kernel,), padding="VALID",
                       kernel_dilation=(self.dilation,), dtype=self.dtype,
                       name="conv")(x)


class CausalConvTranspose1d(nn.Module):
    """Stride-r transposed conv; EnCodec trims the full (k - stride) pad
    from the right (causal, trim_right_ratio=1)."""

    channels: int
    kernel: int
    stride: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        y = nn.ConvTranspose(self.channels, (self.kernel,),
                             strides=(self.stride,), padding="VALID",
                             dtype=self.dtype, name="conv")(x)
        trim = self.kernel - self.stride
        return y[:, : y.shape[1] - trim] if trim else y


class ResnetUnit(nn.Module):
    """EnCodec SEANet residual unit: ELU-conv(k,dil)-ELU-conv(1) with a
    1x1 conv shortcut."""

    channels: int
    kernel: int
    dilation: int
    compress: int
    use_conv_shortcut: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        hidden = self.channels // self.compress
        h = nn.elu(x)
        h = CausalConv1d(hidden, self.kernel, self.dilation, self.dtype,
                         name="block_1")(h)
        h = nn.elu(h)
        h = CausalConv1d(self.channels, 1, 1, self.dtype, name="block_3")(h)
        if self.use_conv_shortcut:
            x = CausalConv1d(self.channels, 1, 1, self.dtype,
                             name="shortcut")(x)
        return x + h


class ResidualLSTM(nn.Module):
    """torch-layout LSTM stack with residual add (EncodecLSTM)."""

    hidden: int
    num_layers: int = 2

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, t, c = x.shape
        residual = x
        h = x.astype(jnp.float32)
        for layer in range(self.num_layers):
            w_ih = self.param(f"weight_ih_l{layer}",
                              nn.initializers.normal(0.02),
                              (4 * self.hidden, h.shape[-1]))
            w_hh = self.param(f"weight_hh_l{layer}",
                              nn.initializers.normal(0.02),
                              (4 * self.hidden, self.hidden))
            b_ih = self.param(f"bias_ih_l{layer}", nn.initializers.zeros,
                              (4 * self.hidden,))
            b_hh = self.param(f"bias_hh_l{layer}", nn.initializers.zeros,
                              (4 * self.hidden,))
            x_proj = h @ w_ih.T + (b_ih + b_hh)  # (B, T, 4H), hoisted

            def step(carry, xt, w_hh=w_hh):
                hprev, cprev = carry
                gates = xt + hprev @ w_hh.T
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c = nn.sigmoid(f) * cprev + nn.sigmoid(i) * jnp.tanh(g)
                hnew = nn.sigmoid(o) * jnp.tanh(c)
                return (hnew, c), hnew

            zeros = jnp.zeros((b, self.hidden), jnp.float32)
            (_, _), hs = jax.lax.scan(step, (zeros, zeros),
                                      x_proj.swapaxes(0, 1))
            h = hs.swapaxes(0, 1)
        return residual + h.astype(residual.dtype)


class CodecDecoder(nn.Module):
    """(B, n_codebooks, T) int codes -> (B, T * hop_length) waveform.

    Module names carry the torch ``decoder.layers.{i}`` indices (ELUs
    occupy slots in the torch ModuleList) so conversion is positional.
    """

    config: CodecConfig

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(self, codes: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dtype = self.dtype
        # RVQ: the quantized latent is the SUM of per-codebook embeddings
        quantized = 0.0
        for k in range(cfg.n_codebooks):
            quantized = quantized + nn.Embed(
                cfg.codebook_size, cfg.codebook_dim, dtype=dtype,
                name=f"codebook_{k}")(codes[:, k])

        scaling = 2 ** len(cfg.upsampling_ratios)
        ch = scaling * cfg.num_filters
        idx = 0
        x = CausalConv1d(ch, cfg.kernel_size, 1, dtype,
                         name=f"layers_{idx}")(quantized)
        idx += 1
        x = ResidualLSTM(ch, cfg.num_lstm_layers, name=f"layers_{idx}")(x)
        for ratio in cfg.upsampling_ratios:
            idx += 1  # ELU slot
            x = nn.elu(x)
            idx += 1
            x = CausalConvTranspose1d(ch // 2, 2 * ratio, ratio, dtype,
                                      name=f"layers_{idx}")(x)
            ch //= 2
            for j in range(cfg.num_residual_layers):
                idx += 1
                x = ResnetUnit(ch, cfg.residual_kernel_size,
                               cfg.dilation_growth_rate ** j, cfg.compress,
                               cfg.use_conv_shortcut, dtype,
                               name=f"layers_{idx}")(x)
        idx += 1  # final ELU slot
        x = nn.elu(x)
        idx += 1
        x = CausalConv1d(1, cfg.last_kernel_size, 1, dtype,
                         name=f"layers_{idx}")(x)
        return x[..., 0].astype(jnp.float32)
