"""Neural codec decoder (Flax): RVQ code stacks -> waveform.

The last stage of bark-class TTS (workloads/audio.py): the fine acoustic
codes are EnCodec residual-vector-quantizer indices; decoding sums the
per-codebook embeddings and runs a SEANet-style transposed-conv decoder.
Mirrors EnCodec's 24 kHz decoder shape (ratios 8·5·4·2 -> hop 320) minus
its LSTM block — inference here is pure convs, which XLA fuses into a
handful of MXU-friendly kernels. Conversion from torch folds weight norm
(convert/torch_to_flax.py:_fold_weight_norm).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class CodecConfig:
    n_codebooks: int = 8
    codebook_size: int = 1024
    codebook_dim: int = 128
    hidden: int = 512
    upsample_rates: tuple[int, ...] = (8, 5, 4, 2)
    kernel_mult: int = 2              # transposed-conv kernel = 2 * rate
    sampling_rate: int = 24000
    dtype: str = "float32"

    @property
    def hop_length(self) -> int:
        hop = 1
        for r in self.upsample_rates:
            hop *= r
        return hop


class DecoderResBlock(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h = nn.elu(x)
        h = nn.Conv(self.channels // 2, (3,), padding="SAME",
                    dtype=self.dtype, name="conv1")(h)
        h = nn.elu(h)
        h = nn.Conv(self.channels, (1,), dtype=self.dtype, name="conv2")(h)
        return x + h


class CodecDecoder(nn.Module):
    """(B, n_codebooks, T) int codes -> (B, T * hop_length) waveform."""

    config: CodecConfig

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(self, codes: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dtype = self.dtype
        # RVQ: the quantized latent is the SUM of per-codebook embeddings
        quantized = 0.0
        for k in range(cfg.n_codebooks):
            quantized = quantized + nn.Embed(
                cfg.codebook_size, cfg.codebook_dim, dtype=dtype,
                name=f"codebook_{k}")(codes[:, k])
        x = nn.Conv(cfg.hidden, (7,), padding="SAME", dtype=dtype,
                    name="conv_pre")(quantized)
        ch = cfg.hidden
        for i, rate in enumerate(cfg.upsample_rates):
            ch = max(ch // 2, cfg.codebook_dim // 2)
            x = nn.elu(x)
            x = nn.ConvTranspose(ch, (cfg.kernel_mult * rate,),
                                 strides=(rate,), padding="SAME",
                                 dtype=dtype, name=f"upsample_{i}")(x)
            x = DecoderResBlock(ch, dtype, name=f"resblock_{i}")(x)
        x = nn.elu(x)
        x = nn.Conv(1, (7,), padding="SAME", dtype=dtype,
                    name="conv_post")(x)
        return jnp.tanh(x)[..., 0].astype(jnp.float32)
