"""T5 text encoder (Flax) — the conditioning tower for pixel-space cascades.

DeepFloyd-IF-class models condition on a T5-v1.1 encoder instead of CLIP
(the reference loads it inside ``DiffusionPipeline.from_pretrained`` for
``DeepFloyd/IF-I-XL-v1.0``, swarm/diffusion/diffusion_func_if.py:16-19 —
prompt embeds are computed once and shared across all three cascade
stages, :45-61). This module reproduces the real T5 encoder architecture
so transformers ``T5EncoderModel`` checkpoints convert directly:

- RMSNorm (no mean subtraction, no bias), pre-norm residual blocks
- relative position bias (bucketed, bidirectional) owned by block 0 and
  shared by all layers
- attention without 1/sqrt(d) scaling (T5 folds it into initialization)
- gated-GELU feed-forward (v1.1: ``wi_0`` * gelu -> ``wi_1`` product)

TPU notes: static sequence length, one fused program per (batch, length)
bucket; the encode cost is negligible next to the pixel diffusion stages.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 4096        # T5-v1.1-XXL (IF's encoder)
    d_kv: int = 64
    d_ff: int = 10240
    num_layers: int = 24
    num_heads: int = 64
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    max_length: int = 77
    layer_norm_epsilon: float = 1e-6
    eos_token_id: int = 1
    pad_token_id: int = 0      # T5 pads with id 0 and prepends no BOS
    dtype: str = "bfloat16"


def _rsqrt(var: jnp.ndarray, eps: float) -> jnp.ndarray:
    return 1.0 / jnp.sqrt(var + eps)


def relative_position_buckets(length: int, num_buckets: int,
                              max_distance: int) -> np.ndarray:
    """Bidirectional T5 bucket table, (length, length) int32, built on the
    host once per compile (static shapes — no traced control flow)."""
    context = np.arange(length)[:, None]
    memory = np.arange(length)[None, :]
    relative = memory - context
    half = num_buckets // 2
    bucket = np.where(relative > 0, half, 0)
    rel = np.abs(relative)
    max_exact = half // 2
    is_small = rel < max_exact
    log_ratio = np.log(np.maximum(rel, 1) / max_exact) / np.log(
        max_distance / max_exact)
    large = max_exact + (log_ratio * (half - max_exact)).astype(np.int64)
    large = np.minimum(large, half - 1)
    bucket = bucket + np.where(is_small, rel, large)
    return bucket.astype(np.int32)


class T5Attention(nn.Module):
    config: T5Config
    has_relative_bias: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 position_bias: jnp.ndarray | None,
                 mask_bias: jnp.ndarray | None = None,
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
        cfg = self.config
        inner = cfg.num_heads * cfg.d_kv
        b, l, _ = x.shape
        q = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="q")(x)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="k")(x)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="v")(x)
        q = q.reshape(b, l, cfg.num_heads, cfg.d_kv).transpose(0, 2, 1, 3)
        k = k.reshape(b, l, cfg.num_heads, cfg.d_kv).transpose(0, 2, 1, 3)
        v = v.reshape(b, l, cfg.num_heads, cfg.d_kv).transpose(0, 2, 1, 3)

        if self.has_relative_bias:
            buckets = relative_position_buckets(
                l, cfg.relative_attention_num_buckets,
                cfg.relative_attention_max_distance)
            table = self.param(
                "relative_attention_bias",
                nn.initializers.normal(1.0),
                (cfg.relative_attention_num_buckets, cfg.num_heads),
            )
            # (L, L, H) -> (1, H, L, L); the padding-mask bias folds in
            # here once and rides the shared bias through every layer,
            # exactly as transformers merges its extended attention mask
            position_bias = table[buckets].transpose(2, 0, 1)[None]
            if mask_bias is not None:
                position_bias = position_bias + mask_bias

        # T5: NO 1/sqrt(d) scaling
        scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32))
        if position_bias is not None:
            scores = scores + position_bias.astype(jnp.float32)
        weights = nn.softmax(scores, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", weights, v)
        out = out.transpose(0, 2, 1, 3).reshape(b, l, inner)
        return nn.Dense(x.shape[-1], use_bias=False, dtype=self.dtype,
                        name="o")(out), position_bias


class T5Block(nn.Module):
    config: T5Config
    has_relative_bias: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 position_bias: jnp.ndarray | None,
                 mask_bias: jnp.ndarray | None = None,
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
        cfg = self.config
        h = T5LayerNorm(cfg.layer_norm_epsilon, name="attn_norm")(x)
        attn, position_bias = T5Attention(
            cfg, self.has_relative_bias, self.dtype, name="attention"
        )(h, position_bias, mask_bias)
        x = x + attn
        h = T5LayerNorm(cfg.layer_norm_epsilon, name="ff_norm")(x)
        gate = nn.Dense(cfg.d_ff, use_bias=False, dtype=self.dtype,
                        name="wi_0")(h)
        lin = nn.Dense(cfg.d_ff, use_bias=False, dtype=self.dtype,
                       name="wi_1")(h)
        h = nn.gelu(gate, approximate=True) * lin
        x = x + nn.Dense(cfg.d_model, use_bias=False, dtype=self.dtype,
                         name="wo")(h)
        return x, position_bias


class T5LayerNorm(nn.Module):
    """T5's RMSNorm: no mean subtraction, no bias, fp32 accumulation."""

    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        dtype = x.dtype
        x32 = x.astype(jnp.float32)
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        return (x32 * _rsqrt(var, self.epsilon) * scale).astype(dtype)


class T5Encoder(nn.Module):
    """(B, L) int32 token ids -> (B, L, d_model) float sequence."""

    config: T5Config

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(self, input_ids: jnp.ndarray,
                 attention_mask: jnp.ndarray | None = None) -> jnp.ndarray:
        """``attention_mask`` (B, L) of 1/0 — DeepFloyd's serving path
        passes the tokenizer padding mask to T5 (the reference's pipeline
        does the same through transformers); ``None`` attends everywhere."""
        cfg = self.config
        emb = nn.Embed(cfg.vocab_size, cfg.d_model,
                       dtype=self.dtype, name="token_embedding")
        x = emb(input_ids)
        mask_bias = None
        if attention_mask is not None:
            mask_bias = jnp.where(
                attention_mask[:, None, None, :] > 0, 0.0,
                jnp.finfo(jnp.float32).min)
        position_bias = None
        for i in range(cfg.num_layers):
            x, position_bias = T5Block(
                cfg, has_relative_bias=(i == 0), dtype=self.dtype,
                name=f"block_{i}",
            )(x, position_bias, mask_bias)
        return T5LayerNorm(cfg.layer_norm_epsilon,
                           name="final_layer_norm")(x).astype(jnp.float32)
