"""UperNet semantic segmentation (ConvNeXt backbone) — the seg preprocessor.

The reference's seg ControlNet mode runs UperNet over the ADE20K classes
(swarm/controlnet/input_processor.py:96-115, the transformers
``UperNetForSemanticSegmentation`` checkpoints); this is the same model
natively: a ConvNeXt backbone tapped at all four stages, the PSP pyramid
pooling module, the FPN top-down path, and the fused classifier head.
Weights convert 1:1 from the HF state dict (convert/torch_to_flax.py::
convert_upernet), fidelity-tested against torch.

TPU notes: one fixed canvas per checkpoint (single compiled program);
adaptive average pooling and the align-corners-false bilinear resizes are
einsum contractions against constant interpolation matrices (MXU-
friendly, no gathers); BatchNorms run in inference form from their
converted running statistics. The argmax class map leaves the chip as
uint8; the ADE palette lookup is host-side.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class UperNetConfig:
    # ConvNeXt backbone (openmmlab/upernet-convnext-small defaults)
    depths: Sequence[int] = (3, 3, 27, 3)
    hidden_sizes: Sequence[int] = (96, 192, 384, 768)
    layer_scale: bool = True
    # decode head
    channels: int = 512
    pool_scales: Sequence[int] = (1, 2, 3, 6)
    num_labels: int = 150
    image_size: int = 512
    dtype: str = "float32"


UPERNET_CONVNEXT_SMALL = UperNetConfig()

UPERNET_TINY = UperNetConfig(depths=(1, 1, 1, 1),
                             hidden_sizes=(8, 16, 24, 32), channels=16,
                             num_labels=10, image_size=64)

UPERNET_CONFIGS = {"upernet_convnext_small": UPERNET_CONVNEXT_SMALL,
                   "upernet_tiny": UPERNET_TINY}


def _resize_matrix(n_in: int, n_out: int) -> np.ndarray:
    """(n_out, n_in) bilinear weights, half-pixel centers (torch
    ``interpolate(..., align_corners=False)``)."""
    w = np.zeros((n_out, n_in), np.float32)
    pos = (np.arange(n_out) + 0.5) * n_in / n_out - 0.5
    pos = pos.clip(0, n_in - 1)
    lo = np.floor(pos).astype(np.int64)
    hi = np.minimum(lo + 1, n_in - 1)
    frac = (pos - lo).astype(np.float32)
    w[np.arange(n_out), lo] += 1.0 - frac
    w[np.arange(n_out), hi] += frac
    return w


def _adaptive_pool_matrix(n_in: int, n_out: int) -> np.ndarray:
    """(n_out, n_in) averaging weights matching torch
    ``adaptive_avg_pool2d`` window placement."""
    w = np.zeros((n_out, n_in), np.float32)
    for o in range(n_out):
        start = (o * n_in) // n_out
        end = -(-(o + 1) * n_in // n_out)
        w[o, start:end] = 1.0 / (end - start)
    return w


def _apply_sep(x: jnp.ndarray, wh: np.ndarray, ww: np.ndarray) -> jnp.ndarray:
    """(B, H, W, C) x separable row/col weight matrices."""
    x = jnp.einsum("oh,bhwc->bowc", jnp.asarray(wh), x)
    return jnp.einsum("pw,bowc->bopc", jnp.asarray(ww), x)


def resize_bilinear(x: jnp.ndarray, size: tuple[int, int]) -> jnp.ndarray:
    b, h, w, c = x.shape
    if (h, w) == size:
        return x
    return _apply_sep(x, _resize_matrix(h, size[0]),
                      _resize_matrix(w, size[1]))


def adaptive_avg_pool(x: jnp.ndarray, scale: int) -> jnp.ndarray:
    b, h, w, c = x.shape
    return _apply_sep(x, _adaptive_pool_matrix(h, scale),
                      _adaptive_pool_matrix(w, scale))


class ConvNextLayer(nn.Module):
    dim: int
    layer_scale: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        h = nn.Conv(self.dim, (7, 7), padding=3,
                    feature_group_count=self.dim, dtype=self.dtype,
                    name="dwconv")(x)
        h = nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32,
                         name="layernorm")(h).astype(self.dtype)
        h = nn.Dense(4 * self.dim, dtype=self.dtype, name="pwconv1")(h)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(self.dim, dtype=self.dtype, name="pwconv2")(h)
        if self.layer_scale:
            gamma = self.param("layer_scale_parameter",
                               nn.initializers.ones, (self.dim,))
            h = h * gamma.astype(self.dtype)
        return residual + h


class BNConv(nn.Module):
    """UperNetConvModule: conv (no bias) + inference BatchNorm + ReLU."""

    channels: int
    kernel: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h = nn.Conv(self.channels, (self.kernel, self.kernel),
                    padding=self.kernel // 2, use_bias=False,
                    dtype=self.dtype, name="conv")(x)
        scale = self.param("bn_scale", nn.initializers.ones,
                           (self.channels,))
        bias = self.param("bn_bias", nn.initializers.zeros,
                          (self.channels,))
        mean = self.param("bn_mean", nn.initializers.zeros,
                          (self.channels,))
        var = self.param("bn_var", nn.initializers.ones, (self.channels,))
        h = (h.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + 1e-5)
        return nn.relu((h * scale + bias).astype(self.dtype))


class UperNetSeg(nn.Module):
    """(B, S, S, 3) normalized pixels -> (B, S, S) uint8 class ids."""

    config: UperNetConfig

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(self, pixel_values: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dtype = self.dtype
        x = pixel_values.astype(dtype)

        # ---- ConvNeXt backbone
        x = nn.Conv(cfg.hidden_sizes[0], (4, 4), strides=(4, 4),
                    dtype=dtype, name="patch_embed")(x)
        x = nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32,
                         name="embed_norm")(x).astype(dtype)
        features = []
        for s, (depth, dim) in enumerate(zip(cfg.depths, cfg.hidden_sizes)):
            if s > 0:
                x = nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32,
                                 name=f"down_norm_{s}")(x).astype(dtype)
                x = nn.Conv(dim, (2, 2), strides=(2, 2), dtype=dtype,
                            name=f"down_conv_{s}")(x)
            for i in range(depth):
                x = ConvNextLayer(dim, cfg.layer_scale, dtype,
                                  name=f"stage{s}_layer{i}")(x)
            f = nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32,
                             name=f"out_norm_{s}")(x).astype(dtype)
            features.append(f)

        # ---- PSP over the last feature
        last = features[-1]
        size = last.shape[1:3]
        psp = [last]
        for k, scale in enumerate(cfg.pool_scales):
            p = adaptive_avg_pool(last, scale)
            p = BNConv(cfg.channels, 1, dtype, name=f"psp_{k}")(p)
            psp.append(resize_bilinear(p, size))
        lat_last = BNConv(cfg.channels, 3, dtype, name="bottleneck")(
            jnp.concatenate(psp, axis=-1))

        # ---- FPN top-down
        laterals = [BNConv(cfg.channels, 1, dtype, name=f"lateral_{i}")(
            features[i]) for i in range(len(features) - 1)]
        laterals.append(lat_last)
        for i in range(len(laterals) - 1, 0, -1):
            laterals[i - 1] = laterals[i - 1] + resize_bilinear(
                laterals[i], laterals[i - 1].shape[1:3])
        outs = [BNConv(cfg.channels, 3, dtype, name=f"fpn_{i}")(
            laterals[i]) for i in range(len(laterals) - 1)]
        outs.append(laterals[-1])
        target = outs[0].shape[1:3]
        outs = [resize_bilinear(o, target) for o in outs]
        fused = BNConv(cfg.channels, 3, dtype, name="fpn_bottleneck")(
            jnp.concatenate(outs, axis=-1))
        logits = nn.Conv(cfg.num_labels, (1, 1), dtype=jnp.float32,
                         name="classifier")(fused)
        # HF upsamples logits to the INPUT size, not a fixed canvas —
        # caught by the published-config oracle run at a non-canvas input
        logits = resize_bilinear(logits, pixel_values.shape[1:3])
        return jnp.argmax(logits, axis=-1).astype(jnp.uint8)


@dataclasses.dataclass
class UperNetDetector:
    """Host wrapper: resize/normalize to the canvas, run the jitted
    model, map class ids through the ADE palette."""

    params: dict
    config: UperNetConfig = UPERNET_CONVNEXT_SMALL

    def __post_init__(self) -> None:
        self._net = UperNetSeg(self.config)
        self._fwd = jax.jit(lambda p, x: self._net.apply(p, x))

    @classmethod
    def random(cls, seed: int = 0,
               config: UperNetConfig = UPERNET_TINY) -> "UperNetDetector":
        net = UperNetSeg(config)
        x = jnp.zeros((1, config.image_size, config.image_size, 3),
                      jnp.float32)
        return cls(params=jax.jit(net.init)(jax.random.PRNGKey(seed), x),
                   config=config)

    @classmethod
    def from_checkpoint(cls, path,
                        config: UperNetConfig = UPERNET_CONVNEXT_SMALL,
                        ) -> "UperNetDetector":
        from chiaswarm_tpu.convert.torch_to_flax import (
            convert_upernet,
            read_torch_weights,
        )

        return cls(params=convert_upernet(read_torch_weights(path)),
                   config=config)

    def class_map(self, image: np.ndarray) -> np.ndarray:
        import cv2

        h, w = image.shape[:2]
        s = self.config.image_size
        resized = cv2.resize(image, (s, s), interpolation=cv2.INTER_CUBIC)
        arr = resized.astype(np.float32) / 255.0
        # ImageNet normalization (the UperNet image processor)
        mean = np.asarray([0.485, 0.456, 0.406], np.float32)
        std = np.asarray([0.229, 0.224, 0.225], np.float32)
        arr = (arr - mean) / std
        out = np.asarray(self._fwd(self.params, jnp.asarray(arr)[None]))[0]
        return cv2.resize(out, (w, h), interpolation=cv2.INTER_NEAREST)

    def __call__(self, image: np.ndarray) -> np.ndarray:
        """uint8 RGB -> uint8 RGB ADE-colored segmentation map."""
        from chiaswarm_tpu.workloads.ade_palette import ADE20K_PALETTE

        classes = self.class_map(image)
        # class k -> palette row k, exactly the reference's mapping
        # (input_processor.py:109-113; row 0 is black)
        idx = np.minimum(classes.astype(np.int32),
                         len(ADE20K_PALETTE) - 1)
        return ADE20K_PALETTE[idx]
