"""Static architecture configs for every supported model family.

The reference is model-agnostic: the hive names a diffusers pipeline class
and checkpoint per job (swarm/job_arguments.py:104-151). Our equivalent seam
is a *family registry*: a hive model name maps to a :class:`ModelFamily`
(architecture + schedule defaults), and the checkpoint converter
(chiaswarm_tpu.convert) maps its weights onto these Flax modules.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class TextEncoderConfig:
    vocab_size: int = 49408
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 77
    hidden_act: str = "quick_gelu"  # "quick_gelu" | "gelu"
    # which hidden layer to read out (-1 = final, -2 = penultimate "clip skip")
    output_layer: int = -1
    final_layer_norm: bool = True
    projection_dim: int | None = None  # OpenCLIP text projection (SDXL enc 2)
    eos_token_id: int = 49407


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    sample_channels: int = 4
    out_channels: int = 4
    block_out_channels: Sequence[int] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    # per-resolution: 0 = plain ResNet block, N = transformer depth
    transformer_depth: Sequence[int] = (1, 1, 1, 0)
    attention_head_dim: int | Sequence[int] = 8  # SD1.5 stores head *count*
    head_dim_is_count: bool = True               # SD1.5 quirk; False = per-head dim
    # None = attention blocks have NO text cross-attention (self-attn +
    # feed-forward only), the AudioLDM UNet layout
    cross_attention_dim: int | None = 768
    use_linear_projection: bool = False
    # SDXL micro-conditioning: concat(sin(time_ids), pooled_text) -> MLP
    addition_embed_dim: int | None = None        # 256 for SDXL
    addition_pooled_dim: int | None = None       # 1280 for SDXL
    # class-label conditioning table (SD-x4-upscaler noise_level: an
    # nn.Embed(num_class_embeds, time_embed_dim) added to the time emb)
    num_class_embeds: int | None = None
    # FiLM conditioning on a continuous vector (AudioLDM text_embeds): a
    # single Linear(class_proj_dim -> time_embed_dim) over float class
    # labels ("simple_projection"), concatenated with — not added to — the
    # time embedding when class_embeddings_concat is set
    class_proj_dim: int | None = None
    class_embeddings_concat: bool = False
    freq_shift: int = 0
    flip_sin_to_cos: bool = True
    dtype: str = "bfloat16"
    # attention dispatch for spatial self-attention: "auto" | "xla" | "flash"
    # (ops/attention.py); text cross-attention always takes the einsum path
    attn_impl: str = "auto"

    def heads_for(self, channels: int, level: int) -> tuple[int, int]:
        """(num_heads, head_dim) at a block level."""
        hd = self.attention_head_dim
        if isinstance(hd, (tuple, list)):
            hd = hd[level]
        if self.head_dim_is_count:
            num_heads = int(hd)
            return num_heads, channels // num_heads
        head_dim = int(hd)
        return channels // head_dim, head_dim


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Sequence[int] = (128, 256, 512, 512)
    layers_per_block: int = 2
    scaling_factor: float = 0.18215
    dtype: str = "bfloat16"

    @property
    def downscale(self) -> int:
        return 2 ** (len(self.block_out_channels) - 1)


@dataclasses.dataclass(frozen=True)
class ModelFamily:
    """Everything static the pipelines need to run one checkpoint family."""

    name: str
    unet: UNetConfig
    vae: VAEConfig
    text_encoders: Sequence[TextEncoderConfig]
    prediction_type: str = "epsilon"
    beta_schedule: str = "scaled_linear"
    default_size: int = 512
    # SDXL conditions on (orig_size, crop_topleft, target_size) time ids
    needs_time_ids: bool = False
    # pipeline class selector: "sd" (DiffusionPipeline) | "upscaler"
    # (LatentUpscalePipeline, swarm/diffusion/upscale.py parity)
    kind: str = "sd"
    # instruct-pix2pix-class: UNet input = concat(noise latents, image
    # latents), dual text+image classifier-free guidance
    # (timbrooks/instruct-pix2pix routing, swarm/job_arguments.py:128-131)
    image_conditioned: bool = False


_CLIP_L = TextEncoderConfig()  # ViT-L/14 text tower: SD1.x, SDXL enc 1
_CLIP_H = TextEncoderConfig(   # OpenCLIP ViT-H text tower: SD2.x
    hidden_size=1024, intermediate_size=4096, num_layers=23, num_heads=16,
    hidden_act="gelu",
)
_CLIP_BIGG = TextEncoderConfig(  # OpenCLIP ViT-bigG text tower: SDXL enc 2
    hidden_size=1280, intermediate_size=5120, num_layers=32, num_heads=20,
    hidden_act="gelu", projection_dim=1280, output_layer=-2,
    final_layer_norm=False,
)

SD15 = ModelFamily(
    name="sd15",
    unet=UNetConfig(),
    vae=VAEConfig(),
    text_encoders=(_CLIP_L,),
    default_size=512,
)

SD21 = ModelFamily(
    name="sd21",
    unet=UNetConfig(
        cross_attention_dim=1024,
        attention_head_dim=64,
        head_dim_is_count=False,
        use_linear_projection=True,
    ),
    vae=VAEConfig(),
    text_encoders=(_CLIP_H,),
    prediction_type="v_prediction",
    default_size=768,
)

SDXL = ModelFamily(
    name="sdxl",
    unet=UNetConfig(
        block_out_channels=(320, 640, 1280),
        # level 0 is a plain DownBlock in real SDXL checkpoints (attention
        # only at the two lower resolutions)
        transformer_depth=(0, 2, 10),
        attention_head_dim=64,
        head_dim_is_count=False,
        cross_attention_dim=2048,
        use_linear_projection=True,
        addition_embed_dim=256,
        addition_pooled_dim=1280,
    ),
    vae=VAEConfig(scaling_factor=0.13025),
    text_encoders=(
        dataclasses.replace(_CLIP_L, output_layer=-2, final_layer_norm=False),
        _CLIP_BIGG,
    ),
    default_size=1024,
    needs_time_ids=True,
)

# instruct-pix2pix: SD1.5 arch with an 8-channel UNet input (noise latents
# + unscaled image latents) and dual text/image guidance.
PIX2PIX = ModelFamily(
    name="pix2pix",
    unet=UNetConfig(sample_channels=8),
    vae=VAEConfig(),
    text_encoders=(_CLIP_L,),
    default_size=512,
    image_conditioned=True,
)

# 4x pixel upscaler (stabilityai/stable-diffusion-x4-upscaler-class): the
# text-conditioned super-resolution stage the reference runs as IF stage 3
# (swarm/diffusion/diffusion_func_if.py:31-40). The UNet denoises 4-ch
# latents channel-concatenated with the NOISED low-res RGB image (7 input
# channels) and conditions on the noise level through a 1000-entry class
# embedding; the f=4 VAE decodes latents at the LOW-RES grid to 4x pixels.
UPSCALER_X4 = ModelFamily(
    name="upscaler_x4",
    unet=UNetConfig(
        sample_channels=7,
        out_channels=4,
        block_out_channels=(256, 512, 512, 1024),
        transformer_depth=(0, 1, 1, 1),  # DownBlock2D first level
        attention_head_dim=8,
        head_dim_is_count=True,
        cross_attention_dim=1024,        # OpenCLIP ViT-H text tower
        use_linear_projection=True,
        num_class_embeds=1000,
    ),
    vae=VAEConfig(block_out_channels=(128, 256, 512),  # f=4 decoder
                  scaling_factor=0.08333),
    text_encoders=(_CLIP_H,),
    prediction_type="v_prediction",
    default_size=512,
    kind="upscaler4",
)

# 2x latent upscaler (sd-x2-latent-upscaler-class): the UNet denoises the
# 2x latent grid conditioned on the nearest-upsampled low-res latents
# concatenated on channels (sample_channels = 2 * latent_channels). Run by
# the reference after generation when the server flags ``upscale``
# (swarm/diffusion/upscale.py:6-32, swarm/job_arguments.py:104-110).
UPSCALER_X2 = ModelFamily(
    name="upscaler_x2",
    unet=UNetConfig(
        sample_channels=8,
        out_channels=4,
        block_out_channels=(384, 768, 768),
        transformer_depth=(1, 1, 1),
        attention_head_dim=64,
        head_dim_is_count=False,
        cross_attention_dim=768,
        use_linear_projection=True,
    ),
    vae=VAEConfig(),
    text_encoders=(_CLIP_L,),
    default_size=512,
    kind="upscaler",
)

# Hermetic-test family: full architecture shape, toy widths — runs on CPU in
# seconds (the tiny-model registry called for by SURVEY.md §4).
TINY = ModelFamily(
    name="tiny",
    unet=UNetConfig(
        block_out_channels=(32, 64),
        layers_per_block=1,
        transformer_depth=(1, 1),
        attention_head_dim=4,
        head_dim_is_count=True,
        cross_attention_dim=32,
        dtype="float32",
    ),
    vae=VAEConfig(block_out_channels=(16, 32), layers_per_block=1,
                  dtype="float32"),
    text_encoders=(
        TextEncoderConfig(vocab_size=1000, hidden_size=32,
                          intermediate_size=64, num_layers=2, num_heads=4,
                          max_position_embeddings=77, eos_token_id=999),
    ),
    default_size=64,
)

TINY_XL = ModelFamily(
    name="tiny_xl",
    unet=UNetConfig(
        block_out_channels=(32, 64),
        layers_per_block=1,
        transformer_depth=(0, 2),  # mirrors SDXL's attention-free first level
        attention_head_dim=8,
        head_dim_is_count=False,
        cross_attention_dim=64,
        use_linear_projection=True,
        addition_embed_dim=32,
        addition_pooled_dim=32,
        dtype="float32",
    ),
    vae=VAEConfig(block_out_channels=(16, 32), layers_per_block=1,
                  scaling_factor=0.13025, dtype="float32"),
    text_encoders=(
        TextEncoderConfig(vocab_size=1000, hidden_size=32,
                          intermediate_size=64, num_layers=2, num_heads=4,
                          eos_token_id=999),
        TextEncoderConfig(vocab_size=1000, hidden_size=32,
                          intermediate_size=64, num_layers=2, num_heads=4,
                          projection_dim=32, output_layer=-2,
                          final_layer_norm=False, eos_token_id=999),
    ),
    default_size=64,
    needs_time_ids=True,
)

# Tiny upscaler family for hermetic tests (concat-conditioned 8ch UNet).
TINY_UP = ModelFamily(
    name="tiny_up",
    unet=UNetConfig(
        sample_channels=8,
        out_channels=4,
        block_out_channels=(32, 64),
        layers_per_block=1,
        transformer_depth=(1, 1),
        attention_head_dim=4,
        head_dim_is_count=True,
        cross_attention_dim=32,
        dtype="float32",
    ),
    vae=VAEConfig(block_out_channels=(16, 32), layers_per_block=1,
                  dtype="float32"),
    text_encoders=(
        TextEncoderConfig(vocab_size=1000, hidden_size=32,
                          intermediate_size=64, num_layers=2, num_heads=4,
                          max_position_embeddings=77, eos_token_id=999),
    ),
    default_size=64,
    kind="upscaler",
)

# Tiny x4-upscaler family for hermetic tests (7ch UNet, noise-level class
# embedding, f=4 VAE).
TINY_UP4 = ModelFamily(
    name="tiny_up4",
    unet=UNetConfig(
        sample_channels=7,
        out_channels=4,
        block_out_channels=(32, 64),
        layers_per_block=1,
        transformer_depth=(0, 1),
        attention_head_dim=4,
        head_dim_is_count=True,
        cross_attention_dim=32,
        use_linear_projection=True,
        num_class_embeds=50,
        dtype="float32",
    ),
    vae=VAEConfig(block_out_channels=(16, 32, 32), layers_per_block=1,
                  scaling_factor=0.08333, dtype="float32"),
    text_encoders=(
        TextEncoderConfig(vocab_size=1000, hidden_size=32,
                          intermediate_size=64, num_layers=2, num_heads=4,
                          max_position_embeddings=77, eos_token_id=999),
    ),
    default_size=64,
    prediction_type="v_prediction",
    kind="upscaler4",
)

# Tiny image-conditioned family for hermetic pix2pix tests.
TINY_P2P = ModelFamily(
    name="tiny_p2p",
    unet=UNetConfig(
        sample_channels=8,
        block_out_channels=(32, 64),
        layers_per_block=1,
        transformer_depth=(1, 1),
        attention_head_dim=4,
        head_dim_is_count=True,
        cross_attention_dim=32,
        dtype="float32",
    ),
    vae=VAEConfig(block_out_channels=(16, 32), layers_per_block=1,
                  dtype="float32"),
    text_encoders=(
        TextEncoderConfig(vocab_size=1000, hidden_size=32,
                          intermediate_size=64, num_layers=2, num_heads=4,
                          max_position_embeddings=77, eos_token_id=999),
    ),
    default_size=64,
    image_conditioned=True,
)

FAMILIES: dict[str, ModelFamily] = {
    f.name: f for f in (SD15, SD21, SDXL, PIX2PIX, UPSCALER_X2, UPSCALER_X4,
                        TINY, TINY_XL, TINY_UP, TINY_UP4, TINY_P2P)
}

# hive model-name prefixes -> family (the dispatch the reference does via
# server-sent pipeline class names, swarm/job_arguments.py:104-151).
# ORDER MATTERS: "x4" must outrank the generic "upscale" hint so
# stabilityai/stable-diffusion-x4-upscaler lands on the 4x family.
_NAME_HINTS = (
    ("x4-upscaler", "upscaler_x4"),
    ("x4", "upscaler_x4"),
    ("latent-upscaler", "upscaler_x2"),
    ("upscale", "upscaler_x2"),
    ("pix2pix", "pix2pix"),
    ("xl", "sdxl"),
    ("stable-diffusion-2", "sd21"),
    ("sd2", "sd21"),
)


def get_family(model_name: str) -> ModelFamily:
    low = (model_name or "").lower()
    # exact family name (full or basename) wins over substring hints —
    # "random/tiny_xl" must hit tiny_xl, not the "xl" hint
    if low in FAMILIES:
        return FAMILIES[low]
    tail = low.rsplit("/", 1)[-1]
    if tail in FAMILIES:
        return FAMILIES[tail]
    for hint, family in _NAME_HINTS:
        if hint in low:
            return FAMILIES[family]
    return FAMILIES["sd15"]
