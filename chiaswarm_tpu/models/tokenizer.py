"""Prompt tokenizers.

The reference delegates tokenization to the CLIPTokenizer bundled inside each
diffusers pipeline (loaded per job, swarm/diffusion/diffusion_func.py:41-46).
Here tokenization is a host-side component with two implementations:

- :class:`ClipBpeTokenizer` — a self-contained CLIP byte-pair-encoding
  tokenizer reading the standard ``vocab.json`` + ``merges.txt`` files from a
  local checkpoint directory (no network, no transformers dependency).
- :class:`HashTokenizer` — deterministic hashing tokenizer for hermetic
  tests and random-weight benchmarks where real vocab files are absent.

Both produce fixed-length (77) id sequences with BOS/EOS/pad, the static
shape every text encoder compiles against.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Protocol, Sequence

import numpy as np


class Tokenizer(Protocol):
    max_length: int

    def encode(self, text: str) -> list[int]: ...

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray: ...


class AddedTokenMixin:
    """Placeholder-token registry for textual inversion (the reference's
    ``load_textual_inversion`` path, swarm/diffusion/diffusion_func.py:48-54).
    A registered token string maps to one or more embedding ids (multi-vector
    concepts) and is extracted from the prompt before normal tokenization."""

    _added: dict[str, list[int]]

    def add_token(self, token: str, ids: list[int]) -> None:
        if not hasattr(self, "_added"):
            self._added = {}
        self._added[token] = list(ids)

    def _split_added(self, text: str) -> list[str | list[int]]:
        """Split the prompt into plain-text spans and added-token id runs."""
        if not getattr(self, "_added", None):
            return [text]
        pattern = "|".join(re.escape(t) for t in
                           sorted(self._added, key=len, reverse=True))
        parts: list[str | list[int]] = []
        pos = 0
        for m in re.finditer(pattern, text):
            if m.start() > pos:
                parts.append(text[pos:m.start()])
            parts.append(self._added[m.group(0)])
            pos = m.end()
        if pos < len(text):
            parts.append(text[pos:])
        return parts


_WORD_RE = re.compile(
    r"'s|'t|'re|'ve|'m|'ll|'d|[a-z]+|[0-9]|[^\sa-z0-9]+", re.IGNORECASE
)


def _basic_tokens(text: str) -> list[str]:
    text = re.sub(r"\s+", " ", text.strip().lower())
    return _WORD_RE.findall(text)


class ClipBpeTokenizer(AddedTokenMixin):
    """CLIP BPE over ``vocab.json``/``merges.txt`` (openai/clip format).

    ASCII-oriented pre-tokenization (the CLIP regex's unicode classes reduced
    to ASCII letter/digit classes); non-ASCII characters fall through as
    single-symbol tokens and map to <unk>-free byte-level entries when the
    vocab has them.
    """

    def __init__(self, vocab: dict[str, int], merges: list[tuple[str, str]],
                 max_length: int = 77) -> None:
        self.vocab = vocab
        self.ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.max_length = max_length
        self.bos_id = vocab.get("<|startoftext|>", 49406)
        self.eos_id = vocab.get("<|endoftext|>", 49407)
        self._cache: dict[str, list[str]] = {}

    @classmethod
    def from_dir(cls, path: str | Path, max_length: int = 77) -> "ClipBpeTokenizer":
        path = Path(path)
        with open(path / "vocab.json", encoding="utf-8") as fh:
            vocab = json.load(fh)
        merges: list[tuple[str, str]] = []
        with open(path / "merges.txt", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#version"):
                    continue
                a, _, b = line.partition(" ")
                merges.append((a, b))
        return cls(vocab, merges, max_length)

    def _bpe(self, token: str) -> list[str]:
        if token in self._cache:
            return self._cache[token]
        word = list(token[:-1]) + [token[-1] + "</w>"]
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            best = min(pairs, key=lambda p: self.ranks.get(p, 1 << 30))
            if best not in self.ranks:
                break
            a, b = best
            merged: list[str] = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and word[i] == a and word[i + 1] == b:
                    merged.append(a + b)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = merged
        self._cache[token] = word
        return word

    def encode(self, text: str) -> list[int]:
        ids = [self.bos_id]
        for span in self._split_added(text):
            if isinstance(span, list):  # textual-inversion placeholder run
                ids.extend(span)
            else:
                for tok in _basic_tokens(span):
                    for piece in self._bpe(tok):
                        pid = self.vocab.get(piece)
                        # drop unknown pieces: mapping them to eos would
                        # hijack the first-EOS pooled readout (models/
                        # clip.py argmax pooling)
                        if pid is not None:
                            ids.append(pid)
                    if len(ids) >= self.max_length - 1:
                        break
            if len(ids) >= self.max_length - 1:
                break
        ids = ids[: self.max_length - 1]
        ids.append(self.eos_id)
        ids += [self.eos_id] * (self.max_length - len(ids))  # CLIP pads w/ eos
        return ids

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray:
        return np.asarray([self.encode(t) for t in texts], dtype=np.int32)


def _bytes_to_unicode() -> dict[int, str]:
    """GPT-2's reversible byte -> printable-unicode table (the byte-level
    BPE alphabet RoBERTa-family vocab.json files are written in)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {b: chr(c) for b, c in zip(bs, cs)}


# the EXACT GPT-2/RoBERTa pre-tokenization pattern (transformers'
# RobertaTokenizer): unicode letter/number classes, so accented and CJK
# prompts split into the same spans (ADVICE r4 #1 — the earlier
# ASCII-only classes silently produced different token ids for them).
# The `regex` module provides \p{L}/\p{N}; plain `re` classes are the
# fallback ([^\W\d_] is re's unicode-letter idiom).
try:
    import regex as _regex

    _GPT2_WORD_RE = _regex.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+"
        r"|\s+(?!\S)|\s+"
    )
except ImportError:  # pragma: no cover
    # best-effort re-only approximation: underscores ride the symbol
    # class (as in the real pattern); non-decimal \p{N} numerics (e.g.
    # superscripts) still split as symbols here — exactness needs `regex`
    _GPT2_WORD_RE = re.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+| ?(?:[^\s\w]|_)+"
        r"|\s+(?!\S)|\s+"
    )


class ByteLevelBpeTokenizer(AddedTokenMixin):
    """GPT-2/RoBERTa byte-level BPE over ``vocab.json``/``merges.txt`` —
    the tokenizer format real AudioLDM snapshots ship for the CLAP text
    tower (RobertaTokenizer). Same file names as CLIP's BPE but a disjoint
    algorithm: case-sensitive, bytes mapped through the GPT-2 unicode
    table, space carried as a leading ``Ġ`` on the piece (no ``</w>``
    suffix), RoBERTa ``<s>``/``</s>``/``<pad>`` specials. Pre-tokenizes
    with the exact GPT-2 unicode pattern (``\\p{L}``/``\\p{N}``), so
    accented/CJK prompts form the same spans — and thus the same token
    ids — as transformers' RobertaTokenizer."""

    def __init__(self, vocab: dict[str, int], merges: list[tuple[str, str]],
                 max_length: int = 77) -> None:
        self.vocab = vocab
        self.ranks = {pair: i for i, pair in enumerate(merges)}
        self.max_length = max_length
        self.byte_map = _bytes_to_unicode()
        self.bos_id = vocab.get("<s>", vocab.get("<|endoftext|>", 0))
        self.eos_id = vocab.get("</s>", vocab.get("<|endoftext|>", 2))
        self.pad_id = vocab.get("<pad>", 1)
        self.unk_id = vocab.get("<unk>", self.eos_id)
        self._cache: dict[str, list[str]] = {}

    @classmethod
    def from_dir(cls, path: str | Path, max_length: int = 77
                 ) -> "ByteLevelBpeTokenizer":
        path = Path(path)
        with open(path / "vocab.json", encoding="utf-8") as fh:
            vocab = json.load(fh)
        merges: list[tuple[str, str]] = []
        with open(path / "merges.txt", encoding="utf-8") as fh:
            for line in fh:
                line = line.rstrip("\n")
                if not line or line.startswith("#version"):
                    continue
                a, _, b = line.partition(" ")
                merges.append((a, b))
        return cls(vocab, merges, max_length)

    def _bpe(self, token: str) -> list[str]:
        if token in self._cache:
            return self._cache[token]
        word = list(token)
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            best = min(pairs, key=lambda p: self.ranks.get(p, 1 << 30))
            if best not in self.ranks:
                break
            a, b = best
            merged: list[str] = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and word[i] == a and word[i + 1] == b:
                    merged.append(a + b)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = merged
        self._cache[token] = word
        return word

    def encode(self, text: str) -> list[int]:
        ids = [self.bos_id]
        for span in self._split_added(text):
            if isinstance(span, list):  # textual-inversion placeholder run
                ids.extend(span)
                continue
            for tok in _GPT2_WORD_RE.findall(span):
                mapped = "".join(self.byte_map[b] for b in
                                 tok.encode("utf-8"))
                for piece in self._bpe(mapped):
                    ids.append(self.vocab.get(piece, self.unk_id))
                if len(ids) >= self.max_length - 1:
                    break
            if len(ids) >= self.max_length - 1:
                break
        ids = ids[: self.max_length - 1]
        ids.append(self.eos_id)
        ids += [self.pad_id] * (self.max_length - len(ids))
        return ids

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray:
        return np.asarray([self.encode(t) for t in texts], dtype=np.int32)


class HashTokenizer(AddedTokenMixin):
    """Deterministic, vocab-file-free tokenizer for tiny/hermetic models."""

    def __init__(self, vocab_size: int = 1000, max_length: int = 77,
                 eos_id: int | None = None, bos_id: int | None = None,
                 pad_id: int | None = None, add_bos: bool = True) -> None:
        self.vocab_size = vocab_size
        self.max_length = max_length
        self.eos_id = eos_id if eos_id is not None else vocab_size - 1
        self.bos_id = bos_id if bos_id is not None else self.eos_id - 1
        # CLIP convention pads with EOS; RoBERTa-family towers (CLAP) have
        # a dedicated pad id their attention mask is derived from; T5 has
        # no BOS at all (add_bos=False) and pads with id 0
        self.pad_id = pad_id if pad_id is not None else self.eos_id
        self.add_bos = add_bos
        # hashed ids must never collide with the specials: masks and
        # pooled readouts are derived from exact id equality. Specials sit
        # either at the bottom (CLAP 0/1/2, T5 0/1) or top (CLIP) of the
        # vocab — hash into the contiguous id range between them.
        specials = {self.eos_id, self.bos_id, self.pad_id}
        self._lo = max((s + 1 for s in specials if s < vocab_size // 2),
                       default=0)
        self._hi = min((s for s in specials if s >= vocab_size // 2),
                       default=vocab_size)

    def tokenize(self, text: str) -> list[int]:
        """Raw hashed ids — no bos/eos/pad (the bark semantic stage needs
        specials-free text ids, pipelines/tts.py)."""
        vspan = max(self._hi - self._lo, 1)
        ids: list[int] = []
        for part in self._split_added(text):
            if isinstance(part, list):
                ids.extend(part)
                continue
            for tok in _basic_tokens(part):
                # FNV-1a: platform-stable hashing (hash() is salted)
                h = 2166136261
                for ch in tok.encode("utf-8"):
                    h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
                ids.append(self._lo + h % vspan)
        return ids

    def encode(self, text: str) -> list[int]:
        """[bos +] tokenize() body (truncated) + eos, padded with pad_id."""
        head = [self.bos_id] if self.add_bos else []
        n_special = len(head) + 1
        ids = head + self.tokenize(text)[: self.max_length - n_special]
        ids.append(self.eos_id)
        ids += [self.pad_id] * (self.max_length - len(ids))
        return ids[: self.max_length]

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray:
        return np.asarray([self.encode(t) for t in texts], dtype=np.int32)


class HFTokenizer(AddedTokenMixin):
    """Wrapper over a serialized HuggingFace ``tokenizer.json`` (the fast-
    tokenizer format T5/DeepFloyd snapshots ship instead of CLIP's
    vocab.json+merges.txt). Pads/truncates to a static length so token ids
    stay shape-stable for the jitted encoders."""

    def __init__(self, tokenizer_file: str | Path, max_length: int = 77,
                 pad_id: int = 0) -> None:
        from tokenizers import Tokenizer as _Tok

        self._tok = _Tok.from_file(str(tokenizer_file))
        self.max_length = max_length
        self.pad_id = pad_id

    def encode(self, text: str) -> list[int]:
        ids: list[int] = []
        for span in self._split_added(text):
            if isinstance(span, list):  # textual-inversion placeholder run
                ids.extend(span)
            else:
                ids.extend(self._tok.encode(span).ids)
        ids = ids[: self.max_length]
        ids += [self.pad_id] * (self.max_length - len(ids))
        return ids

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray:
        return np.asarray([self.encode(t) for t in texts], dtype=np.int32)


def _vocab_is_byte_level(vocab_path: Path) -> bool:
    """vocab.json + merges.txt is both CLIP's format and GPT-2/RoBERTa's.
    CLIP vocabs mark word-final pieces with a ``</w>`` suffix; byte-level
    vocabs carry the space as a leading ``Ġ`` instead."""
    with open(vocab_path, encoding="utf-8") as fh:
        keys = json.load(fh).keys()
    return any(k.startswith("Ġ") for k in keys) and not any(
        k.endswith("</w>") for k in keys)


def load_tokenizer(checkpoint_dir: str | Path | None, vocab_size: int = 49408,
                   eos_id: int = 49407, max_length: int = 77,
                   bos_id: int | None = None, pad_id: int | None = None,
                   add_bos: bool = True) -> Tokenizer:
    """ClipBpeTokenizer or ByteLevelBpeTokenizer (RoBERTa/CLAP) when vocab
    files exist locally — distinguished by vocab content — then a
    serialized ``tokenizer.json`` (T5/sentencepiece-family snapshots), else
    HashTokenizer. Falling back on a REAL checkpoint is loud: hash-bucketed
    ids next to converted weights would silently condition on noise."""
    if checkpoint_dir is not None:
        path = Path(checkpoint_dir)
        for sub in ("", "tokenizer", "text_encoder"):
            cand = path / sub if sub else path
            if (cand / "vocab.json").exists() and (cand / "merges.txt").exists():
                if _vocab_is_byte_level(cand / "vocab.json"):
                    return ByteLevelBpeTokenizer.from_dir(cand, max_length)
                return ClipBpeTokenizer.from_dir(cand, max_length)
        for sub in ("", "tokenizer"):
            cand = (path / sub if sub else path) / "tokenizer.json"
            if cand.exists():
                return HFTokenizer(cand, max_length)
        if path.exists():
            import logging

            logging.getLogger("chiaswarm.tokenizer").warning(
                "checkpoint %s has no recognized tokenizer files "
                "(vocab.json+merges.txt or tokenizer.json); falling back to "
                "HashTokenizer — generations will NOT match the reference "
                "model", path)
    return HashTokenizer(vocab_size, max_length, eos_id, bos_id=bos_id,
                         pad_id=pad_id, add_bos=add_bos)


class WordPieceTokenizer:
    """BERT WordPiece tokenizer over a ``vocab.txt`` (the text side of the
    BLIP captioner, models/blip.py). Greedy longest-match with ``##``
    continuation pieces; lowercase basic tokenization. Unlike the prompt
    tokenizers above it also *decodes* — captions come back off-chip as
    token ids (swarm/captioning/caption_image.py:29-30 equivalence)."""

    def __init__(self, vocab: dict[str, int], max_length: int = 64) -> None:
        self.vocab = vocab
        self.ids_to_tokens = {i: t for t, i in vocab.items()}
        self.max_length = max_length
        self.pad_id = vocab.get("[PAD]", 0)
        self.unk_id = vocab.get("[UNK]", 100)
        self.cls_id = vocab.get("[CLS]", 101)
        self.sep_id = vocab.get("[SEP]", 102)
        # BLIP's [DEC]/[ENC] are *added* tokens beyond the stock BERT
        # vocab.txt (ids 30522/30523 on a 30522-line file); register them
        # rather than aliasing a real wordpiece as the decoder-start token
        if "[DEC]" not in self.vocab:
            for extra in ("[DEC]", "[ENC]"):
                idx = len(self.vocab)
                self.vocab[extra] = idx
                self.ids_to_tokens[idx] = extra
        self.bos_id = self.vocab["[DEC]"]

    @classmethod
    def from_vocab_file(cls, path: str | Path,
                        max_length: int = 64) -> "WordPieceTokenizer":
        vocab: dict[str, int] = {}
        with open(path, encoding="utf-8") as fh:
            for i, line in enumerate(fh):
                vocab[line.rstrip("\n")] = i
        return cls(vocab, max_length)

    def _wordpiece(self, word: str) -> list[int]:
        ids: list[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece_id = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece_id = self.vocab[sub]
                    break
                end -= 1
            if piece_id is None:
                return [self.unk_id]
            ids.append(piece_id)
            start = end
        return ids

    def tokenize(self, text: str) -> list[int]:
        ids: list[int] = []
        for word in _basic_tokens(text):
            ids.extend(self._wordpiece(word))
        return ids

    def encode(self, text: str, max_length: int | None = None) -> list[int]:
        """[CLS] tokens [SEP] + [PAD] fill, fixed length."""
        n = max_length or self.max_length
        ids = [self.cls_id] + self.tokenize(text)[: n - 2] + [self.sep_id]
        return ids + [self.pad_id] * (n - len(ids))

    def decode(self, ids: Sequence[int]) -> str:
        words: list[str] = []
        stop = {self.pad_id, self.cls_id, self.sep_id, self.bos_id}
        for i in ids:
            i = int(i)
            if i == self.sep_id:
                break
            if i in stop:
                continue
            tok = self.ids_to_tokens.get(i, "")
            if tok.startswith("##") and words:
                words[-1] += tok[2:]
            else:
                words.append(tok)
        return " ".join(w for w in words if w)
