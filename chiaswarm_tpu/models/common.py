"""Shared building-block helpers for the Flax model zoo."""

from __future__ import annotations

import jax.numpy as jnp


def num_groups(channels: int) -> int:
    """32 GroupNorm groups when divisible (the SD standard); largest divisor
    <= 32 otherwise (tiny hermetic-test widths)."""
    g = min(32, channels)
    while channels % g:
        g -= 1
    return g


def upsample2x_nearest(x: jnp.ndarray) -> jnp.ndarray:
    """Nearest-neighbor x2 on NHWC — lowers to cheap broadcast-reshapes."""
    return jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)
