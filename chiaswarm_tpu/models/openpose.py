"""OpenPose body-pose detector — the last learned ControlNet preprocessor.

The reference gets skeletons from ``controlnet_aux``'s OpenposeDetector
(swarm/controlnet/input_processor.py:17-60 dispatch); this is a native
implementation of the same CMU two-branch network (VGG trunk + 6 stages of
PAF/heatmap branches) in Flax, with the standard part-affinity-field
assembly and skeleton rendering on the host.

The network runs under jit (CPU or chip — it is a tiny CNN next to the
diffusion workloads); peak finding, bipartite limb assembly, and drawing
are numpy/OpenCV host code, like every other preprocessor in
workloads/controlnet.py. Weights convert from the public CMU
``body_pose_model.pth`` layout (convert/torch_to_flax.py::convert_openpose).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# (name, out_channels, kernel, relu) per conv — the fixed CMU body graph
_TRUNK = [
    ("conv1_1", 64, 3), ("conv1_2", 64, 3), ("pool", 0, 0),
    ("conv2_1", 128, 3), ("conv2_2", 128, 3), ("pool", 0, 0),
    ("conv3_1", 256, 3), ("conv3_2", 256, 3), ("conv3_3", 256, 3),
    ("conv3_4", 256, 3), ("pool", 0, 0),
    ("conv4_1", 512, 3), ("conv4_2", 512, 3),
    ("conv4_3_CPM", 256, 3), ("conv4_4_CPM", 128, 3),
]

N_PAF, N_HEAT = 38, 19

# COCO-18 limb topology: (joint_a, joint_b) and their PAF channel pairs
LIMB_SEQ = [(1, 2), (1, 5), (2, 3), (3, 4), (5, 6), (6, 7), (1, 8),
            (8, 9), (9, 10), (1, 11), (11, 12), (12, 13), (1, 0),
            (0, 14), (14, 16), (0, 15), (15, 17), (2, 16), (5, 17)]
MAP_IDX = [(31, 32), (39, 40), (33, 34), (35, 36), (41, 42), (43, 44),
           (19, 20), (21, 22), (23, 24), (25, 26), (27, 28), (29, 30),
           (47, 48), (49, 50), (53, 54), (51, 52), (55, 56), (37, 38),
           (45, 46)]

_COLORS = [
    (255, 0, 0), (255, 85, 0), (255, 170, 0), (255, 255, 0), (170, 255, 0),
    (85, 255, 0), (0, 255, 0), (0, 255, 85), (0, 255, 170), (0, 255, 255),
    (0, 170, 255), (0, 85, 255), (0, 0, 255), (85, 0, 255), (170, 0, 255),
    (255, 0, 255), (255, 0, 170), (255, 0, 85),
]


class BodyPoseNet(nn.Module):
    """(B, H, W, 3) in [-0.5, 0.5] -> (paf (B, H/8, W/8, 38),
    heatmap (B, H/8, W/8, 19)). Six refinement stages, CMU naming."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        conv = lambda ch, k, name: nn.Conv(
            ch, (k, k), padding=k // 2, dtype=self.dtype, name=name)
        for name, ch, k in _TRUNK:
            if name == "pool":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.relu(conv(ch, k, name)(x))
        feat = x

        def stage1(branch: int, out_ch: int) -> jnp.ndarray:
            h = feat
            for i in (1, 2, 3):
                h = nn.relu(conv(128, 3, f"conv5_{i}_CPM_L{branch}")(h))
            h = nn.relu(conv(512, 1, f"conv5_4_CPM_L{branch}")(h))
            return conv(out_ch, 1, f"conv5_5_CPM_L{branch}")(h)

        def stage_t(t: int, branch: int, out_ch: int,
                    inp: jnp.ndarray) -> jnp.ndarray:
            h = inp
            for i in (1, 2, 3, 4, 5):
                h = nn.relu(conv(128, 7, f"Mconv{i}_stage{t}_L{branch}")(h))
            h = nn.relu(conv(128, 1, f"Mconv6_stage{t}_L{branch}")(h))
            return conv(out_ch, 1, f"Mconv7_stage{t}_L{branch}")(h)

        paf, heat = stage1(1, N_PAF), stage1(2, N_HEAT)
        for t in range(2, 7):
            inp = jnp.concatenate([paf, heat, feat], axis=-1)
            paf, heat = stage_t(t, 1, N_PAF, inp), stage_t(t, 2, N_HEAT, inp)
        return paf, heat


# ------------------------------------------------------- host assembly

def find_peaks(heatmap: np.ndarray, thre1: float = 0.1) -> list[list[tuple]]:
    """Per-joint peak list [(x, y, score, id), ...] from the (H, W, 19)
    upsampled heatmap (channel 18 is background)."""
    import cv2

    all_peaks: list[list[tuple]] = []
    peak_id = 0
    for part in range(18):
        m = cv2.GaussianBlur(heatmap[:, :, part], (0, 0), 3)
        up = np.zeros_like(m); up[1:, :] = m[:-1, :]
        down = np.zeros_like(m); down[:-1, :] = m[1:, :]
        left = np.zeros_like(m); left[:, 1:] = m[:, :-1]
        right = np.zeros_like(m); right[:, :-1] = m[:, 1:]
        is_peak = (m >= up) & (m >= down) & (m >= left) & (m >= right) & \
                  (m > thre1)
        ys, xs = np.nonzero(is_peak)
        peaks = []
        for x, y in zip(xs, ys):
            peaks.append((int(x), int(y), float(heatmap[y, x, part]),
                          peak_id))
            peak_id += 1
        all_peaks.append(peaks)
    return all_peaks


def score_limbs(paf: np.ndarray, all_peaks, thre2: float = 0.05,
                n_sample: int = 10):
    """Score candidate limbs by the PAF line integral; greedy-match each
    limb type. Returns connection_all[k] = [(idA, idB, score, ia, ib)]."""
    h = paf.shape[0]
    connection_all = []
    for k, (ja, jb) in enumerate(LIMB_SEQ):
        ca, cb = all_peaks[ja], all_peaks[jb]
        if not ca or not cb:
            connection_all.append([])
            continue
        score_map = paf[:, :, [MAP_IDX[k][0] - 19, MAP_IDX[k][1] - 19]]
        candidates = []
        for ia, a in enumerate(ca):
            for ib, b in enumerate(cb):
                vec = np.array([b[0] - a[0], b[1] - a[1]], np.float32)
                norm = max(float(np.linalg.norm(vec)), 1e-6)
                u = vec / norm
                xs = np.linspace(a[0], b[0], n_sample).round().astype(int)
                ys = np.linspace(a[1], b[1], n_sample).round().astype(int)
                vals = score_map[ys, xs]                  # (n, 2)
                dots = vals @ u
                prior = min(0.5 * h / norm - 1.0, 0.0)    # length penalty
                score = float(dots.mean()) + prior
                ok = (dots > thre2).sum() > 0.8 * n_sample
                if ok and score > 0:
                    candidates.append((ia, ib, score))
        candidates.sort(key=lambda c: c[2], reverse=True)
        used_a, used_b, conns = set(), set(), []
        for ia, ib, s in candidates:
            if ia in used_a or ib in used_b:
                continue
            used_a.add(ia); used_b.add(ib)
            conns.append((ca[ia][3], cb[ib][3], s, ia, ib))
        connection_all.append(conns)
    return connection_all


def assemble_people(all_peaks, connection_all, min_parts: int = 4,
                    min_score: float = 0.4) -> list[np.ndarray]:
    """Greedy subset assembly (the standard CMU merge): each person is a
    length-20 row — 18 joint peak-ids (-1 absent), total score, #parts."""
    flat = [p for peaks in all_peaks for p in peaks]
    score_of = {p[3]: p[2] for p in flat}
    subsets: list[np.ndarray] = []
    for k, (ja, jb) in enumerate(LIMB_SEQ):
        for ida, idb, s, _, _ in connection_all[k]:
            found = [i for i, row in enumerate(subsets)
                     if row[ja] == ida or row[jb] == idb]
            if len(found) == 1:
                row = subsets[found[0]]
                if row[jb] != idb:
                    row[jb] = idb
                    row[19] += 1
                    row[18] += score_of[idb] + s
                elif row[ja] != ida:
                    row[ja] = ida
                    row[19] += 1
                    row[18] += score_of[ida] + s
            elif len(found) == 2:
                r1, r2 = subsets[found[0]], subsets[found[1]]
                if not np.any((r1[:18] >= 0) & (r2[:18] >= 0)):
                    r1[:18] = np.where(r2[:18] >= 0, r2[:18], r1[:18])
                    r1[18] += r2[18] + s
                    r1[19] += r2[19]
                    subsets.pop(found[1])
                else:
                    r1[jb] = idb
                    r1[19] += 1
                    r1[18] += score_of[idb] + s
            else:
                row = np.full(20, -1.0)
                row[ja], row[jb] = ida, idb
                row[19] = 2
                row[18] = score_of[ida] + score_of[idb] + s
                subsets.append(row)
    return [row for row in subsets
            if row[19] >= min_parts and row[18] / row[19] >= min_score]


def draw_skeletons(shape: tuple[int, int], all_peaks, subsets) -> np.ndarray:
    """Render the openpose conditioning image: colored limbs + joints on
    black, (H, W, 3) uint8."""
    import cv2

    h, w = shape
    canvas = np.zeros((h, w, 3), np.uint8)
    pos = {p[3]: (p[0], p[1]) for peaks in all_peaks for p in peaks}
    for row in subsets:
        for k, (ja, jb) in enumerate(LIMB_SEQ[:17]):
            ida, idb = int(row[ja]), int(row[jb])
            if ida < 0 or idb < 0:
                continue
            (xa, ya), (xb, yb) = pos[ida], pos[idb]
            mx, my = (xa + xb) / 2, (ya + yb) / 2
            length = float(np.hypot(xa - xb, ya - yb))
            angle = float(np.degrees(np.arctan2(ya - yb, xa - xb)))
            poly = cv2.ellipse2Poly((int(mx), int(my)),
                                    (int(length / 2), 4), int(angle), 0,
                                    360, 1)
            cv2.fillConvexPoly(canvas, poly, _COLORS[k % len(_COLORS)])
        for j in range(18):
            idx = int(row[j])
            if idx >= 0:
                cv2.circle(canvas, pos[idx], 4, _COLORS[j], thickness=-1)
    return canvas


@dataclasses.dataclass
class OpenposeDetector:
    """Ties the jitted CNN to the host assembly. ``params`` is the Flax
    tree (converted body_pose_model weights, or random for shape tests)."""

    params: dict
    box_size: int = 368
    stride: int = 8

    def __post_init__(self) -> None:
        self._net = BodyPoseNet()
        self._fwd = jax.jit(lambda p, x: self._net.apply(p, x))

    @classmethod
    def random(cls, seed: int = 0) -> "OpenposeDetector":
        net = BodyPoseNet()
        x = jnp.zeros((1, 64, 64, 3), jnp.float32)
        return cls(params=jax.jit(net.init)(jax.random.PRNGKey(seed), x))

    @classmethod
    def from_checkpoint(cls, path) -> "OpenposeDetector":
        from chiaswarm_tpu.convert.torch_to_flax import (
            convert_openpose,
            read_torch_weights,
        )

        return cls(params=convert_openpose(read_torch_weights(path)))

    def maps(self, image: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(H, W, 3) uint8 RGB -> upsampled (paf, heatmap) at image res."""
        import cv2

        h, w = image.shape[:2]
        scale = self.box_size / max(h, 1)
        nh = int(round(h * scale)); nw = int(round(w * scale))
        nh8 = -(-nh // self.stride) * self.stride
        nw8 = -(-nw // self.stride) * self.stride
        resized = cv2.resize(image, (nw, nh), interpolation=cv2.INTER_CUBIC)
        padded = np.full((nh8, nw8, 3), 128, np.uint8)
        padded[:nh, :nw] = resized
        # CMU convention: BGR, [-0.5, 0.5]
        inp = padded[:, :, ::-1].astype(np.float32) / 256.0 - 0.5
        paf, heat = self._fwd(self.params, jnp.asarray(inp)[None])
        paf = np.asarray(paf)[0]
        heat = np.asarray(heat)[0]
        # upsample to the PADDED extent, crop the stride pad, THEN map to
        # image coordinates — resizing the padded maps straight to (w, h)
        # would shrink every joint toward the origin by nh/nh8
        paf = cv2.resize(paf, (nw8, nh8),
                         interpolation=cv2.INTER_CUBIC)[:nh, :nw]
        heat = cv2.resize(heat, (nw8, nh8),
                          interpolation=cv2.INTER_CUBIC)[:nh, :nw]
        paf = cv2.resize(paf, (w, h), interpolation=cv2.INTER_CUBIC)
        heat = cv2.resize(heat, (w, h), interpolation=cv2.INTER_CUBIC)
        return paf, heat

    def __call__(self, image: np.ndarray) -> np.ndarray:
        """uint8 RGB image -> uint8 RGB skeleton conditioning image."""
        paf, heat = self.maps(image)
        peaks = find_peaks(heat)
        conns = score_limbs(paf, peaks)
        people = assemble_people(peaks, conns)
        return draw_skeletons(image.shape[:2], peaks, people)
