"""Conditional diffusion UNet (Flax, NHWC) for SD 1.x / 2.x / SDXL families.

This is the hot-loop model of the whole framework — the denoise step the
reference runs inside ``pipeline(**kwargs)`` (swarm/diffusion/
diffusion_func.py:96) spends ~all its FLOPs here. TPU-first choices:

- NHWC layout throughout (XLA:TPU's native conv layout; channels ride the
  128-lane dimension).
- Attention runs through chiaswarm_tpu.ops.attention — spatial self-attention
  dispatches to the Pallas flash kernel on TPU, text cross-attention stays on
  the fused-einsum path (tiny KV).
- Fractional timesteps supported (Karras-sigma conditioning interpolates the
  timestep table — schedulers/common.py:sigma_to_timestep).
- No Python control flow on traced values; the module is shape-static and
  jits into one executable per (batch, resolution) bucket.

Covers: SD1.5 (head-count attention, conv projections), SD2.1 (head-dim 64,
linear projections, v-prediction handled by the scheduler), SDXL (mixed
transformer depth [1,2,10], dual-text conditioning + pooled/time-id
micro-conditioning embeddings).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from chiaswarm_tpu.models.configs import UNetConfig
from chiaswarm_tpu.models.common import num_groups as _num_groups
from chiaswarm_tpu.models.common import upsample2x_nearest
from chiaswarm_tpu.ops.attention import attention


def timestep_embedding(timesteps: jnp.ndarray, dim: int,
                       flip_sin_to_cos: bool = True,
                       freq_shift: float = 0.0,
                       max_period: float = 10000.0) -> jnp.ndarray:
    """Sinusoidal embedding, (B,) -> (B, dim). fp32 regardless of model dtype."""
    half = dim // 2
    freqs = jnp.exp(
        -jnp.log(max_period) * jnp.arange(half, dtype=jnp.float32)
        / (half - freq_shift)
    )
    args = timesteps.astype(jnp.float32)[:, None] * freqs[None, :]
    sin, cos = jnp.sin(args), jnp.cos(args)
    emb = jnp.concatenate([cos, sin], axis=-1) if flip_sin_to_cos else \
        jnp.concatenate([sin, cos], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


class TimestepEmbedding(nn.Module):
    """Two-layer MLP lifting the sinusoidal embedding to the block width.
    ``hidden_dim`` covers diffusers' ``out_dim`` variant (SVD's
    ``time_pos_embed``: C -> 4C -> C); None keeps both layers at
    ``out_dim``."""

    out_dim: int
    dtype: jnp.dtype = jnp.float32
    hidden_dim: int | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Dense(self.hidden_dim or self.out_dim, dtype=self.dtype,
                     name="linear_1")(x)
        x = nn.silu(x)
        return nn.Dense(self.out_dim, dtype=self.dtype, name="linear_2")(x)


class ResnetBlock(nn.Module):
    out_channels: int
    dtype: jnp.dtype = jnp.float32
    eps: float = 1e-5  # SVD's attention-level blocks ship 1e-6

    @nn.compact
    def __call__(self, x: jnp.ndarray, temb: jnp.ndarray) -> jnp.ndarray:
        h = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]), epsilon=self.eps, dtype=jnp.float32,
                         name="norm1")(x)
        h = nn.silu(h).astype(self.dtype)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    name="conv1")(h)
        t = nn.Dense(self.out_channels, dtype=self.dtype,
                     name="time_emb_proj")(nn.silu(temb))
        h = h + t[:, None, None, :]
        h = nn.GroupNorm(num_groups=_num_groups(h.shape[-1]), epsilon=self.eps, dtype=jnp.float32,
                         name="norm2")(h)
        h = nn.silu(h).astype(self.dtype)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                        name="conv_shortcut")(x)
        return x + h


class FeedForward(nn.Module):
    """GEGLU feed-forward (transformer MLP used by SD's attention blocks)."""

    dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        inner = self.dim * 4
        x = nn.Dense(inner * 2, dtype=self.dtype, name="proj_in")(x)
        x, gate = jnp.split(x, 2, axis=-1)
        # exact (erf) gelu — diffusers' GEGLU calls F.gelu without the
        # tanh approximation; matters for number-level checkpoint parity
        x = x * nn.gelu(gate, approximate=False)
        return nn.Dense(self.dim, dtype=self.dtype, name="proj_out")(x)


class CrossAttention(nn.Module):
    """Scaled dot-product attention with to_q/to_k/to_v/to_out heads.

    Context-batch contract: ``context.shape[0]`` must equal the query
    batch OR divide it, and in the divisible case query rows must be
    ordered context-major (row ``i`` attends to ``context[i // m]`` for
    ``m = b // bc`` — what ``(B, F, ...) -> (B*F, ...)`` folds and
    ``(b, s, f, c) -> (b*s, f, c)`` reshapes produce). CFG callers must
    still concatenate their negative/positive contexts to the full query
    batch themselves: a batch-1 context against a CFG pair would be
    silently broadcast to both halves, making guidance a no-op.
    """

    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray, context: jnp.ndarray | None) -> jnp.ndarray:
        context = x if context is None else context
        inner = self.num_heads * self.head_dim
        b, l, _ = x.shape
        bc, s = context.shape[:2]
        q = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_q")(x)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_k")(context)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_v")(context)
        if s == 1:
            # Softmax over a single key is identically 1, so the attended
            # value is the value row itself — independent of the queries.
            # out == to_out(v) broadcast over every query position (exact,
            # not an approximation; SVD's one-token CLIP-image context hits
            # this in every spatial and temporal cross-attention). q/k above
            # are kept so the param tree matches checkpoints; XLA removes
            # the dead computation. The context batch may be a divisor of
            # the query batch (an unbroadcast per-sample token): the result
            # broadcast replaces materializing the per-site context.
            out = nn.Dense(inner, dtype=self.dtype, name="to_out")(v)
            out = jnp.broadcast_to(out.reshape(bc, 1, 1, inner),
                                   (bc, b // bc, l, inner))
            return out.reshape(b, l, inner)
        if bc != b:
            # un-broadcast per-sample context on the general path too, so
            # callers never depend on which path runs: expand k/v after
            # projection (cheaper than materializing a per-site context)
            k = jnp.broadcast_to(k[:, None], (bc, b // bc, s, inner))
            v = jnp.broadcast_to(v[:, None], (bc, b // bc, s, inner))
            k = k.reshape(b, s, inner)
            v = v.reshape(b, s, inner)
        q = q.reshape(b, l, self.num_heads, self.head_dim)
        k = k.reshape(b, s, self.num_heads, self.head_dim)
        v = v.reshape(b, s, self.num_heads, self.head_dim)
        out = attention(q, k, v, impl=self.attn_impl).reshape(b, l, inner)
        return nn.Dense(inner, dtype=self.dtype, name="to_out")(out)


class TransformerBlock(nn.Module):
    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"
    has_cross_attn: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 context: jnp.ndarray | None) -> jnp.ndarray:
        # spatial self-attention (flash-kernel eligible)
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm1")(x).astype(self.dtype)
        x = x + CrossAttention(self.num_heads, self.head_dim, self.dtype,
                               self.attn_impl, name="attn1")(h, None)
        if self.has_cross_attn:
            # text cross-attention (small KV -> einsum path)
            h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm2")(x).astype(self.dtype)
            x = x + CrossAttention(self.num_heads, self.head_dim, self.dtype,
                                   "xla", name="attn2")(h, context)
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm3")(x).astype(self.dtype)
        return x + FeedForward(x.shape[-1], self.dtype, name="ff")(h)


class SpatialTransformer(nn.Module):
    """GroupNorm -> project -> depth x TransformerBlock -> project + residual."""

    depth: int
    num_heads: int
    head_dim: int
    use_linear_projection: bool
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"
    has_cross_attn: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 context: jnp.ndarray | None) -> jnp.ndarray:
        b, h, w, c = x.shape
        residual = x
        x = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]), epsilon=1e-6, dtype=jnp.float32,
                         name="norm")(x).astype(self.dtype)
        if self.use_linear_projection:
            x = x.reshape(b, h * w, c)
            x = nn.Dense(c, dtype=self.dtype, name="proj_in")(x)
        else:
            x = nn.Conv(c, (1, 1), dtype=self.dtype, name="proj_in")(x)
            x = x.reshape(b, h * w, c)
        for i in range(self.depth):
            x = TransformerBlock(self.num_heads, self.head_dim, self.dtype,
                                 self.attn_impl, self.has_cross_attn,
                                 name=f"transformer_blocks_{i}")(x, context)
        if self.use_linear_projection:
            x = nn.Dense(c, dtype=self.dtype, name="proj_out")(x)
            x = x.reshape(b, h, w, c)
        else:
            x = x.reshape(b, h, w, c)
            x = nn.Conv(c, (1, 1), dtype=self.dtype, name="proj_out")(x)
        return x + residual


class Downsample(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return nn.Conv(self.channels, (3, 3), strides=(2, 2), padding=1,
                       dtype=self.dtype, name="conv")(x)


class Upsample(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = upsample2x_nearest(x)
        return nn.Conv(self.channels, (3, 3), padding=1, dtype=self.dtype,
                       name="conv")(x)


def time_conditioning(cfg: UNetConfig, dtype: jnp.dtype,
                      timesteps: jnp.ndarray,
                      added_cond: dict[str, jnp.ndarray] | None,
                      class_labels: jnp.ndarray | None = None) -> jnp.ndarray:
    """Timestep (+ SDXL micro-conditioning, + class-label) embedding.
    Shared by the UNet and the ControlNet trunk — creates the
    ``time_embedding`` / ``add_embedding`` / ``class_embedding`` submodules
    in the CALLER's compact scope, so both models keep identical parameter
    paths for the checkpoint converter."""
    channels = list(cfg.block_out_channels)
    time_embed_dim = channels[0] * 4
    temb = timestep_embedding(timesteps, channels[0],
                              cfg.flip_sin_to_cos, cfg.freq_shift)
    temb = TimestepEmbedding(time_embed_dim, dtype=dtype,
                             name="time_embedding")(temb.astype(dtype))
    if cfg.num_class_embeds is not None:
        # noise-level conditioning (SD-x4-upscaler): a learned embedding
        # row per discrete level, added to the time embedding
        if class_labels is None:
            raise ValueError("this family requires class_labels "
                             "(e.g. the x4-upscaler noise level)")
        temb = temb + nn.Embed(cfg.num_class_embeds, time_embed_dim,
                               dtype=dtype, name="class_embedding")(
            class_labels.astype(jnp.int32))
    if cfg.class_proj_dim is not None:
        # FiLM conditioning on a continuous vector (AudioLDM conditions the
        # UNet on the L2-normalized CLAP text_embeds this way — diffusers'
        # class_embed_type="simple_projection"); class_labels is (B, D) float
        if class_labels is None:
            raise ValueError("this family requires float class_labels "
                             "(e.g. AudioLDM's projected text embedding)")
        class_emb = nn.Dense(time_embed_dim, dtype=dtype,
                             name="class_embedding")(
            class_labels.astype(dtype))
        if cfg.class_embeddings_concat:
            temb = jnp.concatenate([temb, class_emb], axis=-1)
        else:
            temb = temb + class_emb
    if cfg.addition_embed_dim is not None:
        if added_cond is None:
            raise ValueError("this family requires added_cond "
                             "(time_ids [+ text_embeds])")
        # SDXL: 6 time ids + pooled text; SVD-class video: 3 ids
        # (fps, motion bucket, noise-aug strength), no pooled branch
        time_ids = added_cond["time_ids"]          # (B, K)
        b = time_ids.shape[0]
        ids_emb = timestep_embedding(
            time_ids.reshape(-1), cfg.addition_embed_dim,
            cfg.flip_sin_to_cos, cfg.freq_shift,
        ).reshape(b, -1)
        if cfg.addition_pooled_dim is not None:
            text_embeds = added_cond["text_embeds"]  # (B, pooled_dim)
            add = jnp.concatenate(
                [text_embeds.astype(jnp.float32), ids_emb], axis=-1)
        else:
            add = ids_emb
        temb = temb + TimestepEmbedding(
            time_embed_dim, dtype=dtype, name="add_embedding"
        )(add.astype(dtype))
    return temb


def down_trunk(cfg: UNetConfig, dtype: jnp.dtype, x: jnp.ndarray,
               temb: jnp.ndarray, context: jnp.ndarray,
               ) -> tuple[jnp.ndarray, list[jnp.ndarray]]:
    """Down path from the post-conv_in activation: returns (x, skips).
    Shared verbatim by UNet and ControlNet (same submodule names)."""
    channels = list(cfg.block_out_channels)
    skips = [x]
    for level, ch in enumerate(channels):
        depth = cfg.transformer_depth[level]
        heads, head_dim = cfg.heads_for(ch, level)
        for j in range(cfg.layers_per_block):
            x = ResnetBlock(ch, dtype,
                            name=f"down_{level}_resnets_{j}")(x, temb)
            if depth > 0:
                x = SpatialTransformer(
                    depth, heads, head_dim, cfg.use_linear_projection,
                    dtype, cfg.attn_impl,
                    cfg.cross_attention_dim is not None,
                    name=f"down_{level}_attentions_{j}",
                )(x, context)
            skips.append(x)
        if level < len(channels) - 1:
            x = Downsample(ch, dtype, name=f"down_{level}_downsample")(x)
            skips.append(x)
    return x, skips


def mid_trunk(cfg: UNetConfig, dtype: jnp.dtype, x: jnp.ndarray,
              temb: jnp.ndarray, context: jnp.ndarray) -> jnp.ndarray:
    """Mid block (resnet -> transformer -> resnet), shared like down_trunk."""
    channels = list(cfg.block_out_channels)
    mid_ch = channels[-1]
    mid_heads, mid_head_dim = cfg.heads_for(mid_ch, len(channels) - 1)
    mid_depth = max(d for d in cfg.transformer_depth) or 1
    x = ResnetBlock(mid_ch, dtype, name="mid_resnets_0")(x, temb)
    x = SpatialTransformer(mid_depth, mid_heads, mid_head_dim,
                           cfg.use_linear_projection, dtype,
                           cfg.attn_impl,
                           cfg.cross_attention_dim is not None,
                           name="mid_attention")(x, context)
    return ResnetBlock(mid_ch, dtype, name="mid_resnets_1")(x, temb)


class UNet(nn.Module):
    """Returns the model prediction (epsilon/v per family) for NHWC latents.

    ``down_residuals``/``mid_residual`` inputs accept ControlNet residual
    injections (models/controlnet.py) — ``None`` for plain generation.

    DeepCache seam (ISSUE 12, Ma et al. 2023): adjacent denoise steps
    share slow-changing DEEP features, so the step-collapse subsystem
    (pipelines/diffusion.py) caches the up-path activation entering the
    shallowest (level 0) up block and replays it on designated steps:

    - ``return_deep=True`` runs the full network and ALSO returns that
      activation — the cache-refresh step. Static flag: the default
      trace is byte-identical to the pre-seam network.
    - ``cached_deep`` (the captured activation) runs the SHALLOW replay:
      conv_in + the level-0 down blocks recompute (they feed the level-0
      skip connections), every deeper level, the mid block, and the
      deeper up path are SKIPPED, and the cached activation splices in
      where the level-1 upsample output would arrive. For SDXL that
      skips the transformer-heavy levels entirely — the dominant cost
      of a denoise step.

    Both variants keep the exact submodule names of the full path, so
    one parameter tree serves all three traces.
    """

    config: UNetConfig

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(
        self,
        sample: jnp.ndarray,               # (B, H, W, C_latent)
        timesteps: jnp.ndarray,            # (B,) float32 (fractional ok)
        encoder_hidden_states: jnp.ndarray | None,  # (B, S, cross_dim);
        #   None for families without text cross-attention (AudioLDM)
        added_cond: dict[str, jnp.ndarray] | None = None,  # SDXL micro-cond
        down_residuals: tuple[jnp.ndarray, ...] | None = None,
        mid_residual: jnp.ndarray | None = None,
        # (B,) int noise level (x4-upscaler) or (B, class_proj_dim) float
        # FiLM vector (AudioLDM text_embeds)
        class_labels: jnp.ndarray | None = None,
        # DeepCache seam (static at trace time; see class docstring)
        cached_deep: jnp.ndarray | None = None,
        return_deep: bool = False,
    ) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
        cfg = self.config
        dtype = self.dtype
        channels = list(cfg.block_out_channels)
        if (cached_deep is not None or return_deep) and len(channels) < 2:
            raise ValueError(
                "DeepCache needs a deep/shallow split: this UNet has a "
                "single resolution level")
        if cached_deep is not None and return_deep:
            raise ValueError("shallow replay already carries the cache; "
                             "return_deep only applies to full passes")

        temb = time_conditioning(cfg, dtype, timesteps, added_cond,
                                 class_labels)
        context = (None if encoder_hidden_states is None
                   else encoder_hidden_states.astype(dtype))
        sample = sample.astype(dtype)

        x = nn.Conv(channels[0], (3, 3), padding=1, dtype=dtype,
                    name="conv_in")(sample)

        if cached_deep is not None:
            # ---- shallow replay: level-0 down blocks only (they feed
            # the level-0 skips), then the cached deep activation stands
            # in for the whole level>=1 + mid + deeper-up computation
            ch0 = channels[0]
            depth0 = cfg.transformer_depth[0]
            heads0, head_dim0 = cfg.heads_for(ch0, 0)
            skips = [x]
            for j in range(cfg.layers_per_block):
                x = ResnetBlock(ch0, dtype,
                                name=f"down_0_resnets_{j}")(x, temb)
                if depth0 > 0:
                    x = SpatialTransformer(
                        depth0, heads0, head_dim0,
                        cfg.use_linear_projection, dtype, cfg.attn_impl,
                        cfg.cross_attention_dim is not None,
                        name=f"down_0_attentions_{j}",
                    )(x, context)
                skips.append(x)
            if down_residuals is not None:
                # only the level-0 residuals have matching skips here
                skips = [s + r for s, r in zip(skips, down_residuals)]
            x = cached_deep.astype(dtype)
            up_levels: list[int] = [0]
        else:
            x, skips = down_trunk(cfg, dtype, x, temb, context)

            if down_residuals is not None:
                skips = [s + r for s, r in zip(skips, down_residuals)]

            x = mid_trunk(cfg, dtype, x, temb, context)
            if mid_residual is not None:
                x = x + mid_residual
            up_levels = list(range(len(channels) - 1, -1, -1))

        # ---- up path (mirrors down, consumes skips)
        deep = None
        for level in up_levels:
            ch = channels[level]
            depth = cfg.transformer_depth[level]
            heads, head_dim = cfg.heads_for(ch, level)
            if level == 0 and return_deep:
                # the activation the shallow replay will splice back in:
                # the level-1 upsample output entering the level-0 blocks
                deep = x
            for j in range(cfg.layers_per_block + 1):
                skip = skips.pop()
                x = jnp.concatenate([x, skip], axis=-1)
                x = ResnetBlock(ch, dtype,
                                name=f"up_{level}_resnets_{j}")(x, temb)
                if depth > 0:
                    x = SpatialTransformer(
                        depth, heads, head_dim, cfg.use_linear_projection,
                        dtype, cfg.attn_impl,
                        cfg.cross_attention_dim is not None,
                        name=f"up_{level}_attentions_{j}",
                    )(x, context)
            if level > 0:
                x = Upsample(ch, dtype, name=f"up_{level}_upsample")(x)

        x = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]), epsilon=1e-5, dtype=jnp.float32,
                         name="conv_norm_out")(x)
        x = nn.silu(x).astype(dtype)
        x = nn.Conv(cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32,
                    name="conv_out")(x)
        if return_deep:
            return x, deep
        return x
