"""HiFiGAN vocoder (Flax): mel spectrogram -> waveform.

The final stage of the txt2audio path (AudioLDM-class models, parity with
swarm/audio/audioldm.py:12-36 where the vocoder runs inside the diffusers
``AudioLDMPipeline``). Mirrors transformers' ``SpeechT5HifiGan``: conv_pre
-> N x (transposed-conv upsample + averaged multi-kernel dilated residual
blocks) -> conv_post -> tanh. Weight-norm is folded into plain kernels at
conversion time (convert/torch_to_flax.py), so inference is pure convs —
one fused XLA program, MXU-friendly.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class HifiGanConfig:
    model_in_dim: int = 64              # mel bins
    upsample_initial_channel: int = 512
    upsample_rates: tuple[int, ...] = (4, 4, 4, 4)
    upsample_kernel_sizes: tuple[int, ...] = (8, 8, 8, 8)
    resblock_kernel_sizes: tuple[int, ...] = (3, 7, 11)
    resblock_dilation_sizes: tuple[tuple[int, ...], ...] = (
        (1, 3, 5), (1, 3, 5), (1, 3, 5))
    sampling_rate: int = 16000
    leaky_relu_slope: float = 0.1
    dtype: str = "float32"

    @property
    def hop_length(self) -> int:
        hop = 1
        for r in self.upsample_rates:
            hop *= r
        return hop


class ResBlock(nn.Module):
    channels: int
    kernel_size: int
    dilations: tuple[int, ...]
    slope: float
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i, d in enumerate(self.dilations):
            h = nn.leaky_relu(x, self.slope)
            h = nn.Conv(self.channels, (self.kernel_size,),
                        kernel_dilation=(d,), padding="SAME",
                        dtype=self.dtype, name=f"convs1_{i}")(h)
            h = nn.leaky_relu(h, self.slope)
            h = nn.Conv(self.channels, (self.kernel_size,), padding="SAME",
                        dtype=self.dtype, name=f"convs2_{i}")(h)
            x = x + h
        return x


class HifiGan(nn.Module):
    """(B, T, mel_bins) -> (B, T * hop_length) float waveform in [-1, 1]."""

    config: HifiGanConfig

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(self, mel: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dtype = self.dtype
        x = nn.Conv(cfg.upsample_initial_channel, (7,), padding="SAME",
                    dtype=dtype, name="conv_pre")(mel.astype(dtype))
        for i, (rate, kernel) in enumerate(
                zip(cfg.upsample_rates, cfg.upsample_kernel_sizes)):
            ch = cfg.upsample_initial_channel // (2 ** (i + 1))
            x = nn.leaky_relu(x, cfg.leaky_relu_slope)
            x = nn.ConvTranspose(ch, (kernel,), strides=(rate,),
                                 padding="SAME", dtype=dtype,
                                 name=f"upsampler_{i}")(x)
            acc = None
            for j, (ks, dil) in enumerate(zip(cfg.resblock_kernel_sizes,
                                              cfg.resblock_dilation_sizes)):
                r = ResBlock(ch, ks, dil, cfg.leaky_relu_slope, dtype,
                             name=f"resblocks_{i}_{j}")(x)
                acc = r if acc is None else acc + r
            x = acc / len(cfg.resblock_kernel_sizes)
        x = nn.leaky_relu(x, cfg.leaky_relu_slope)
        x = nn.Conv(1, (7,), padding="SAME", dtype=dtype,
                    name="conv_post")(x)
        return jnp.tanh(x)[..., 0].astype(jnp.float32)
