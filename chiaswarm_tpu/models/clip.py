"""CLIP-style text encoder (Flax) — the conditioning tower for every SD family.

Replaces the torch CLIPTextModel the reference loads inside each diffusers
pipeline (swarm/diffusion/diffusion_func.py:41-46). Covers the three towers
used across SD1.x (ViT-L quick-gelu), SD2.x (ViT-H gelu, clip-skip), and
SDXL (ViT-L penultimate + OpenCLIP bigG with text projection & pooled
output) via :class:`TextEncoderConfig`.

TPU notes: pure encoder, static 77-token length, causal mask baked as a
constant — the whole prompt encode jits into a single fused program and is
negligible next to the denoise loop.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from chiaswarm_tpu.models.configs import TextEncoderConfig
from chiaswarm_tpu.ops.attention import attention


def _act(name: str):
    if name == "quick_gelu":
        return lambda x: x * nn.sigmoid(1.702 * x)
    if name == "gelu":
        # HF's ACT2FN["gelu"] is the EXACT erf GELU; flax's default is the
        # tanh approximation — close enough to hide in tiny tests, caught
        # by the full-config transformers parity suite
        return lambda x: nn.gelu(x, approximate=False)
    raise ValueError(f"unknown activation {name!r}")


class ClipAttention(nn.Module):
    config: TextEncoderConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        dense = lambda name: nn.Dense(cfg.hidden_size, dtype=self.dtype, name=name)
        b, l, _ = x.shape
        split = lambda t: t.reshape(b, l, cfg.num_heads, head_dim)
        q, k, v = split(dense("q_proj")(x)), split(dense("k_proj")(x)), split(dense("v_proj")(x))
        # causal mask via additive bias on the logits; sequence is a fixed 77
        # tokens so we fold the mask rather than calling the flash kernel.
        scale = head_dim ** -0.5
        logits = jnp.einsum("blhd,bshd->bhls", q, k,
                            preferred_element_type=jnp.float32) * scale
        logits = logits + mask
        weights = nn.softmax(logits, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhls,bshd->blhd", weights, v).reshape(b, l, -1)
        return dense("out_proj")(out)


class ClipLayer(nn.Module):
    config: TextEncoderConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        residual = x
        x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="layer_norm1")(x)
        x = ClipAttention(cfg, dtype=self.dtype, name="self_attn")(x, mask)
        x = residual + x
        residual = x
        x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="layer_norm2")(x)
        x = nn.Dense(cfg.intermediate_size, dtype=self.dtype, name="fc1")(x)
        x = _act(cfg.hidden_act)(x)
        x = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="fc2")(x)
        return residual + x


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    """CLIP vision tower (ViT-L/14 defaults — the safety checker's trunk).
    Field names match TextEncoderConfig so ClipLayer/ClipAttention reuse."""

    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    hidden_act: str = "quick_gelu"
    image_size: int = 224
    patch_size: int = 14
    projection_dim: int = 768


class ClipVisionEncoder(nn.Module):
    """(B, H, W, 3) preprocessed pixels -> (B, projection_dim) image embeds.

    The image tower of the NSFW safety checker (workloads/safety.py) —
    patch conv + CLS token + pre-LN ViT + post-LN CLS readout + visual
    projection, reusing the text encoder's transformer blocks.
    """

    config: VisionConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixel_values: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        b = pixel_values.shape[0]
        patches = nn.Conv(
            cfg.hidden_size, (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size), use_bias=False,
            dtype=self.dtype, name="patch_embedding",
        )(pixel_values.astype(self.dtype))
        patches = patches.reshape(b, -1, cfg.hidden_size)
        cls = self.param("class_embedding",
                         nn.initializers.normal(0.02),
                         (cfg.hidden_size,))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, cfg.hidden_size)), patches], axis=1)
        n_pos = (cfg.image_size // cfg.patch_size) ** 2 + 1
        pos = nn.Embed(n_pos, cfg.hidden_size, dtype=self.dtype,
                       name="position_embedding")(jnp.arange(x.shape[1]))
        x = x + pos[None]
        x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="pre_layrnorm")(x)
        mask = jnp.zeros((1, 1, x.shape[1], x.shape[1]), jnp.float32)
        for i in range(cfg.num_layers):
            x = ClipLayer(cfg, dtype=self.dtype, name=f"layers_{i}")(x, mask)
        pooled = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype,
                              name="post_layernorm")(x[:, 0])
        return nn.Dense(cfg.projection_dim, use_bias=False,
                        dtype=self.dtype, name="visual_projection")(pooled)


class ClipTextEncoder(nn.Module):
    """Returns (sequence_embeddings, pooled_embedding).

    ``sequence_embeddings`` honors ``config.output_layer`` (clip-skip) and
    ``config.final_layer_norm``; ``pooled_embedding`` is the EOS-token state
    of the *final* layer after the final LayerNorm, passed through the text
    projection when ``projection_dim`` is set (the SDXL pooled conditioning).
    """

    config: TextEncoderConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        cfg = self.config
        b, l = input_ids.shape
        tok = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=self.dtype,
                       name="token_embedding")(input_ids)
        pos = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                       dtype=self.dtype, name="position_embedding")(
            jnp.arange(l)[None, :].repeat(b, axis=0)
        )
        x = tok + pos

        causal = jnp.triu(jnp.full((l, l), -1e9, dtype=jnp.float32), k=1)
        mask = causal[None, None, :, :]

        hidden_states = []
        for i in range(cfg.num_layers):
            hidden_states.append(x)
            x = ClipLayer(cfg, dtype=self.dtype, name=f"layers_{i}")(x, mask)
        hidden_states.append(x)  # index -1 == final layer output

        # Single LN module reused on different inputs (shared params): the
        # pooled path always reads the final-LN state even when the sequence
        # readout skips it (OpenCLIP bigG / SDXL penultimate readout).
        final_ln = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype,
                                name="final_layer_norm")
        final = final_ln(x)

        readout = x if cfg.output_layer == -1 else hidden_states[cfg.output_layer]
        seq = final_ln(readout) if cfg.final_layer_norm else readout

        # pooled = final-LN state at the EOS position (highest token id ==
        # eos for CLIP's vocab ordering; we use argmax like HF does)
        eos_idx = jnp.argmax((input_ids == cfg.eos_token_id).astype(jnp.int32), axis=-1)
        pooled = jnp.take_along_axis(
            final, eos_idx[:, None, None].repeat(final.shape[-1], axis=-1), axis=1
        )[:, 0, :]
        if cfg.projection_dim is not None:
            pooled = nn.Dense(cfg.projection_dim, use_bias=False,
                              dtype=self.dtype, name="text_projection")(pooled)
        return seq, pooled
