"""DPT monocular depth estimation — the learned depth/normal preprocessor.

The reference's depth ControlNet mode runs the transformers
depth-estimation pipeline (swarm/controlnet/input_processor.py:87-93,
Intel/dpt-*); this is the same DPT architecture natively: a plain ViT
backbone tapped at four layers, the reassemble stage (readout-projected
tokens -> image-like maps at 4 scales), the feature-fusion decoder
(pre-activation residual units, align-corners-true x2 upsampling), and
the 3-conv depth head. Weights convert 1:1 from the HF
``DPTForDepthEstimation`` state dict (convert/torch_to_flax.py::
convert_dpt), fidelity-tested against torch.

TPU notes: one fixed square canvas (the checkpoint's ViT grid) keeps a
single compiled program for every request size; the align-corners
bilinear x2 upsamples are einsum contractions with constant weight
matrices (MXU work, no gathers).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DPTConfig:
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    image_size: int = 384
    patch_size: int = 16
    backbone_out_indices: Sequence[int] = (5, 11, 17, 23)
    neck_hidden_sizes: Sequence[int] = (256, 512, 1024, 1024)
    reassemble_factors: Sequence[float] = (4, 2, 1, 0.5)
    fusion_hidden_size: int = 256
    qkv_bias: bool = True
    layer_norm_eps: float = 1e-12
    dtype: str = "float32"


# Intel/dpt-large
DPT_LARGE = DPTConfig()

DPT_TINY = DPTConfig(hidden_size=32, intermediate_size=64, num_layers=4,
                     num_heads=4, image_size=32, patch_size=8,
                     backbone_out_indices=(0, 1, 2, 3),
                     neck_hidden_sizes=(16, 16, 24, 24),
                     fusion_hidden_size=16)

DPT_CONFIGS = {"dpt_large": DPT_LARGE, "dpt_tiny": DPT_TINY}


def _upsample_matrix(n_in: int, n_out: int) -> np.ndarray:
    """(n_out, n_in) align_corners=True bilinear interpolation weights."""
    w = np.zeros((n_out, n_in), np.float32)
    if n_in == 1:
        w[:, 0] = 1.0
        return w
    pos = np.arange(n_out) * (n_in - 1) / max(n_out - 1, 1)
    lo = np.floor(pos).astype(np.int64).clip(0, n_in - 1)
    hi = np.minimum(lo + 1, n_in - 1)
    frac = (pos - lo).astype(np.float32)
    w[np.arange(n_out), lo] += 1.0 - frac
    w[np.arange(n_out), hi] += frac
    return w


def _upsample2x(x: jnp.ndarray) -> jnp.ndarray:
    """(B, H, W, C) -> (B, 2H, 2W, C), bilinear align_corners=True (the
    torch ``interpolate(scale_factor=2, align_corners=True)`` the DPT
    decoder uses)."""
    b, h, w, c = x.shape
    wh = jnp.asarray(_upsample_matrix(h, 2 * h))
    ww = jnp.asarray(_upsample_matrix(w, 2 * w))
    x = jnp.einsum("oh,bhwc->bowc", wh, x)
    return jnp.einsum("pw,bowc->bopc", ww, x)


class DPTViTLayer(nn.Module):
    config: DPTConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        b, l, _ = x.shape
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="layernorm_before")(x).astype(self.dtype)
        dense = lambda name: nn.Dense(cfg.hidden_size,
                                      use_bias=cfg.qkv_bias,
                                      dtype=self.dtype, name=name)
        split = lambda t: t.reshape(b, l, cfg.num_heads, head_dim)
        q = split(dense("query")(h))
        k = split(dense("key")(h))
        v = split(dense("value")(h))
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores * (head_dim ** -0.5)
        weights = nn.softmax(scores, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v).reshape(b, l, -1)
        x = x + nn.Dense(cfg.hidden_size, dtype=self.dtype,
                         name="attn_out")(out)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="layernorm_after")(x).astype(self.dtype)
        h = nn.Dense(cfg.intermediate_size, dtype=self.dtype,
                     name="intermediate")(h)
        h = nn.gelu(h, approximate=False)
        return x + nn.Dense(cfg.hidden_size, dtype=self.dtype,
                            name="output")(h)


class DPTDepth(nn.Module):
    """(B, S, S, 3) normalized pixels (S = config.image_size) ->
    (B, 16*S/patch, 16*S/patch) relative inverse depth — the fusion
    decoder upsamples x2 per stage from the ViT grid (S/patch) and the
    head adds one more, so patch 16 checkpoints return (B, S, S)."""

    config: DPTConfig

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(self, pixel_values: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dtype = self.dtype
        b = pixel_values.shape[0]
        grid = cfg.image_size // cfg.patch_size

        # ---- ViT backbone, tapped at 4 layers
        patches = nn.Conv(cfg.hidden_size,
                          (cfg.patch_size, cfg.patch_size),
                          strides=(cfg.patch_size, cfg.patch_size),
                          dtype=dtype, name="patch_embedding",
                          )(pixel_values.astype(dtype))
        patches = patches.reshape(b, -1, cfg.hidden_size)
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, cfg.hidden_size))
        pos = self.param("position_embeddings", nn.initializers.zeros,
                         (grid * grid + 1, cfg.hidden_size))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(dtype), (b, 1, cfg.hidden_size)),
             patches], axis=1)
        x = x + pos[None].astype(dtype)

        taps = []
        want = set(cfg.backbone_out_indices)
        for i in range(cfg.num_layers):
            x = DPTViTLayer(cfg, dtype, name=f"layer_{i}")(x)
            if i in want:
                taps.append(x)

        # ---- reassemble: tokens -> 4 image-like maps
        maps = []
        for i, state in enumerate(taps):
            cls_tok, tokens = state[:, :1], state[:, 1:]
            readout = jnp.concatenate(
                [tokens, jnp.broadcast_to(cls_tok, tokens.shape)], axis=-1)
            tokens = nn.gelu(
                nn.Dense(cfg.hidden_size, dtype=dtype,
                         name=f"readout_{i}")(readout), approximate=False)
            m = tokens.reshape(b, grid, grid, cfg.hidden_size)
            m = nn.Conv(cfg.neck_hidden_sizes[i], (1, 1), dtype=dtype,
                        name=f"reassemble_proj_{i}")(m)
            factor = cfg.reassemble_factors[i]
            if factor > 1:
                f = int(factor)
                m = nn.ConvTranspose(cfg.neck_hidden_sizes[i], (f, f),
                                     strides=(f, f), padding="VALID",
                                     dtype=dtype,
                                     name=f"reassemble_resize_{i}")(m)
            elif factor < 1:
                m = nn.Conv(cfg.neck_hidden_sizes[i], (3, 3),
                            strides=(2, 2), padding=1, dtype=dtype,
                            name=f"reassemble_resize_{i}")(m)
            m = nn.Conv(cfg.fusion_hidden_size, (3, 3), padding=1,
                        use_bias=False, dtype=dtype,
                        name=f"neck_conv_{i}")(m)
            maps.append(m)

        # ---- fusion decoder (coarsest first)
        def residual_unit(m, name):
            h = nn.relu(m)
            h = nn.Conv(cfg.fusion_hidden_size, (3, 3), padding=1,
                        dtype=dtype, name=f"{name}_conv1")(h)
            h = nn.relu(h)
            h = nn.Conv(cfg.fusion_hidden_size, (3, 3), padding=1,
                        dtype=dtype, name=f"{name}_conv2")(h)
            return m + h

        fused = None
        for j, m in enumerate(reversed(maps)):
            name = f"fusion_{j}"
            if fused is None:
                fused = m
            else:
                fused = fused + residual_unit(m, f"{name}_res1")
            fused = residual_unit(fused, f"{name}_res2")
            fused = _upsample2x(fused)
            fused = nn.Conv(cfg.fusion_hidden_size, (1, 1), dtype=dtype,
                            name=f"{name}_proj")(fused)

        # ---- depth head
        h = nn.Conv(cfg.fusion_hidden_size // 2, (3, 3), padding=1,
                    dtype=dtype, name="head_conv1")(fused)
        h = _upsample2x(h)
        h = nn.relu(nn.Conv(32, (3, 3), padding=1, dtype=dtype,
                            name="head_conv2")(h))
        h = nn.relu(nn.Conv(1, (1, 1), dtype=jnp.float32,
                            name="head_conv3")(h))
        return h[..., 0]


@dataclasses.dataclass
class DPTDetector:
    """Host wrapper: resize/normalize to the fixed canvas, run the jitted
    model, min-max scale the inverse depth to a uint8 map (the depth
    conditioning format)."""

    params: dict
    config: DPTConfig = DPT_LARGE

    def __post_init__(self) -> None:
        self._net = DPTDepth(self.config)
        self._fwd = jax.jit(lambda p, x: self._net.apply(p, x))

    @classmethod
    def random(cls, seed: int = 0,
               config: DPTConfig = DPT_TINY) -> "DPTDetector":
        net = DPTDepth(config)
        x = jnp.zeros((1, config.image_size, config.image_size, 3),
                      jnp.float32)
        return cls(params=jax.jit(net.init)(jax.random.PRNGKey(seed), x),
                   config=config)

    @classmethod
    def from_checkpoint(cls, path,
                        config: DPTConfig = DPT_LARGE) -> "DPTDetector":
        from chiaswarm_tpu.convert.torch_to_flax import (
            convert_dpt,
            read_torch_weights,
        )

        return cls(params=convert_dpt(read_torch_weights(path)),
                   config=config)

    def depth(self, image: np.ndarray) -> np.ndarray:
        """uint8 RGB (H, W, 3) -> float32 relative inverse depth (H, W),
        larger = nearer."""
        import cv2

        h, w = image.shape[:2]
        s = self.config.image_size
        resized = cv2.resize(image, (s, s), interpolation=cv2.INTER_CUBIC)
        arr = resized.astype(np.float32) / 255.0
        arr = (arr - 0.5) / 0.5  # DPT image processor: mean .5, std .5
        out = np.asarray(self._fwd(self.params, jnp.asarray(arr)[None]))[0]
        return cv2.resize(out, (w, h), interpolation=cv2.INTER_CUBIC)

    def __call__(self, image: np.ndarray) -> np.ndarray:
        """uint8 RGB -> uint8 single-channel depth conditioning map."""
        d = self.depth(image)
        lo, hi = float(d.min()), float(d.max())
        return ((d - lo) / max(hi - lo, 1e-6) * 255.0).astype(np.uint8)
