"""Temporal (video) diffusion UNets — faithful to the two published layouts.

The reference's txt2vid workload runs ``damo-vilab/text-to-video-ms-1.7b``
through diffusers' ``UNet3DConditionModel`` (swarm/video/tx2vid.py:24-27);
BASELINE config #5 names the SVD class, diffusers'
``UNetSpatioTemporalConditionModel``. Earlier rounds served a generic
factorized space-time UNet; VERDICT r4 #1: real snapshots' trained temporal
weights could not be converted onto it. This module now mirrors the two
published module graphs exactly — every torch parameter has a
corresponding leaf here (convert/torch_to_flax.py maps them 1:1, and
pipelines/video.py refuses to synthesize leaves for these families).

:class:`UNet3D` (ModelScope text-to-video layout):
- ``conv_in`` -> ``transformer_in`` (a temporal transformer at full res,
  8 heads) -> down blocks of [resnet, temp_conv, spatial attn, temp attn]
  -> mid -> up -> ``conv_out``.
- ``TemporalConvLayer``: four GroupNorm+SiLU+Conv(3,1,1) stages with a
  residual add; the published init zeroes the fourth conv.
- ``TemporalTransformer``: GroupNorm -> linear proj -> ONE basic block
  whose attn1 AND attn2 are both frame-axis self-attention (diffusers'
  ``double_self_attention=True``) -> linear proj + residual. No frame
  positional embedding — the published layout has none.

:class:`UNetSpatioTemporal` (SVD image-to-video layout):
- every resnet slot is a :class:`SpatioTemporalResBlock` — a spatial
  ResnetBlock, a :class:`TemporalResnetBlock` (frame-axis convs, per-frame
  time embedding), and a learned sigmoid blend (``mix_factor``, the
  AlphaBlender with ``switch_spatial_to_temporal_mix``);
- every attention slot is a :class:`TransformerSpatioTemporal` — a spatial
  transformer block, a sinusoidal frame-position embedding
  (``time_pos_embed``), a :class:`TemporalBasicBlock` (ff_in -> frame
  self-attn -> cross-attn to the conditioning token -> ff) and a second
  learned blend, inside one linear proj_in/proj_out pair.

TPU notes: frame folding is pure reshape in NHWC — XLA sees large static
(B*F, H, W, C) convs for the MXU and (B*H*W, F, C) attention batches; the
frame-axis convs are (3, 1, 1) kernels on the 5-D tensor (one conv op, no
gather). Frame count is a compile-time static (bucketed by the pipeline).
Serving always runs with diffusers' ``image_only_indicator`` at zero, so
the blend weights reduce to ``sigmoid(mix_factor)`` — constants under jit.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from chiaswarm_tpu.models.common import num_groups as _num_groups
from chiaswarm_tpu.models.configs import UNetConfig
from chiaswarm_tpu.models.unet import (
    CrossAttention,
    Downsample,
    FeedForward,
    ResnetBlock,
    SpatialTransformer,
    TimestepEmbedding,
    TransformerBlock,
    Upsample,
    time_conditioning,
    timestep_embedding,
)

zeros_init = nn.initializers.zeros


def _fold(x: jnp.ndarray) -> jnp.ndarray:
    """(B, F, H, W, C) -> (B*F, H, W, C) for the shared 2D spatial blocks."""
    return x.reshape((-1,) + x.shape[2:])


def _unfold(x: jnp.ndarray, b: int, f: int) -> jnp.ndarray:
    return x.reshape((b, f) + x.shape[1:])


# --------------------------------------------------- ModelScope modules


class TemporalConvLayer(nn.Module):
    """diffusers ``TemporalConvLayer``: four (GroupNorm, SiLU, Conv3d
    (3,1,1)) stages with a residual add; the published init zeroes conv4
    so an untrained layer is identity. GroupNorm statistics run over
    (F, H, W) per channel group — the torch layout applies it to the
    (B, C, F, H, W) tensor — which the 5-D NHWC GroupNorm matches."""

    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:  # (B, F, H, W, C)
        identity = x
        h = x
        for k in (1, 2, 3, 4):
            h = nn.GroupNorm(num_groups=_num_groups(h.shape[-1]),
                             epsilon=1e-5, dtype=jnp.float32,
                             name=f"norm{k}")(h)
            h = nn.silu(h).astype(self.dtype)
            h = nn.Conv(self.channels, (3, 1, 1),
                        padding=((1, 1), (0, 0), (0, 0)),
                        kernel_init=zeros_init if k == 4
                        else nn.initializers.lecun_normal(),
                        dtype=self.dtype, name=f"conv{k}")(h)
        return identity + h


class TemporalTransformer(nn.Module):
    """diffusers ``TransformerTemporalModel`` with its default
    ``double_self_attention=True``: frames are the sequence axis, spatial
    sites fold into batch; attn1 and attn2 are BOTH self-attention (the
    constructor's cross_attention_dim is discarded in this mode). No
    positional embedding — the published layout relies on the temporal
    convs for order information."""

    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:  # (B, F, H, W, C)
        b, f, hh, ww, c = x.shape
        residual = x
        h = nn.GroupNorm(num_groups=_num_groups(c), epsilon=1e-6,
                         dtype=jnp.float32, name="norm")(x)
        h = h.transpose(0, 2, 3, 1, 4).reshape(b * hh * ww, f, c)
        h = h.astype(self.dtype)
        inner = self.num_heads * self.head_dim
        h = nn.Dense(inner, dtype=self.dtype, name="proj_in")(h)
        # ONE basic block (num_layers=1 in both the transformer_in and the
        # per-level temp_attentions of the published config); attn2 runs
        # self-attention because context=None falls back to h
        h = TransformerBlock(self.num_heads, self.head_dim, self.dtype,
                             "xla", has_cross_attn=True,
                             name="transformer_blocks_0")(h, None)
        h = nn.Dense(c, dtype=self.dtype, name="proj_out")(h)
        h = h.reshape(b, hh, ww, f, c).transpose(0, 3, 1, 2, 4)
        return residual + h


class UNet3D(nn.Module):
    """ModelScope-class text-to-video UNet (diffusers
    ``UNet3DConditionModel``): (B, F, H, W, C) latents -> model prediction.

    Block order per down layer: resnet -> temp_conv -> spatial attention
    -> temporal attention (CrossAttnDownBlock3D); the attention-free last
    level runs resnet -> temp_conv only (DownBlock3D). ``transformer_in``
    (8 heads at the stem width) runs right after conv_in. The spatial
    modules are models/unet.py's own (same parameter names, so the 2D
    converter rules apply to them verbatim)."""

    config: UNetConfig

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(
        self,
        sample: jnp.ndarray,                 # (B, F, H, W, C)
        timesteps: jnp.ndarray,              # (B,)
        encoder_hidden_states: jnp.ndarray,  # (B, S, cross_dim)
        added_cond: dict[str, jnp.ndarray] | None = None,
    ) -> jnp.ndarray:
        cfg = self.config
        dtype = self.dtype
        channels = list(cfg.block_out_channels)
        b, f, hh, ww, _ = sample.shape

        temb = time_conditioning(cfg, dtype, timesteps, added_cond)
        temb_f = jnp.repeat(temb, f, axis=0)          # (B*F, D)
        # spatial-attention queries are (B*F, S, C) b-major: the text
        # context rides CrossAttention's divisible-batch expansion
        # un-broadcast (k/v projected once per sample, not per frame)
        ctx_f = encoder_hidden_states.astype(dtype)

        x = nn.Conv(channels[0], (3, 3), padding=1, dtype=dtype,
                    name="conv_in")(_fold(sample.astype(dtype)))
        x = _unfold(x, b, f)
        # full-resolution temporal transformer at the stem width: the
        # published layout fixes 8 heads here (not channels/head_dim)
        head_dim0 = cfg.heads_for(channels[0], 0)[1]
        x = TemporalTransformer(8, head_dim0, dtype,
                                name="transformer_in")(x)
        skips = [x]

        # ---- down path
        for level, ch in enumerate(channels):
            depth = cfg.transformer_depth[level]
            heads, head_dim = cfg.heads_for(ch, level)
            for j in range(cfg.layers_per_block):
                x = _unfold(ResnetBlock(ch, dtype,
                                        name=f"down_{level}_resnets_{j}")(
                    _fold(x), temb_f), b, f)
                x = TemporalConvLayer(ch, dtype,
                                      name=f"down_{level}_tconvs_{j}")(x)
                if depth > 0:
                    x = _unfold(SpatialTransformer(
                        depth, heads, head_dim, cfg.use_linear_projection,
                        dtype, cfg.attn_impl,
                        name=f"down_{level}_attentions_{j}")(
                        _fold(x), ctx_f), b, f)
                    x = TemporalTransformer(
                        heads, head_dim, dtype,
                        name=f"down_{level}_tattns_{j}")(x)
                skips.append(x)
            if level < len(channels) - 1:
                x = _unfold(Downsample(ch, dtype,
                                       name=f"down_{level}_downsample")(
                    _fold(x)), b, f)
                skips.append(x)

        # ---- mid (UNetMidBlock3DCrossAttn, num_layers=1):
        # resnet, temp_conv, attn, temp_attn, resnet, temp_conv
        mid_ch = channels[-1]
        mid_heads, mid_head_dim = cfg.heads_for(mid_ch, len(channels) - 1)
        mid_depth = max(d for d in cfg.transformer_depth) or 1
        x = _unfold(ResnetBlock(mid_ch, dtype, name="mid_resnets_0")(
            _fold(x), temb_f), b, f)
        x = TemporalConvLayer(mid_ch, dtype, name="mid_tconvs_0")(x)
        x = _unfold(SpatialTransformer(
            mid_depth, mid_heads, mid_head_dim, cfg.use_linear_projection,
            dtype, cfg.attn_impl, name="mid_attention")(
            _fold(x), ctx_f), b, f)
        x = TemporalTransformer(mid_heads, mid_head_dim, dtype,
                                name="mid_tattn")(x)
        x = _unfold(ResnetBlock(mid_ch, dtype, name="mid_resnets_1")(
            _fold(x), temb_f), b, f)
        x = TemporalConvLayer(mid_ch, dtype, name="mid_tconvs_1")(x)

        # ---- up path
        for rev, ch in enumerate(reversed(channels)):
            level = len(channels) - 1 - rev
            depth = cfg.transformer_depth[level]
            heads, head_dim = cfg.heads_for(ch, level)
            for j in range(cfg.layers_per_block + 1):
                skip = skips.pop()
                x = jnp.concatenate([x, skip], axis=-1)
                x = _unfold(ResnetBlock(ch, dtype,
                                        name=f"up_{level}_resnets_{j}")(
                    _fold(x), temb_f), b, f)
                x = TemporalConvLayer(ch, dtype,
                                      name=f"up_{level}_tconvs_{j}")(x)
                if depth > 0:
                    x = _unfold(SpatialTransformer(
                        depth, heads, head_dim, cfg.use_linear_projection,
                        dtype, cfg.attn_impl,
                        name=f"up_{level}_attentions_{j}")(
                        _fold(x), ctx_f), b, f)
                    x = TemporalTransformer(
                        heads, head_dim, dtype,
                        name=f"up_{level}_tattns_{j}")(x)
            if level > 0:
                x = _unfold(Upsample(ch, dtype,
                                     name=f"up_{level}_upsample")(
                    _fold(x)), b, f)

        x = _fold(x)
        x = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]), epsilon=1e-5,
                         dtype=jnp.float32, name="conv_norm_out")(x)
        x = nn.silu(x).astype(dtype)
        x = nn.Conv(cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32,
                    name="conv_out")(x)
        return _unfold(x, b, f)


# ---------------------------------------------------------- SVD modules


class TemporalResnetBlock(nn.Module):
    """diffusers ``TemporalResnetBlock``: the frame-axis twin of a spatial
    resnet — (3,1,1) convs, a per-frame time-embedding projection
    (``temb_bf=None`` skips it — the temporal VAE decoder's temb-free
    variant). The SVD layouts always keep in_channels == out_channels
    here (no shortcut)."""

    out_channels: int
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 temb_bf: jnp.ndarray | None = None) -> jnp.ndarray:
        # x (B, F, H, W, C); temb_bf (B, F, D)
        h = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]),
                         epsilon=self.eps, dtype=jnp.float32,
                         name="norm1")(x)
        h = nn.silu(h).astype(self.dtype)
        h = nn.Conv(self.out_channels, (3, 1, 1),
                    padding=((1, 1), (0, 0), (0, 0)), dtype=self.dtype,
                    name="conv1")(h)
        if temb_bf is not None:
            t = nn.Dense(self.out_channels, dtype=self.dtype,
                         name="time_emb_proj")(nn.silu(temb_bf))
            h = h + t[:, :, None, None, :].astype(h.dtype)
        h = nn.GroupNorm(num_groups=_num_groups(h.shape[-1]),
                         epsilon=self.eps, dtype=jnp.float32,
                         name="norm2")(h)
        h = nn.silu(h).astype(self.dtype)
        h = nn.Conv(self.out_channels, (3, 1, 1),
                    padding=((1, 1), (0, 0), (0, 0)), dtype=self.dtype,
                    name="conv2")(h)
        return x + h


class SpatioTemporalResBlock(nn.Module):
    """diffusers ``SpatioTemporalResBlock``: spatial resnet -> temporal
    resnet -> learned blend. Serving runs diffusers'
    ``image_only_indicator`` at zero, so the AlphaBlender reduces to
    out = a*spatial + (1-a)*temporal with a = sigmoid(mix_factor) — the
    non-switched direction the SVD UNet blocks use
    (``switch_spatial_to_temporal_mix`` is enabled only in the temporal
    VAE decoder, where ``switch_mix`` flips the blend)."""

    out_channels: int
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32
    switch_mix: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, temb_f: jnp.ndarray,
                 temb_bf: jnp.ndarray) -> jnp.ndarray:
        b, f = x.shape[:2]
        s = ResnetBlock(self.out_channels, self.dtype, eps=self.eps,
                        name="spatial")(_fold(x), temb_f)
        s = _unfold(s, b, f)
        t = TemporalResnetBlock(self.out_channels, self.eps, self.dtype,
                                name="temporal")(s, temb_bf)
        a = nn.sigmoid(self.param("mix_factor",
                                  nn.initializers.constant(0.5), (1,)))
        a = a.astype(s.dtype)
        if self.switch_mix:
            a = 1.0 - a
        return a * s + (1.0 - a) * t


class TemporalBasicBlock(nn.Module):
    """diffusers ``TemporalBasicTransformerBlock``: norm_in+ff_in (with
    residual), frame-axis self-attention, cross-attention to the
    first-frame conditioning token, feed-forward. Operates on the
    (B*S, F, C) frame-major layout."""

    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, time_ctx: jnp.ndarray,
                 b: int, f: int) -> jnp.ndarray:
        # x (B*F, S, C); time_ctx (B, S_ctx, ctx_dim)
        bf, s, c = x.shape
        h = x.reshape(b, f, s, c).transpose(0, 2, 1, 3).reshape(b * s, f, c)
        residual = h
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                         name="norm_in")(h).astype(self.dtype)
        h = FeedForward(c, self.dtype, name="ff_in")(h) + residual
        a = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                         name="norm1")(h).astype(self.dtype)
        h = CrossAttention(self.num_heads, self.head_dim, self.dtype,
                           "xla", name="attn1")(a, None) + h
        # every spatial site cross-attends to the (first-frame) context,
        # passed un-broadcast: CrossAttention's divisible-batch support
        # expands k/v after projection, so the per-site context copy
        # (the largest tensor in the block — b*s ~ 9k sites at SVD's
        # portrait shape) is never materialized
        ctx = time_ctx.astype(self.dtype)
        a = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                         name="norm2")(h).astype(self.dtype)
        h = CrossAttention(self.num_heads, self.head_dim, self.dtype,
                           "xla", name="attn2")(a, ctx) + h
        a = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                         name="norm3")(h).astype(self.dtype)
        h = FeedForward(c, self.dtype, name="ff")(a) + h
        return h.reshape(b, s, f, c).transpose(0, 2, 1, 3).reshape(bf, s, c)


class TransformerSpatioTemporal(nn.Module):
    """diffusers ``TransformerSpatioTemporalModel``: per depth step, a
    spatial transformer block and a temporal one run on the same tokens
    (the temporal one seeded with a sinusoidal frame-position embedding
    through ``time_pos_embed``), blended by a learned sigmoid factor —
    all inside one GroupNorm + linear proj_in/proj_out pair. GroupNorm
    statistics are per frame (the torch layout normalizes the folded
    (B*F, C, H, W) tensor)."""

    depth: int
    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray, ctx: jnp.ndarray) -> jnp.ndarray:
        # x (B, F, H, W, C); ctx (B, S, cross_dim)
        b, f, hh, ww, c = x.shape
        residual = x
        h = nn.GroupNorm(num_groups=_num_groups(c), epsilon=1e-6,
                         dtype=jnp.float32, name="norm")(_fold(x))
        seq = h.reshape(b * f, hh * ww, c).astype(self.dtype)
        inner = self.num_heads * self.head_dim
        seq = nn.Dense(inner, dtype=self.dtype, name="proj_in")(seq)

        # the spatial blocks' queries are (B*F, S, C) in b-major order, so
        # the context rides CrossAttention's divisible-batch expansion
        # un-broadcast (no f-fold copy, k/v projected once per sample)
        ctx_f = ctx.astype(self.dtype)
        # sinusoidal frame ids -> MLP (in C, hidden 4C, out C)
        femb = timestep_embedding(jnp.arange(f, dtype=jnp.float32), c)
        femb = TimestepEmbedding(c, self.dtype, hidden_dim=c * 4,
                                 name="time_pos_embed")(
            femb.astype(self.dtype))
        femb = jnp.tile(femb, (b, 1))[:, None, :]     # (B*F, 1, C)

        mix = nn.sigmoid(self.param("mix_factor",
                                    nn.initializers.constant(0.5), (1,)))
        mix = mix.astype(self.dtype)
        for i in range(self.depth):
            s = TransformerBlock(self.num_heads, self.head_dim, self.dtype,
                                 self.attn_impl, has_cross_attn=True,
                                 name=f"transformer_blocks_{i}")(seq, ctx_f)
            t = TemporalBasicBlock(self.num_heads, self.head_dim,
                                   self.dtype,
                                   name=f"temporal_blocks_{i}")(
                s + femb, ctx, b, f)
            seq = mix * s + (1.0 - mix) * t
        seq = nn.Dense(c, dtype=self.dtype, name="proj_out")(seq)
        return residual + seq.reshape(b, f, hh, ww, c)


class UNetSpatioTemporal(nn.Module):
    """SVD-class image-to-video UNet (diffusers
    ``UNetSpatioTemporalConditionModel``): (B, F, H, W, 8) noise++cond
    latents -> prediction, conditioned on a single CLIP-image token and
    the (fps, motion bucket, noise-aug) micro-conditioning ids through
    ``add_embedding``. Published quirk kept for checkpoint fidelity: the
    resnets of attention-bearing levels use GroupNorm eps 1e-6, the
    attention-free level and the mid block 1e-5."""

    config: UNetConfig

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(
        self,
        sample: jnp.ndarray,                 # (B, F, H, W, C)
        timesteps: jnp.ndarray,              # (B,)
        encoder_hidden_states: jnp.ndarray,  # (B, S, cross_dim)
        added_cond: dict[str, jnp.ndarray] | None = None,
    ) -> jnp.ndarray:
        cfg = self.config
        dtype = self.dtype
        channels = list(cfg.block_out_channels)
        b, f, hh, ww, _ = sample.shape

        temb = time_conditioning(cfg, dtype, timesteps, added_cond)
        temb_f = jnp.repeat(temb, f, axis=0)             # (B*F, D)
        temb_bf = jnp.repeat(temb[:, None], f, axis=1)   # (B, F, D)
        ctx = encoder_hidden_states.astype(dtype)

        x = nn.Conv(channels[0], (3, 3), padding=1, dtype=dtype,
                    name="conv_in")(_fold(sample.astype(dtype)))
        x = _unfold(x, b, f)
        skips = [x]

        for level, ch in enumerate(channels):
            depth = cfg.transformer_depth[level]
            heads, head_dim = cfg.heads_for(ch, level)
            eps = 1e-6 if depth > 0 else 1e-5
            for j in range(cfg.layers_per_block):
                x = SpatioTemporalResBlock(
                    ch, eps, dtype,
                    name=f"down_{level}_resnets_{j}")(x, temb_f, temb_bf)
                if depth > 0:
                    x = TransformerSpatioTemporal(
                        depth, heads, head_dim, dtype, cfg.attn_impl,
                        name=f"down_{level}_attentions_{j}")(x, ctx)
                skips.append(x)
            if level < len(channels) - 1:
                x = _unfold(Downsample(ch, dtype,
                                       name=f"down_{level}_downsample")(
                    _fold(x)), b, f)
                skips.append(x)

        mid_ch = channels[-1]
        mid_heads, mid_head_dim = cfg.heads_for(mid_ch, len(channels) - 1)
        mid_depth = max(d for d in cfg.transformer_depth) or 1
        x = SpatioTemporalResBlock(mid_ch, 1e-5, dtype,
                                   name="mid_resnets_0")(x, temb_f, temb_bf)
        x = TransformerSpatioTemporal(
            mid_depth, mid_heads, mid_head_dim, dtype, cfg.attn_impl,
            name="mid_attention")(x, ctx)
        x = SpatioTemporalResBlock(mid_ch, 1e-5, dtype,
                                   name="mid_resnets_1")(x, temb_f, temb_bf)

        for rev, ch in enumerate(reversed(channels)):
            level = len(channels) - 1 - rev
            depth = cfg.transformer_depth[level]
            heads, head_dim = cfg.heads_for(ch, level)
            eps = 1e-6 if depth > 0 else 1e-5
            for j in range(cfg.layers_per_block + 1):
                skip = skips.pop()
                x = jnp.concatenate([x, skip], axis=-1)
                x = SpatioTemporalResBlock(
                    ch, eps, dtype,
                    name=f"up_{level}_resnets_{j}")(x, temb_f, temb_bf)
                if depth > 0:
                    x = TransformerSpatioTemporal(
                        depth, heads, head_dim, dtype, cfg.attn_impl,
                        name=f"up_{level}_attentions_{j}")(x, ctx)
            if level > 0:
                x = _unfold(Upsample(ch, dtype,
                                     name=f"up_{level}_upsample")(
                    _fold(x)), b, f)

        x = _fold(x)
        x = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]), epsilon=1e-5,
                         dtype=jnp.float32, name="conv_norm_out")(x)
        x = nn.silu(x).astype(dtype)
        x = nn.Conv(cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32,
                    name="conv_out")(x)
        return _unfold(x, b, f)
