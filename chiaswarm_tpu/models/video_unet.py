"""Temporal (video) diffusion UNet — ModelScope-class text-to-video.

The model family behind the reference's txt2vid workload
(swarm/video/tx2vid.py:17-57 runs ``damo-vilab/text-to-video-ms-1.7b``
through diffusers). Factorized space-time design, the standard for this
class: every level runs the 2D blocks of models/unet.py with frames folded
into the batch axis (pure reuse — same parameter naming, so the 2D
converter rules extend), interleaved with

- :class:`TemporalAttention`: self-attention along the frame axis at each
  spatial site (frames become the sequence; spatial sites fold into batch),
  with a learned frame-position embedding;
- a temporal 1D conv in each level (local motion mixing).

TPU notes: both foldings are pure reshapes in NHWC — XLA sees large, static
(B*F, H, W, C) convs for the MXU and (B*H*W, F, C) attention batches; no
gather/scatter, no dynamic shapes. Frame count is a compile-time static
(bucketed by the pipeline).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from chiaswarm_tpu.models.common import num_groups as _num_groups
from chiaswarm_tpu.models.configs import UNetConfig
from chiaswarm_tpu.models.unet import (
    Downsample,
    ResnetBlock,
    SpatialTransformer,
    Upsample,
    time_conditioning,
)
from chiaswarm_tpu.ops.attention import attention

zeros_init = nn.initializers.zeros


class TemporalAttention(nn.Module):
    """Self-attention over the frame axis. Input (B, F, H, W, C); the
    output projection is zero-initialized so an untrained temporal layer
    is identity (frames stay independent), the AnimateDiff-style safe
    default for weights converted from 2D checkpoints."""

    num_heads: int
    head_dim: int
    max_frames: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, f, h, w, c = x.shape
        residual = x
        pos = self.param("frame_pos_embed",
                         nn.initializers.normal(0.02),
                         (self.max_frames, c))
        seq = x.transpose(0, 2, 3, 1, 4).reshape(b * h * w, f, c)
        seq = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm")(seq)
        seq = (seq + pos[None, :f, :]).astype(self.dtype)
        inner = self.num_heads * self.head_dim
        q = nn.Dense(inner, use_bias=False, dtype=self.dtype,
                     name="to_q")(seq)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype,
                     name="to_k")(seq)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype,
                     name="to_v")(seq)
        n = b * h * w
        out = attention(
            q.reshape(n, f, self.num_heads, self.head_dim),
            k.reshape(n, f, self.num_heads, self.head_dim),
            v.reshape(n, f, self.num_heads, self.head_dim),
            impl="xla",  # tiny sequence (frames) — einsum path
        ).reshape(n, f, inner)
        out = nn.Dense(c, kernel_init=zeros_init, dtype=self.dtype,
                       name="to_out")(out)
        out = out.reshape(b, h, w, f, c).transpose(0, 3, 1, 2, 4)
        return residual + out


class TemporalConv(nn.Module):
    """1D conv over frames (local motion), zero-init output -> identity."""

    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, f, h, w, c = x.shape
        residual = x
        seq = x.transpose(0, 2, 3, 1, 4).reshape(b * h * w, f, c)
        seq = nn.GroupNorm(num_groups=_num_groups(c), epsilon=1e-5,
                           dtype=jnp.float32, name="norm")(seq)
        seq = nn.silu(seq).astype(self.dtype)
        seq = nn.Conv(self.channels, (3,), padding="SAME", dtype=self.dtype,
                      name="conv1")(seq)
        seq = nn.silu(seq)
        seq = nn.Conv(c, (3,), padding="SAME", kernel_init=zeros_init,
                      dtype=self.dtype, name="conv2")(seq)
        return residual + seq.reshape(b, h, w, f, c).transpose(0, 3, 1, 2, 4)


class VideoUNet(nn.Module):
    """(B, F, H, W, C) latents -> model prediction, text-conditioned.

    Spatial blocks share models/unet.py modules (frames folded into
    batch); temporal attention + conv interleave at every level.
    """

    config: UNetConfig
    max_frames: int = 64

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(
        self,
        sample: jnp.ndarray,                 # (B, F, H, W, C)
        timesteps: jnp.ndarray,              # (B,)
        encoder_hidden_states: jnp.ndarray,  # (B, S, cross_dim)
        added_cond: dict[str, jnp.ndarray] | None = None,  # SVD micro-cond
    ) -> jnp.ndarray:
        cfg = self.config
        dtype = self.dtype
        channels = list(cfg.block_out_channels)
        b, f, hh, ww, _ = sample.shape

        temb = time_conditioning(cfg, dtype, timesteps, added_cond)
        temb_f = jnp.repeat(temb, f, axis=0)          # (B*F, D) for 2D blocks
        ctx = encoder_hidden_states.astype(dtype)
        ctx_f = jnp.repeat(ctx, f, axis=0)            # frames share the text

        def fold(x):   # (B, F, H, W, C) -> (B*F, H, W, C)
            return x.reshape((-1,) + x.shape[2:])

        def unfold(x):
            return x.reshape((b, f) + x.shape[1:])

        x = nn.Conv(channels[0], (3, 3), padding=1, dtype=dtype,
                    name="conv_in")(fold(sample.astype(dtype)))
        x = unfold(x)
        skips = [x]

        # ---- down path
        for level, ch in enumerate(channels):
            depth = cfg.transformer_depth[level]
            heads, head_dim = cfg.heads_for(ch, level)
            for j in range(cfg.layers_per_block):
                x = unfold(ResnetBlock(ch, dtype,
                                       name=f"down_{level}_resnets_{j}")(
                    fold(x), temb_f))
                x = TemporalConv(ch, dtype,
                                 name=f"down_{level}_tconv_{j}")(x)
                if depth > 0:
                    x = unfold(SpatialTransformer(
                        depth, heads, head_dim, cfg.use_linear_projection,
                        dtype, cfg.attn_impl,
                        name=f"down_{level}_attentions_{j}")(fold(x), ctx_f))
                    x = TemporalAttention(
                        heads, head_dim, self.max_frames, dtype,
                        name=f"down_{level}_tattn_{j}")(x)
                skips.append(x)
            if level < len(channels) - 1:
                x = unfold(Downsample(ch, dtype,
                                      name=f"down_{level}_downsample")(
                    fold(x)))
                skips.append(x)

        # ---- mid
        mid_ch = channels[-1]
        mid_heads, mid_head_dim = cfg.heads_for(mid_ch, len(channels) - 1)
        mid_depth = max(d for d in cfg.transformer_depth) or 1
        x = unfold(ResnetBlock(mid_ch, dtype, name="mid_resnets_0")(
            fold(x), temb_f))
        x = unfold(SpatialTransformer(
            mid_depth, mid_heads, mid_head_dim, cfg.use_linear_projection,
            dtype, cfg.attn_impl, name="mid_attention")(fold(x), ctx_f))
        x = TemporalAttention(mid_heads, mid_head_dim, self.max_frames,
                              dtype, name="mid_tattn")(x)
        x = unfold(ResnetBlock(mid_ch, dtype, name="mid_resnets_1")(
            fold(x), temb_f))

        # ---- up path
        for rev, ch in enumerate(reversed(channels)):
            level = len(channels) - 1 - rev
            depth = cfg.transformer_depth[level]
            heads, head_dim = cfg.heads_for(ch, level)
            for j in range(cfg.layers_per_block + 1):
                skip = skips.pop()
                x = jnp.concatenate([x, skip], axis=-1)
                x = unfold(ResnetBlock(ch, dtype,
                                       name=f"up_{level}_resnets_{j}")(
                    fold(x), temb_f))
                x = TemporalConv(ch, dtype, name=f"up_{level}_tconv_{j}")(x)
                if depth > 0:
                    x = unfold(SpatialTransformer(
                        depth, heads, head_dim, cfg.use_linear_projection,
                        dtype, cfg.attn_impl,
                        name=f"up_{level}_attentions_{j}")(fold(x), ctx_f))
                    x = TemporalAttention(
                        heads, head_dim, self.max_frames, dtype,
                        name=f"up_{level}_tattn_{j}")(x)
            if level > 0:
                x = unfold(Upsample(ch, dtype,
                                    name=f"up_{level}_upsample")(fold(x)))

        x = fold(x)
        x = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]), epsilon=1e-5,
                         dtype=jnp.float32, name="conv_norm_out")(x)
        x = nn.silu(x).astype(dtype)
        x = nn.Conv(cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32,
                    name="conv_out")(x)
        return unfold(x)
