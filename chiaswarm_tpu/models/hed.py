"""HED edge detector — the learned scribble/softedge preprocessor.

The reference gets soft edges from controlnet_aux's HEDdetector
(swarm/controlnet/input_processor.py:17-60 dispatch). This is the same
network natively: a VGG-style trunk of five double/triple-conv blocks
with a 1x1 side projection per block; the five side maps upsample to the
input size and fuse by sigmoid-of-mean. Weights convert from the public
``ControlNetHED.pth`` layout (convert/torch_to_flax.py::convert_hed).

The CNN runs under jit; resize/fusion is host-side like the other
preprocessors (workloads/controlnet.py).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# (out_channels, n_convs) per block — the fixed ControlNetHED graph
_BLOCKS = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]


class HEDBlock(nn.Module):
    channels: int
    n_convs: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        for i in range(self.n_convs):
            x = nn.relu(nn.Conv(self.channels, (3, 3), padding=1,
                                dtype=self.dtype, name=f"convs_{i}")(x))
        side = nn.Conv(1, (1, 1), dtype=self.dtype, name="projection")(x)
        return x, side


class HEDNetwork(nn.Module):
    """(B, H, W, 3) raw RGB (0-255 floats) -> 5 side maps at strides
    1/1, 1/2, 1/4, 1/8, 1/16 (pre-sigmoid logits)."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> list[jnp.ndarray]:
        norm = self.param("norm", nn.initializers.zeros, (3,))
        x = x.astype(self.dtype) - norm.astype(self.dtype)
        sides = []
        for b, (ch, n) in enumerate(_BLOCKS):
            if b > 0:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            x, side = HEDBlock(ch, n, self.dtype, name=f"block{b + 1}")(x)
            sides.append(side)
        return sides


@dataclasses.dataclass
class HEDDetector:
    """Ties the jitted CNN to the host fuse: sigmoid of the mean of the
    upsampled side maps (controlnet_aux HEDdetector semantics)."""

    params: dict

    def __post_init__(self) -> None:
        self._net = HEDNetwork()
        self._fwd = jax.jit(lambda p, x: self._net.apply(p, x))

    @classmethod
    def random(cls, seed: int = 0, canvas: int = 512) -> "HEDDetector":
        net = HEDNetwork()
        x = jnp.zeros((1, 64, 64, 3), jnp.float32)
        return cls(params=jax.jit(net.init)(jax.random.PRNGKey(seed), x),
                   canvas=canvas)

    @classmethod
    def from_checkpoint(cls, path) -> "HEDDetector":
        from chiaswarm_tpu.convert.torch_to_flax import (
            convert_hed,
            read_torch_weights,
        )

        return cls(params=convert_hed(read_torch_weights(path)))

    # fixed working canvas: ONE compiled shape for every request (the
    # per-size alternative recompiles the whole VGG on each new 16-px
    # bucket, a multi-second stall inside the job)
    canvas: int = 512

    def __call__(self, image: np.ndarray) -> np.ndarray:
        """uint8 RGB (H, W, 3) -> uint8 single-channel edge map."""
        import cv2

        h, w = image.shape[:2]
        scale = self.canvas / max(h, w, 1)
        nh = max(16, min(self.canvas, round(h * scale)))
        nw = max(16, min(self.canvas, round(w * scale)))
        resized = cv2.resize(image, (nw, nh),
                             interpolation=cv2.INTER_AREA)
        # replicate-pad to the square canvas: a zero apron would read as
        # a hard dark border after mean subtraction and ring every scale
        padded = cv2.copyMakeBorder(resized, 0, self.canvas - nh, 0,
                                    self.canvas - nw, cv2.BORDER_REPLICATE)
        sides = jax.device_get(self._fwd(
            self.params, jnp.asarray(padded.astype(np.float32))[None]))
        maps = []
        for side in sides:
            m = np.asarray(side, np.float32)[0, :, :, 0]
            # crop the pad at map scale, then resize to the image
            sy = m.shape[0] / self.canvas
            sx = m.shape[1] / self.canvas
            m = m[: max(1, round(nh * sy)), : max(1, round(nw * sx))]
            maps.append(cv2.resize(m, (w, h),
                                   interpolation=cv2.INTER_LINEAR))
        fused = 1.0 / (1.0 + np.exp(-np.mean(np.stack(maps), axis=0)))
        return (fused * 255.0).clip(0, 255).astype(np.uint8)
