"""ControlNet (Flax, NHWC): conditioning branch for the diffusion UNet.

Capability parity with the reference's ControlNet path — loading a
``ControlNetModel`` next to the pipeline and running UNet+ControlNet in the
hot loop (swarm/diffusion/diffusion_func.py:29-39,96;
swarm/job_arguments.py:116-124). TPU-first differences:

- The conditioning-image embedder (:class:`ControlCondEmbedding`) is
  timestep-independent, so the pipeline evaluates it ONCE and hoists it out
  of the ``lax.scan`` denoise loop; diffusers recomputes it every step.
- The control branch shares this framework's UNet block modules (NHWC,
  Pallas-flash-eligible attention) and the same parameter naming, so the
  checkpoint converter (convert/torch_to_flax.py) maps diffusers
  ``ControlNetModel`` state dicts with the same path rules as the UNet.
- ``conditioning_scale`` is a traced scalar — changing it never recompiles.

The residuals it returns feed the UNet's ``down_residuals``/``mid_residual``
injection points (models/unet.py).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from chiaswarm_tpu.models.configs import UNetConfig
from chiaswarm_tpu.models.unet import down_trunk, mid_trunk, time_conditioning

zeros_init = nn.initializers.zeros


class ControlCondEmbedding(nn.Module):
    """Conditioning image (B, H, W, 3) in [-1, 1] -> (B, H/8, W/8, C0).

    The "hint" encoder: three stride-2 stages onto the latent grid, final
    conv zero-initialized so an untrained ControlNet is a no-op.
    """

    out_channels: int
    downscale: int = 8  # pixel -> latent grid factor (family.vae.downscale)
    dtype: jnp.dtype = jnp.float32

    @property
    def block_channels(self) -> tuple[int, ...]:
        stages = max(self.downscale.bit_length() - 1, 0)  # log2(downscale)
        return (16, 32, 96, 256)[: stages + 1]

    @nn.compact
    def __call__(self, cond: jnp.ndarray) -> jnp.ndarray:
        x = cond.astype(self.dtype)
        x = nn.Conv(self.block_channels[0], (3, 3), padding=1,
                    dtype=self.dtype, name="conv_in")(x)
        x = nn.silu(x)
        for i in range(len(self.block_channels) - 1):
            x = nn.Conv(self.block_channels[i], (3, 3), padding=1,
                        dtype=self.dtype, name=f"blocks_{2 * i}")(x)
            x = nn.silu(x)
            x = nn.Conv(self.block_channels[i + 1], (3, 3), strides=(2, 2),
                        padding=1, dtype=self.dtype,
                        name=f"blocks_{2 * i + 1}")(x)
            x = nn.silu(x)
        return nn.Conv(self.out_channels, (3, 3), padding=1,
                       kernel_init=zeros_init, dtype=self.dtype,
                       name="conv_out")(x)


class ControlNet(nn.Module):
    """Control branch: mirrors the UNet down+mid path, emits zero-conv'd
    residuals ``(down_residuals, mid_residual)`` for UNet injection.

    ``cond_emb`` is the pre-embedded hint from :class:`ControlCondEmbedding`
    (hoisted out of the denoise scan by the pipeline).
    """

    config: UNetConfig

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(
        self,
        sample: jnp.ndarray,                 # (B, H/8, W/8, C_latent)
        timesteps: jnp.ndarray,              # (B,)
        encoder_hidden_states: jnp.ndarray,  # (B, S, cross_attention_dim)
        cond_emb: jnp.ndarray,               # (B, H/8, W/8, C0) pre-embedded
        added_cond: dict[str, jnp.ndarray] | None = None,
        conditioning_scale: jnp.ndarray | float = 1.0,
    ) -> tuple[tuple[jnp.ndarray, ...], jnp.ndarray]:
        cfg = self.config
        dtype = self.dtype
        channels = list(cfg.block_out_channels)

        temb = time_conditioning(cfg, dtype, timesteps, added_cond)
        context = encoder_hidden_states.astype(dtype)
        x = nn.Conv(channels[0], (3, 3), padding=1, dtype=dtype,
                    name="conv_in")(sample.astype(dtype))
        x = x + cond_emb.astype(dtype)
        x, skips = down_trunk(cfg, dtype, x, temb, context)
        x = mid_trunk(cfg, dtype, x, temb, context)

        mid_ch = channels[-1]
        scale = jnp.asarray(conditioning_scale, jnp.float32)
        down_residuals = tuple(
            scale * nn.Conv(s.shape[-1], (1, 1), kernel_init=zeros_init,
                            dtype=dtype,
                            name=f"controlnet_down_blocks_{i}")(s)
            for i, s in enumerate(skips)
        )
        mid_residual = scale * nn.Conv(
            mid_ch, (1, 1), kernel_init=zeros_init, dtype=dtype,
            name="controlnet_mid_block",
        )(x)
        return down_residuals, mid_residual
