"""M-LSD line-segment detector — the learned wireframe preprocessor.

The reference reaches mlsd conditioning through controlnet_aux's
MLSDdetector (swarm/controlnet/input_processor.py:17-60 dispatch), which
wraps the mlsd_pytorch ``MobileV2_MLSD_Large`` graph: a 4-channel-input
MobileNetV2 trunk (inverted residuals up to the 96-channel stage, FPN taps
at features [1, 3, 6, 10, 13]) and a decoder of TypeA (1x1-conv merge +
align-corners bilinear 2x) / TypeB (residual 3x3) blocks ending in a
TypeC (dilated 3x3) head producing 16 maps at quarter resolution; the last
9 are the TP map (center heat + 4 displacement + 4 aux). Weights convert
from the public ``mlsd_large_512_fp32.pth`` layout
(convert/torch_to_flax.py::convert_mlsd).

TPU-native notes: BatchNorm folds to inference affine at load time is NOT
done — running stats are applied exactly (eps 1e-5) so converter fidelity
is testable; the align-corners bilinear 2x (which jax.image.resize does
not offer) is two tiny dense interpolation matrices applied per axis —
static shapes, MXU-friendly. The CNN runs under jit; the line decode
(center-NMS top-K + displacement endpoints, controlnet_aux
``pred_lines`` semantics) is host-side numpy like every other
preprocessor's post step (workloads/controlnet.py).
"""

from __future__ import annotations

import dataclasses
import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# MobileNetV2 inverted-residual plan (t, c, n, s) — mlsd_pytorch subset
_MBV2_PLAN = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
              (6, 96, 3, 1)]
_FPN_TAPS = (1, 3, 6, 10, 13)  # feature indices -> c1..c5


class BatchNorm(nn.Module):
    """Inference-mode torch BatchNorm2d: affine + running stats."""

    features: int
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        scale = self.param("scale", nn.initializers.ones, (self.features,))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        mean = self.param("mean", nn.initializers.zeros, (self.features,))
        var = self.param("var", nn.initializers.ones, (self.features,))
        inv = scale / jnp.sqrt(var + self.eps)
        return x * inv + (bias - mean * inv)


class ConvBN(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1
    dilation: int = 1
    groups: int = 1
    relu6: bool = True
    relu: bool = False
    # backbone ConvBNReLU convs are bias-free (torchvision); the decoder
    # blocks use default nn.Conv2d(bias=True) — redundant under BN but
    # present in the public checkpoint, so it must exist to convert
    use_bias: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        pad = (self.kernel - 1) // 2 * self.dilation
        x = nn.Conv(self.features, (self.kernel, self.kernel),
                    strides=(self.stride, self.stride), padding=pad,
                    kernel_dilation=(self.dilation, self.dilation),
                    feature_group_count=self.groups, use_bias=self.use_bias,
                    name="conv")(x)
        x = BatchNorm(self.features, name="bn")(x)
        if self.relu6:
            return jnp.minimum(nn.relu(x), 6.0)
        if self.relu:
            return nn.relu(x)
        return x


class InvertedResidual(nn.Module):
    features: int
    stride: int
    expand: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        in_ch = x.shape[-1]
        h = x
        i = 0
        if self.expand != 1:
            h = ConvBN(in_ch * self.expand, kernel=1, name=f"layer_{i}")(h)
            i += 1
        h = ConvBN(in_ch * self.expand, kernel=3, stride=self.stride,
                   groups=in_ch * self.expand, name=f"layer_{i}")(h)
        h = nn.Conv(self.features, (1, 1), use_bias=False, name="project")(h)
        h = BatchNorm(self.features, name="project_bn")(h)
        if self.stride == 1 and in_ch == self.features:
            h = x + h
        return h


def _align_corners_up2(x: jnp.ndarray) -> jnp.ndarray:
    """Bilinear 2x upsample with torch align_corners=True semantics,
    as two static interpolation matrices (NHWC)."""
    def matrix(n: int) -> np.ndarray:
        out = 2 * n
        w = np.zeros((out, n), np.float32)
        if n == 1:
            w[:, 0] = 1.0
            return w
        src = np.arange(out) * (n - 1) / (out - 1)
        lo = np.floor(src).astype(np.int64)
        hi = np.minimum(lo + 1, n - 1)
        frac = (src - lo).astype(np.float32)
        w[np.arange(out), lo] += 1.0 - frac
        w[np.arange(out), hi] += frac
        return w

    wh = jnp.asarray(matrix(x.shape[1]))
    ww = jnp.asarray(matrix(x.shape[2]))
    x = jnp.einsum("ij,bjwc->biwc", wh, x)
    return jnp.einsum("kw,bhwc->bhkc", ww, x)


class BlockTypeA(nn.Module):
    out_c1: int
    out_c2: int
    upscale: bool = True

    @nn.compact
    def __call__(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        b = ConvBN(self.out_c2, kernel=1, relu6=False, relu=True,
                   use_bias=True, name="conv1")(b)
        a = ConvBN(self.out_c1, kernel=1, relu6=False, relu=True,
                   use_bias=True, name="conv2")(a)
        if self.upscale:
            b = _align_corners_up2(b)
        return jnp.concatenate([a, b], axis=-1)


class BlockTypeB(nn.Module):
    features: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        in_ch = x.shape[-1]
        x = ConvBN(in_ch, kernel=3, relu6=False, relu=True,
                   use_bias=True, name="conv1")(x) + x
        return ConvBN(self.features, kernel=3, relu6=False, relu=False,
                      use_bias=True, name="conv2")(x)


class BlockTypeC(nn.Module):
    features: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        in_ch = x.shape[-1]
        x = ConvBN(in_ch, kernel=3, dilation=5, relu6=False, relu=True,
                   use_bias=True, name="conv1")(x)
        x = ConvBN(in_ch, kernel=3, relu6=False, relu=True,
                   use_bias=True, name="conv2")(x)
        return nn.Conv(self.features, (1, 1), name="conv3")(x)


class MLSDNetwork(nn.Module):
    """(B, H, W, 4) normalized input -> (B, H/2, W/2, 9) TP map
    (MobileV2_MLSD_Large forward, keeping channels [7:16])."""

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        feats = []
        x = ConvBN(32, kernel=3, stride=2, name="stem")(x)
        feats.append(x)
        in_ch = 32
        idx = 1
        for t, c, n, s in _MBV2_PLAN:
            for j in range(n):
                x = InvertedResidual(c, s if j == 0 else 1, t,
                                     name=f"ir_{idx}")(x)
                feats.append(x)
                in_ch = c
                idx += 1
        c1, c2, c3, c4, c5 = (feats[i] for i in _FPN_TAPS)

        x = BlockTypeA(64, 64, upscale=False, name="block15")(c4, c5)
        x = BlockTypeB(64, name="block16")(x)
        x = BlockTypeA(64, 64, name="block17")(c3, x)
        x = BlockTypeB(64, name="block18")(x)
        x = BlockTypeA(64, 64, name="block19")(c2, x)
        x = BlockTypeB(64, name="block20")(x)
        x = BlockTypeA(64, 64, name="block21")(c1, x)
        x = BlockTypeB(64, name="block22")(x)
        x = BlockTypeC(16, name="block23")(x)
        return x[..., 7:]


def decode_lines(tp_map: np.ndarray, *, score_thr: float = 0.1,
                 dist_thr: float = 0.1, top_k: int = 200) -> np.ndarray:
    """controlnet_aux ``deccode_output_score_and_ptss`` + ``pred_lines``
    semantics on the (H/2, W/2, 9) TP map: sigmoid center heat, 3x3
    local-max NMS, top-K peaks, endpoints = peak +- displacement, kept if
    score > thr and map-space length > dist_thr (compared directly, like
    pred_lines; the default 0.1 is MLSDdetector's thr_d, which keeps
    nearly every scored segment). Returns (N, 4) [x1, y1, x2, y2] in
    FULL-resolution (2x map) coordinates."""
    center = tp_map[:, :, 0]
    disp = tp_map[:, :, 1:5]
    heat = 1.0 / (1.0 + np.exp(-center))
    # 3x3 max filter (numpy sliding max via padded shifts)
    p = np.pad(heat, 1, mode="constant", constant_values=-np.inf)
    hmax = heat.copy()
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            hmax = np.maximum(
                hmax, p[1 + dy: 1 + dy + heat.shape[0],
                        1 + dx: 1 + dx + heat.shape[1]])
    nms = np.where(hmax == heat, heat, 0.0)
    flat = nms.reshape(-1)
    k = min(top_k, flat.size)
    top = np.argpartition(-flat, k - 1)[:k]
    top = top[np.argsort(-flat[top])]
    yy, xx = np.unravel_index(top, nms.shape)

    lines = []
    for y, x in zip(yy, xx):
        if nms[y, x] <= score_thr:
            continue
        dxs, dys, dxe, dye = disp[y, x]
        x1, y1 = x + dxs, y + dys
        x2, y2 = x + dxe, y + dye
        if np.hypot(x2 - x1, y2 - y1) > dist_thr:
            lines.append((x1 * 2, y1 * 2, x2 * 2, y2 * 2))
    return np.asarray(lines, np.float32).reshape(-1, 4)


@dataclasses.dataclass
class MLSDDetector:
    """Host-facing wrapper: uint8 RGB -> uint8 white-on-black wireframe
    (the M-LSD conditioning format)."""

    params: dict
    canvas: int = 512  # fixed compiled shape (models/hed.py rationale)

    def __post_init__(self) -> None:
        self._net = MLSDNetwork()
        self._fwd = jax.jit(lambda p, x: self._net.apply(p, x))

    @classmethod
    def random(cls, seed: int = 0, canvas: int = 512) -> "MLSDDetector":
        net = MLSDNetwork()
        x = jnp.zeros((1, 64, 64, 4), jnp.float32)
        return cls(params=jax.jit(net.init)(jax.random.PRNGKey(seed), x),
                   canvas=canvas)

    @classmethod
    def from_checkpoint(cls, path) -> "MLSDDetector":
        from chiaswarm_tpu.convert.torch_to_flax import (
            convert_mlsd,
            read_torch_weights,
        )

        return cls(params=convert_mlsd(read_torch_weights(path)))

    def __call__(self, image: np.ndarray, *, score_thr: float = 0.1,
                 dist_thr: float = 0.1) -> np.ndarray:
        import cv2

        h, w = image.shape[:2]
        # aspect-preserving resize + replicate pad (same scheme as
        # HEDDetector/LineartDetector): squashing to a square would
        # distort line geometry relative to the image the UNet sees
        scale = self.canvas / max(h, w, 1)
        nh = max(16, min(self.canvas, round(h * scale)))
        nw = max(16, min(self.canvas, round(w * scale)))
        resized = cv2.resize(image, (nw, nh), interpolation=cv2.INTER_AREA)
        padded = cv2.copyMakeBorder(resized, 0, self.canvas - nh, 0,
                                    self.canvas - nw, cv2.BORDER_REPLICATE)
        # pred_lines input prep: np.ones (value 1.0, NOT 255) concatenates
        # BEFORE the /127.5-1 normalization, so the trained 4th channel is
        # 1/127.5 - 1 ~= -0.992
        x = np.concatenate(
            [padded.astype(np.float32),
             np.ones(padded.shape[:2] + (1,), np.float32)],
            axis=-1) / 127.5 - 1.0
        tp = np.asarray(jax.device_get(
            self._fwd(self.params, jnp.asarray(x)[None])))[0]
        lines = decode_lines(tp, score_thr=score_thr, dist_thr=dist_thr)
        # draw at full-resolution canvas scale, thick enough to survive
        # the downscale back to the request size
        out = np.zeros((self.canvas, self.canvas), np.uint8)
        thickness = max(1, int(round(1.0 / max(scale, 1e-6))))
        for x1, y1, x2, y2 in lines:
            cv2.line(out, (int(round(x1)), int(round(y1))),
                     (int(round(x2)), int(round(y2))), 255, thickness)
        out = out[:nh, :nw]
        return cv2.resize(out, (w, h), interpolation=cv2.INTER_NEAREST)
