"""BLIP-class captioning / VQA model (Flax) — the img2txt workload's trunk.

The reference runs BLIP through torch classes named by the hive
(swarm/captioning/caption_image.py:12-30). Here the model is native:

- :class:`BlipVisionEncoder` — pre-LN ViT over patch tokens (the image
  tower; one jitted forward, 577 tokens at 384px).
- :class:`BlipTextModel` — BERT-style post-LN transformer with per-layer
  cross-attention onto the vision sequence. One module serves both roles
  the BLIP family needs: bidirectional *encoder* (VQA question tower) and
  causal *decoder* with a static-shape KV cache (caption/answer head).

TPU-first decode design (mirrors models/gpt.py): the cross-attention
K/V over the image are computed ONCE per image (they never change during
decoding), the self-attention cache is a fixed ring carried through a
``lax.scan``, and greedy token selection happens on-chip — the whole
caption is one compiled program, no per-token dispatch.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

NEG_INF = -1e9


# --------------------------------------------------------------- configs

@dataclasses.dataclass(frozen=True)
class BlipVisionConfig:
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    image_size: int = 384
    patch_size: int = 16
    layer_norm_eps: float = 1e-5
    dtype: str = "float32"

    @property
    def num_tokens(self) -> int:
        return (self.image_size // self.patch_size) ** 2 + 1


@dataclasses.dataclass(frozen=True)
class BlipTextConfig:
    vocab_size: int = 30524           # BERT vocab + [DEC]/[ENC]
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 512
    encoder_hidden_size: int = 768    # vision width cross-attended to
    layer_norm_eps: float = 1e-12
    bos_token_id: int = 30522         # [DEC]
    sep_token_id: int = 102           # [SEP] — decode stop token
    pad_token_id: int = 0
    dtype: str = "float32"


@dataclasses.dataclass(frozen=True)
class BlipConfig:
    name: str = "blip_base"
    vision: BlipVisionConfig = BlipVisionConfig()
    text: BlipTextConfig = BlipTextConfig()
    # image preprocessing (host side): CLIP-style mean/std
    pixel_mean: Sequence[float] = (0.48145466, 0.4578275, 0.40821073)
    pixel_std: Sequence[float] = (0.26862954, 0.26130258, 0.27577711)


BLIP_BASE = BlipConfig()

BLIP_TINY = BlipConfig(
    name="blip_tiny",
    vision=BlipVisionConfig(hidden_size=32, intermediate_size=64,
                            num_layers=2, num_heads=4, image_size=32,
                            patch_size=8),
    text=BlipTextConfig(vocab_size=1000, hidden_size=32,
                        intermediate_size=64, num_layers=2, num_heads=4,
                        max_position_embeddings=64, encoder_hidden_size=32,
                        bos_token_id=998, sep_token_id=999),
)

BLIP_CONFIGS = {c.name: c for c in (BLIP_BASE, BLIP_TINY)}


# ---------------------------------------------------------------- vision

class BlipVisionLayer(nn.Module):
    config: BlipVisionConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        b, l, _ = x.shape
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="layer_norm1")(x).astype(self.dtype)
        qkv = nn.Dense(3 * cfg.hidden_size, dtype=self.dtype,
                       name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda t: t.reshape(b, l, cfg.num_heads, head_dim)
        q, k, v = split(q), split(k), split(v)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores * (head_dim ** -0.5)
        weights = nn.softmax(scores, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v).reshape(b, l, -1)
        x = x + nn.Dense(cfg.hidden_size, dtype=self.dtype,
                         name="projection")(out)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="layer_norm2")(x).astype(self.dtype)
        h = nn.Dense(cfg.intermediate_size, dtype=self.dtype, name="fc1")(h)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="fc2")(h)
        return x + h


class BlipVisionEncoder(nn.Module):
    """(B, H, W, 3) normalized pixels -> (B, tokens, hidden) patch states."""

    config: BlipVisionConfig

    @nn.compact
    def __call__(self, pixel_values: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        b = pixel_values.shape[0]
        patches = nn.Conv(
            cfg.hidden_size, (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size), dtype=dtype,
            name="patch_embedding",
        )(pixel_values.astype(dtype))
        patches = patches.reshape(b, -1, cfg.hidden_size)
        cls = self.param("class_embedding", nn.initializers.normal(0.02),
                         (cfg.hidden_size,))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(dtype), (b, 1, cfg.hidden_size)),
             patches], axis=1)
        pos = self.param("position_embedding",
                         nn.initializers.normal(0.02),
                         (cfg.num_tokens, cfg.hidden_size))
        x = x + pos[None, : x.shape[1]].astype(dtype)
        for i in range(cfg.num_layers):
            x = BlipVisionLayer(cfg, dtype, name=f"layers_{i}")(x)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                            name="post_layernorm")(x)


# ------------------------------------------------------------------ text

class BlipTextLayer(nn.Module):
    """BERT-style post-LN block with cross-attention.

    Three entry modes (all sharing one param set):
    - ``cross_kv``: project encoder states to this layer's cross K/V once.
    - full forward (``cache is None``): bidirectional or causal self-attn
      over the whole padded sequence (encoder tower / prefill).
    - cached step (``cache`` given): self-attn against the KV ring at
      ``index`` (scan decode).
    """

    config: BlipTextConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        cfg = self.config
        dense = partial(nn.Dense, dtype=self.dtype)
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_eps,
                     dtype=jnp.float32)
        self.self_query = dense(cfg.hidden_size, name="self_query")
        self.self_key = dense(cfg.hidden_size, name="self_key")
        self.self_value = dense(cfg.hidden_size, name="self_value")
        self.self_out = dense(cfg.hidden_size, name="self_out")
        self.self_ln = ln(name="self_ln")
        self.cross_query = dense(cfg.hidden_size, name="cross_query")
        self.cross_key = dense(cfg.hidden_size, name="cross_key")
        self.cross_value = dense(cfg.hidden_size, name="cross_value")
        self.cross_out = dense(cfg.hidden_size, name="cross_out")
        self.cross_ln = ln(name="cross_ln")
        self.intermediate = dense(cfg.intermediate_size, name="intermediate")
        self.output = dense(cfg.hidden_size, name="output")
        self.output_ln = ln(name="output_ln")

    def _heads(self, t: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        b, l, _ = t.shape
        return t.reshape(b, l, cfg.num_heads,
                         cfg.hidden_size // cfg.num_heads)

    def cross_kv(self, enc_states: jnp.ndarray) -> tuple[jnp.ndarray,
                                                         jnp.ndarray]:
        return (self._heads(self.cross_key(enc_states)),
                self._heads(self.cross_value(enc_states)))

    def _attend(self, q, k, v, bias) -> jnp.ndarray:
        head_dim = q.shape[-1]
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores * (head_dim ** -0.5)
        if bias is not None:
            scores = scores + bias
        weights = nn.softmax(scores, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        b, l = out.shape[:2]
        return out.reshape(b, l, -1)

    def __call__(self, x, *, self_bias, cross_k=None, cross_v=None,
                 cross_bias=None, cache=None, index=None):
        q = self._heads(self.self_query(x))
        k = self._heads(self.self_key(x))
        v = self._heads(self.self_value(x))
        if cache is not None:
            ck, cv = cache
            ck = jax.lax.dynamic_update_slice(ck, k, (0, index, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v, (0, index, 0, 0))
            k, v, cache = ck, cv, (ck, cv)
        attn = self._attend(q, k, v, self_bias)
        x = self.self_ln(x + self.self_out(attn)).astype(self.dtype)
        if cross_k is not None:
            cq = self._heads(self.cross_query(x))
            attn = self._attend(cq, cross_k, cross_v, cross_bias)
            x = self.cross_ln(x + self.cross_out(attn)).astype(self.dtype)
        h = nn.gelu(self.intermediate(x), approximate=False)
        x = self.output_ln(x + self.output(h)).astype(self.dtype)
        return x, cache


class BlipTextModel(nn.Module):
    """Embeddings + N BlipTextLayers + LM head (shared across modes)."""

    config: BlipTextConfig
    with_lm_head: bool = True

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    def setup(self) -> None:
        cfg = self.config
        self.word_embeddings = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                                        dtype=self.dtype,
                                        name="word_embeddings")
        self.position_embeddings = self.param(
            "position_embeddings", nn.initializers.normal(0.02),
            (cfg.max_position_embeddings, cfg.hidden_size))
        self.embed_ln = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                     dtype=jnp.float32, name="embed_ln")
        self.layers = [BlipTextLayer(cfg, self.dtype, name=f"layer_{i}")
                       for i in range(cfg.num_layers)]
        if self.with_lm_head:
            self.head_transform = nn.Dense(cfg.hidden_size,
                                           dtype=self.dtype,
                                           name="head_transform")
            self.head_ln = nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                        dtype=jnp.float32, name="head_ln")
            self.decoder = nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                                    name="decoder")

    def _embed(self, ids: jnp.ndarray, index) -> jnp.ndarray:
        t = ids.shape[1]
        tok = self.word_embeddings(ids)
        pos = jax.lax.dynamic_slice(
            self.position_embeddings, (index, 0),
            (t, self.config.hidden_size))
        return self.embed_ln(tok + pos[None].astype(self.dtype)).astype(
            self.dtype)

    def cross_kvs(self, enc_states: jnp.ndarray) -> list:
        return [layer.cross_kv(enc_states) for layer in self.layers]

    def lm_logits(self, x: jnp.ndarray) -> jnp.ndarray:
        h = nn.gelu(self.head_transform(x), approximate=False)
        return self.decoder(self.head_ln(h).astype(self.dtype))

    def __call__(self, ids, *, causal: bool, attn_mask=None,
                 cross_kvs=None, cross_bias=None, caches=None, index=0,
                 valid_len=None, pos_index=None, ring_bias=None,
                 logits: bool = True):
        """Full forward (``caches=None``) or cached step.

        ``attn_mask``: (B, L) 1/0 key-validity (full forward only).
        ``caches``: per-layer (k, v) rings (B, ring, H, D); ``index`` is
        the ring position ``ids[:, 0]`` writes to; ``valid_len`` the
        count of live ring positions after this call. ``pos_index``
        (traced ok) overrides the *logical* position used for the
        position embeddings when it differs from the ring slot (padded
        prefills). ``ring_bias`` (1|B, 1, T, ring) replaces the default
        ring visibility mask.
        """
        cfg = self.config
        b, t = ids.shape
        x = self._embed(ids, index if pos_index is None else pos_index)

        if caches is None:
            bias = jnp.zeros((1, 1, t, t), jnp.float32)
            if causal:
                bias = bias + jnp.triu(
                    jnp.full((t, t), NEG_INF, jnp.float32), k=1)[None, None]
            if attn_mask is not None:
                bias = bias + jnp.where(
                    attn_mask[:, None, None, :] > 0, 0.0, NEG_INF)
        elif ring_bias is not None:
            bias = ring_bias
        else:
            ring = caches[0][0].shape[1]
            kpos = jnp.arange(ring)
            qpos = index + jnp.arange(t)
            ok = (kpos[None, :] <= qpos[:, None]) & (kpos[None, :] < valid_len)
            bias = jnp.where(ok, 0.0, NEG_INF)[None, None]

        new_caches = []
        for i, layer in enumerate(self.layers):
            ck = cross_kvs[i][0] if cross_kvs is not None else None
            cv = cross_kvs[i][1] if cross_kvs is not None else None
            x, cache = layer(
                x, self_bias=bias, cross_k=ck, cross_v=cv,
                cross_bias=cross_bias,
                cache=None if caches is None else caches[i],
                index=None if caches is None else index)
            new_caches.append(cache)
        if logits and self.with_lm_head:
            return self.lm_logits(x), new_caches
        return x, new_caches


def init_text_caches(cfg: BlipTextConfig, batch: int, ring: int) -> list:
    head_dim = cfg.hidden_size // cfg.num_heads
    shape = (batch, ring, cfg.num_heads, head_dim)
    dtype = jnp.dtype(cfg.dtype)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(cfg.num_layers)]


@partial(jax.jit, static_argnames=("model", "max_new", "prompt_len"))
def generate_text(model: BlipTextModel, params: Any,
                  prompt_ids: jnp.ndarray, enc_states: jnp.ndarray,
                  enc_mask: jnp.ndarray | None, *, prompt_len: int,
                  max_new: int,
                  actual_len: jnp.ndarray | int | None = None
                  ) -> jnp.ndarray:
    """Greedy cross-attending decode: prefill ``prompt_ids`` (B,
    prompt_len — [DEC] + optional conditioning text), then scan
    ``max_new`` steps. Returns (B, max_new) int32; positions after SEP
    repeat SEP (host trims).

    ``prompt_len`` is the STATIC prompt bucket (one compiled program per
    bucket); ``actual_len`` (traced, defaults to ``prompt_len``) is the
    number of real tokens — pad ``prompt_ids`` to the bucket with
    anything. Pad ring slots are masked out of every later query, the
    first generated token reads the logits at ``actual_len - 1``, and
    decode steps use *logical* positions (``actual_len + t``) for the
    position embeddings, so a padded prefill is numerically identical to
    an unpadded one.
    """
    cfg = model.config
    b = prompt_ids.shape[0]
    ring = prompt_len + max_new
    eos = jnp.int32(cfg.sep_token_id)
    alen = jnp.int32(prompt_len if actual_len is None else actual_len)

    cross_bias = None
    if enc_mask is not None:
        cross_bias = jnp.where(enc_mask[:, None, None, :] > 0, 0.0, NEG_INF)

    cross_kvs = model.apply(params, enc_states, method="cross_kvs")
    caches = init_text_caches(cfg, b, ring)
    kpos = jnp.arange(ring)

    # prefill: query i sees real prompt keys j <= i only
    qpos = jnp.arange(prompt_len)
    ok = (kpos[None, :] <= qpos[:, None]) & (kpos[None, :] < alen)
    logits, caches = model.apply(
        params, prompt_ids, causal=True, cross_kvs=cross_kvs,
        cross_bias=cross_bias, caches=caches, index=0,
        ring_bias=jnp.where(ok, 0.0, NEG_INF)[None, None])
    last = jnp.take_along_axis(
        logits, jnp.full((b, 1, 1), 1, jnp.int32) * (alen - 1), axis=1
    )[:, 0]
    first = jnp.argmax(last, axis=-1).astype(jnp.int32)

    def body(carry, _):
        caches, tok, idx, done = carry
        # idx = ring write slot (prompt_len + t); logical position is
        # alen + t; pad slots [alen, prompt_len) stay masked forever
        ok = (kpos < alen) | ((kpos >= prompt_len) & (kpos <= idx))
        logits, caches = model.apply(
            params, tok[:, None], causal=True, cross_kvs=cross_kvs,
            cross_bias=cross_bias, caches=caches, index=idx,
            pos_index=alen + (idx - prompt_len),
            ring_bias=jnp.where(ok, 0.0, NEG_INF)[None, None, None])
        nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, eos, nxt)
        done = done | (nxt == eos)
        return (caches, nxt, idx + 1, done), nxt

    (_, _, _, _), toks = jax.lax.scan(
        body, (caches, first, jnp.int32(prompt_len), first == eos),
        None, length=max_new - 1)
    return jnp.concatenate([first[:, None], toks.swapaxes(0, 1)], axis=1)
