"""CLAP text tower (Flax) — AudioLDM's conditioning encoder.

The reference's txt2audio path loads ``cvssp/audioldm-s-full-v2`` through
``AudioLDMPipeline`` (swarm/audio/audioldm.py:12-24), whose text encoder is
transformers' ``ClapTextModelWithProjection``: a **RoBERTa** language model
(post-LayerNorm residual blocks, learned absolute positions offset by the
pad id, token-type row 0, eps 1e-12) with a tanh CLS pooler and a two-layer
ReLU projection head. This is architecturally disjoint from CLIP's text
tower (pre-LN, causal mask, argmax-EOS pooling) — rounds 1-3 approximated
it with the CLIP module and VERDICT r3 correctly flagged that as a likely
real bug; this module is the faithful layout, oracle-tested against
transformers' own class in tests/test_real_config_parity.py.

TPU notes: static (batch, 77) shapes, one compiled program per bucket; the
tower is a few GEMMs per token — negligible next to the mel diffusion — so
everything stays on the fused XLA path (no pallas needed).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ClapTextConfig:
    """transformers ``ClapTextConfig`` defaults == the laion/clap-htsat
    checkpoints AudioLDM ships (text_encoder/config.json)."""

    vocab_size: int = 50265
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 514
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 1
    bos_token_id: int = 0
    eos_token_id: int = 2
    projection_dim: int = 512
    # static prompt length served by the node — the reference tokenizes at
    # RobertaTokenizer's model_max_length (512); padding is masked, so
    # short prompts embed identically and long prompts are no longer
    # truncated at ~75 tokens (ADVICE r4 #3). One compile bucket either
    # way, and the 512-token text encode is trivial next to the UNet scan.
    max_length: int = 512
    dtype: str = "float32"


class ClapTextLayer(nn.Module):
    """One post-LN (BERT-style) encoder layer."""

    config: ClapTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, bias: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        b, l, d = x.shape
        head_dim = cfg.hidden_size // cfg.num_heads
        q = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="query")(x)
        k = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="key")(x)
        v = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="value")(x)
        q = q.reshape(b, l, cfg.num_heads, head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(b, l, cfg.num_heads, head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(b, l, cfg.num_heads, head_dim).transpose(0, 2, 1, 3)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(head_dim)) + bias
        weights = nn.softmax(scores, axis=-1).astype(self.dtype)
        attn = jnp.einsum("bhqk,bhkd->bhqd", weights, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, l, d)
        attn = nn.Dense(cfg.hidden_size, dtype=self.dtype,
                        name="attn_out")(attn)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="attn_norm")(x + attn).astype(self.dtype)
        h = nn.Dense(cfg.intermediate_size, dtype=self.dtype,
                     name="intermediate")(x)
        h = nn.gelu(h, approximate=False)      # RoBERTa: exact (erf) gelu
        h = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="output")(h)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                            name="out_norm")(x + h).astype(self.dtype)


class ClapTextEncoder(nn.Module):
    """(B, L) ids -> (sequence (B, L, hidden), text_embeds (B, proj_dim)).

    ``text_embeds`` is the projection-head output AudioLDM conditions on
    (the caller L2-normalizes, matching the serving pipeline's
    ``F.normalize``). ``attention_mask=None`` derives the mask from
    ``input_ids != pad_token_id`` — the RoBERTa padding convention.
    """

    config: ClapTextConfig

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.config.dtype)

    @nn.compact
    def __call__(self, input_ids: jnp.ndarray,
                 attention_mask: jnp.ndarray | None = None,
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
        cfg = self.config
        dtype = self.dtype
        if attention_mask is None:
            attention_mask = (input_ids != cfg.pad_token_id)
        mask = attention_mask.astype(jnp.int32)

        # RoBERTa position ids: pad rows pinned at padding_idx, real tokens
        # counted from padding_idx + 1 (create_position_ids_from_input_ids)
        positions = jnp.cumsum(mask, axis=1) * mask + cfg.pad_token_id

        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=dtype,
                     name="word_embeddings")(input_ids)
        x = x + nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                         dtype=dtype, name="position_embeddings")(positions)
        x = x + nn.Embed(1, cfg.hidden_size, dtype=dtype,
                         name="token_type_embeddings")(
            jnp.zeros_like(input_ids))
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="embed_norm")(x).astype(dtype)

        # additive key mask, broadcast over (B, heads, Q, K)
        bias = jnp.where(mask[:, None, None, :] > 0, 0.0,
                         jnp.finfo(jnp.float32).min)
        for i in range(cfg.num_layers):
            x = ClapTextLayer(cfg, dtype, name=f"layer_{i}")(x, bias)

        pooled = jnp.tanh(nn.Dense(cfg.hidden_size, dtype=dtype,
                                   name="pooler")(x[:, 0]))
        proj = nn.Dense(cfg.projection_dim, dtype=dtype,
                        name="proj1")(pooled)
        proj = nn.Dense(cfg.projection_dim, dtype=dtype,
                        name="proj2")(nn.relu(proj))
        return x.astype(jnp.float32), proj.astype(jnp.float32)
