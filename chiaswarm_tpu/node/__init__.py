"""Node runtime: worker daemon, hive protocol, dispatch, artifacts, settings."""
