"""Node runtime: worker daemon, hive protocol, dispatch, artifacts,
settings, fault tolerance (resilience) and the chaos harness."""
