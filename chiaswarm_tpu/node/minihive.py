"""MiniHive: a lease-tracking in-process hive — fleet-scale fault seams.

The PR-2 :class:`~chiaswarm_tpu.node.chaos.ChaoticHive` proves ONE worker
is fault-contained; the failure mode that dominates real TPU fleets — a
whole worker preempted mid-job — needs the hive side of the contract.
This module grows the chaos hive into a real mini-hive with the standard
lease-and-redeliver recipe of large-scale serving systems:

- **Leases**: every job handed out by ``GET /api/work`` is leased to the
  polling worker for ``lease_s`` seconds. Polls and ``POST
  /api/heartbeat`` calls from the holder extend its leases.
- **Redelivery**: an expired lease (worker died, was partitioned, or
  went silent) puts the job back in the queue with an incremented
  attempt count and the late worker on the job's excluded list, so the
  next poll hands it to a DIFFERENT worker.
- **Resume state**: heartbeats carry the worker's latest step-boundary
  checkpoint per in-flight job (node/resilience.py::CheckpointSpool,
  serving/stepper.py lane snapshots). The redelivered job rides out
  with a ``resume`` field, so the surviving worker splices it into a
  lane at step k instead of restarting at step 0.
- **Exactly-once completion**: the first success-or-error envelope for
  a job id settles it; any later upload — the classic race of a
  presumed-dead worker's stale result against the redelivered copy — is
  acked idempotently (``{"status": "duplicate"}``) and never counted
  twice. Chip time is salvaged whichever copy lands first.
- **Redispatch by error kind**: envelopes whose ``error_kind`` is in
  :data:`~chiaswarm_tpu.node.resilience.REDISPATCH_KINDS`
  (``model_unavailable``, ``quarantined``) are NOT settled: the job
  requeues with the refusing worker excluded. This resolves the
  reference-parity taxonomy tension ROADMAP carried since PR 2 — a
  node-local model-unavailable is a routing problem, not a fatal error.

- **Durability** (swarmdurable, ISSUE 14): with a
  :class:`~chiaswarm_tpu.node.hivelog.HiveJournal` attached, every
  state transition above is journaled (write-ahead, fsync'd batch per
  request) and a killed hive rebuilds its queue, lease books,
  checkpoints, and flight records by deterministic replay
  (:meth:`MiniHive.recover`). Each attachment bumps a monotone
  ``hive_epoch`` stamped into every granted payload and echoed on
  uploads: a recovered hive accepts a pre-crash grant's late upload
  exactly once (counted as epoch salvage), dedupes against the
  journaled settle set, and rejects a stale worker's heartbeat via the
  epoch handshake. Without a journal nothing is stamped — the wire
  shape stays byte-compatible with the reference contract (gated by
  test).

Chaos composition: all of :class:`ChaoticHive`'s scripted poll/result
faults still apply, plus :meth:`partition`/:meth:`heal` cut one worker
off from every endpoint (its requests see connection resets) — the
deterministic stand-in for a network partition outliving the lease —
and :func:`kill_hive`/:func:`restart_hive` SIGKILL the hive itself
mid-flight and bring it back from its journal on the same port.

Like the chaos harness, this is product code: operators smoke a
multi-worker build against one MiniHive in one process
(tests/test_minihive.py is the executable spec), and the ROADMAP's
fleet-scale load harness builds on the same queue.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Iterable

from chiaswarm_tpu.node.chaos import ChaoticHive
from chiaswarm_tpu.node.hivelog import HIVE_EPOCH_KEY, HiveJournal
from chiaswarm_tpu.node.resilience import REDISPATCH_KINDS, classify_result
from chiaswarm_tpu.obs import flight as obs_flight
from chiaswarm_tpu.obs.metrics import Registry

log = logging.getLogger("chiaswarm.minihive")


def result_error_kind(result: dict[str, Any]) -> str | None:
    """The ``error_kind`` an envelope carries, or None for a success.

    Delegates to the worker-side classifier so hive and worker can never
    disagree about what counts as an error envelope."""
    kind = classify_result(result)
    return None if kind == "ok" else kind


class MiniHive(ChaoticHive):
    """In-process hive with leases, heartbeats, redelivery, and
    exactly-once completion. See the module docstring for semantics.

    ``lease_s``             seconds a handed-out job stays leased without
                            a heartbeat/poll from its holder
    ``max_attempts``        delivery attempts per job before it is
                            abandoned (parked in ``self.abandoned``)
    ``max_jobs_per_poll``   cap per poll (0 = reference semantics: the
                            first poller drains the queue)
    ``clock``               injectable monotonic clock for tests
    """

    def __init__(self, poll_faults: Iterable[str] | None = None,
                 result_faults: dict[str, Iterable[str]] | None = None,
                 delay_s: float = 0.05, *,
                 lease_s: float = 30.0,
                 max_attempts: int = 4,
                 max_jobs_per_poll: int = 0,
                 redispatch_kinds: frozenset[str] = REDISPATCH_KINDS,
                 journal: HiveJournal | None = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        super().__init__(poll_faults, result_faults, delay_s)
        self.lease_s = float(lease_s)
        self.max_attempts = max(1, int(max_attempts))
        self.max_jobs_per_poll = max(0, int(max_jobs_per_poll))
        self.redispatch_kinds = frozenset(redispatch_kinds)
        self._clock = clock
        # job id -> {job, worker, expires_at, attempt}
        self.leases: dict[str, dict[str, Any]] = {}
        self.attempts: dict[str, int] = {}
        self.excluded: dict[str, set[str]] = {}
        self.checkpoints: dict[str, dict[str, Any]] = {}
        self.completed: dict[str, dict[str, Any]] = {}
        self.duplicate_results: list[dict[str, Any]] = []
        self.abandoned: list[str] = []
        # first-submission stamp per job id: every delivery carries the
        # job's total queue age as ``queued_s`` so a worker's overload
        # controller (ISSUE 9, node/overload.py) can count hive-side
        # waiting against the deadline — under overload the backlog
        # lives HERE, not in the worker's bounded local queue
        self.submitted_at: dict[str, float] = {}
        self.known_workers: set[str] = set()
        self.worker_seen: dict[str, float] = {}  # last poll/heartbeat
        self.partitioned: set[str] = set()
        # swarmsight (ISSUE 13): the per-job flight recorder (trace
        # context out, span digests in, hive-clock event timeline,
        # settle-time budget attribution) + the fleet plane — latest
        # per-worker metric snapshot pushed by heartbeats, and the
        # hive's own observed-arrival EWMA (the item-5 autoscaler's
        # demand signal)
        self.flights = obs_flight.FlightRecorder()
        self.fleet: dict[str, dict[str, Any]] = {}
        self._submit_rate = obs_flight.RateEwma(window_s=30.0)
        # swarmplan (ISSUE 19): per-model arrival EWMAs — the demand
        # split the planner's placement plan ranks models by (the
        # fleet-level twin of the residency ledger's per-model EWMA)
        self._model_rates: dict[str, obs_flight.RateEwma] = {}
        # an attached FleetPlanner (node/planner.py); None keeps exact
        # wire parity with the pre-planner contract — no /api/plan
        # body, no placement key on heartbeat acks
        self.planner: Any = None
        self.last_plan: dict[str, Any] | None = None
        self._app.router.add_post("/api/heartbeat", self._heartbeat)
        self._app.router.add_get("/api/stats", self._stats_endpoint)
        self._app.router.add_get("/api/fleet", self._fleet_endpoint)
        self._app.router.add_get("/api/plan", self._plan_endpoint)
        self._app.router.add_get("/api/flight", self._flights_endpoint)
        self._app.router.add_get("/api/flight/{job_id}",
                                 self._flight_endpoint)
        # per-hive registry (hermetic, like the worker's): the snapshot
        # is the accounting tests reconcile against the result lists
        self.metrics = Registry()
        m = self.metrics
        self._leases_granted = m.counter(
            "chiaswarm_hive_leases_granted_total",
            "jobs handed out under a lease")
        self._leases_expired = m.counter(
            "chiaswarm_hive_leases_expired_total",
            "leases that expired without a settling upload")
        self._redelivered = m.counter(
            "chiaswarm_hive_jobs_redelivered_total",
            "expired-lease jobs requeued for another worker")
        self._redispatched = m.counter(
            "chiaswarm_hive_jobs_redispatched_total",
            "jobs requeued because a worker refused them", ("kind",))
        self._completed = m.counter(
            "chiaswarm_hive_results_completed_total",
            "results settled exactly once")
        self._duplicates = m.counter(
            "chiaswarm_hive_results_duplicate_total",
            "late/racing uploads acked idempotently, never counted")
        self._heartbeats = m.counter(
            "chiaswarm_hive_heartbeats_total", "heartbeats received")
        self._ckpt_stored = m.counter(
            "chiaswarm_hive_checkpoints_stored_total",
            "resume checkpoints accepted from lease holders")
        self._ckpt_stale = m.counter(
            "chiaswarm_hive_checkpoints_stale_total",
            "checkpoints rejected because the sender lost the lease")
        self._abandoned = m.counter(
            "chiaswarm_hive_jobs_abandoned_total",
            "jobs parked after exhausting max_attempts deliveries")
        self._salvaged = m.counter(
            "chiaswarm_hive_jobs_salvaged_total",
            "abandoned jobs settled late by a straggler upload "
            "(chip time recovered; the job leaves the abandoned list)")
        # swarmdurable (ISSUE 14): journal / recovery / epoch families
        self._recoveries = m.counter(
            "chiaswarm_hive_recoveries_total",
            "times this hive state was rebuilt by journal replay")
        self._epoch_salvaged = m.counter(
            "chiaswarm_hive_epoch_salvage_total",
            "pre-epoch uploads (granted before a hive restart) settled "
            "exactly once after recovery — billing parity across crashes")
        self._stale_epoch_beats = m.counter(
            "chiaswarm_hive_stale_epoch_heartbeats_total",
            "heartbeats rejected by the epoch handshake (sender still "
            "on a pre-restart epoch)")
        self._epoch_gauge = m.gauge(
            "chiaswarm_hive_epoch",
            "current hive epoch (0 = journaling off; bumps on every "
            "journal attach / recovery)")
        self._journal_records = m.counter(
            "chiaswarm_hive_journal_records_total",
            "state transitions made durable in the write-ahead log")
        self._journal_fsyncs = m.counter(
            "chiaswarm_hive_journal_fsyncs_total",
            "batched journal commits fsync'd to disk")
        self._journal_parked = m.counter(
            "chiaswarm_hive_journal_parked_total",
            "torn/corrupt journal tails parked as .bad at recovery")
        self._journal_snapshots = m.counter(
            "chiaswarm_hive_journal_snapshots_total",
            "compaction snapshots written (segments pruned behind them)")
        # journal OFF (the default) stamps nothing: wire parity with the
        # reference contract. recover() attaches with the replayed epoch
        # instead of coming through here.
        self.journal: HiveJournal | None = None
        self.hive_epoch = 0
        if journal is not None:
            self.journal = journal
            if journal.last_seq > 0:
                # attaching to a journal with prior life (e.g. a torn
                # tail from a crash): run the repairing replay FIRST so
                # this epoch never appends after bytes a future
                # recovery would park — hivelog's documented invariant
                journal.replay()
            self.hive_epoch = journal.stored_epoch() + 1
            journal.begin_epoch(self.hive_epoch, t=self._clock())
            self._epoch_gauge.set(self.hive_epoch)

    def submit(self, job: dict[str, Any]) -> None:
        job_id = str(job.get("id"))
        now = self._clock()
        self.submitted_at.setdefault(job_id, now)
        # flight record opens at submit (idempotent for resubmitted
        # ids); the observed-arrival EWMA feeds /api/fleet. With a
        # journal, the trace id rides the submit record so a recovered
        # hive reopens the SAME trace.
        trace_id = self.flights.trace_id_of(job_id)
        if trace_id is None and self.journal is not None:
            trace_id = obs_flight.new_trace_id()
        self.flights.open(job_id, job, t=now, trace_id=trace_id)
        self._submit_rate.note(now)
        self._note_model_arrival(job, now)
        self._journal("submit", id=job_id, t=now, job=job,
                      trace_id=trace_id)
        super().submit(job)
        self._journal_commit()

    def _note_model_arrival(self, job: dict[str, Any], now: float) -> None:
        model = str(job.get("model_name") or "")
        if model:
            self._model_rates.setdefault(
                model,
                obs_flight.RateEwma(window_s=30.0)).note(now)

    # ---- the write-ahead log (swarmdurable, ISSUE 14) -------------------

    def _journal(self, ev: str, **fields: Any) -> None:
        if self.journal is not None:
            self.journal.append(ev, **fields)

    def _journal_commit(self) -> None:
        """Make the current batch durable (one fsync); the caller acks
        its request only after this returns. Auto-compacts once the
        tail outgrows ``CHIASWARM_HIVE_JOURNAL_COMPACT_EVERY``."""
        if self.journal is None:
            return
        self.journal.commit()
        if self.journal.maybe_compact():
            self.compact()

    def compact(self):
        """Write a compaction snapshot of the full hive state and prune
        the journal segments it covers. replay(snapshot + tail) must
        equal replay(full log) — gated by tests/test_durability.py."""
        if self.journal is None:
            return None
        return self.journal.write_snapshot(
            self.dump_state(), epoch=self.hive_epoch, t=self._clock())

    def record_plan(self, decision: dict[str, Any]) -> None:
        """Make one planner decision durable (swarmplan, ISSUE 19): a
        ``plan`` journal transition plus a flight note on the
        ``fleet-planner`` pseudo record. A recovered hive replays the
        newest decision into :attr:`last_plan`, which a re-attached
        planner seeds its cooldown clocks and placement from — intent
        survives the crash without being actuated twice."""
        t = float(decision.get("at_s") or self._clock())
        self.last_plan = dict(decision)
        self.flights.note("fleet-planner", "plan", t=t,
                          direction=decision.get("direction"),
                          reason=decision.get("reason"),
                          target=decision.get("target"),
                          actual=decision.get("actual"),
                          drain=list(decision.get("drain") or ()))
        self._journal("plan", t=t, plan=dict(decision))
        self._journal_commit()

    # ---- chaos controls -------------------------------------------------

    def partition(self, worker_name: str) -> None:
        """Cut ``worker_name`` off: its polls, heartbeats, and uploads
        all see dropped connections until :meth:`heal`. Its leases expire
        on schedule — the deterministic worker-vanished fault."""
        self.partitioned.add(str(worker_name))

    def heal(self, worker_name: str) -> None:
        self.partitioned.discard(str(worker_name))

    def _worker_reachable(self, worker_name: str) -> bool:
        return worker_name not in self.partitioned

    # ---- leases ---------------------------------------------------------

    def sweep(self) -> list[str]:
        """Expire overdue leases; requeue (or abandon) their jobs.
        Runs on every poll/heartbeat/upload — callers never wait on a
        background timer — and returns the redelivered job ids."""
        now = self._clock()
        redelivered: list[str] = []
        for job_id in [j for j, lease in self.leases.items()
                       if now >= lease["expires_at"]]:
            lease = self.leases.pop(job_id)
            self._leases_expired.inc()
            self.excluded.setdefault(job_id, set()).add(lease["worker"])
            self.flights.note(job_id, "lease_expired", t=now,
                              worker=lease["worker"],
                              attempt=lease["attempt"])
            self._journal("lease_expired", id=job_id, t=now,
                          worker=lease["worker"],
                          attempt=lease["attempt"])
            if self.attempts.get(job_id, 0) >= self.max_attempts:
                log.error("job %s abandoned after %d deliveries",
                          job_id, self.attempts.get(job_id, 0))
                self.abandoned.append(job_id)
                self._abandoned.inc()
                self.flights.note(job_id, "abandoned", t=now,
                                  attempts=self.attempts.get(job_id, 0))
                self._journal("abandoned", id=job_id, t=now,
                              attempts=self.attempts.get(job_id, 0))
                # GC like the settle path does: an abandoned job's
                # latent-sized checkpoint blob is never resumed again
                self.checkpoints.pop(job_id, None)
                continue
            log.warning("lease for job %s (worker %s) expired; "
                        "redelivering (attempt %d done)", job_id,
                        lease["worker"], lease["attempt"])
            self.pending_jobs.append(lease["job"])
            self._redelivered.inc()
            self.flights.note(job_id, "redelivered", t=now)
            self._journal("redelivered", id=job_id, t=now)
            redelivered.append(job_id)
        self._journal_commit()  # no-op when nothing expired
        return redelivered

    def expire_worker(self, worker_name: str) -> list[str]:
        """Declare ``worker_name`` dead NOW: every lease it holds expires
        immediately and redelivers on this very sweep, without waiting
        out ``lease_s``. The TPU-fleet analog is a preemption notice —
        the scheduler knows the node is gone before the lease clock
        does. Pairs with :meth:`partition` (cut it off first, so nothing
        it still uploads can race ahead of the revocation)."""
        for lease in self.leases.values():
            if lease["worker"] == worker_name:
                lease["expires_at"] = float("-inf")
        return self.sweep()

    def _extend_leases(self, worker_name: str) -> None:
        expiry = self._clock() + self.lease_s
        for lease in self.leases.values():
            if lease["worker"] == worker_name:
                lease["expires_at"] = expiry

    def live_workers(self) -> set[str]:
        """Workers seen (poll or heartbeat) within the last two lease
        periods. The starvation valve compares exclusion against THIS
        set, not ``known_workers``: a dead worker stays known forever,
        and waiting for its refusal would strand a job that every
        surviving worker has already refused."""
        horizon = self._clock() - 2 * self.lease_s
        return {name for name, seen in self.worker_seen.items()
                if seen >= horizon}

    def lease_holder(self, job_id: Any) -> str | None:
        lease = self.leases.get(str(job_id))
        return None if lease is None else lease["worker"]

    def leased_ids(self, worker_name: str) -> list[str]:
        return sorted(job_id for job_id, lease in self.leases.items()
                      if lease["worker"] == worker_name)

    # ---- handout (ChaoticHive seam) ------------------------------------

    def _take_jobs(self, worker_name: str) -> list[dict[str, Any]]:
        self.known_workers.add(worker_name)
        self.worker_seen[worker_name] = self._clock()
        self.sweep()
        self._extend_leases(worker_name)  # a poll proves liveness
        live = self.live_workers()
        handed: list[dict[str, Any]] = []
        remaining: list[dict[str, Any]] = []
        for job in self.pending_jobs:
            job_id = str(job.get("id"))
            if job_id in self.completed:
                # settled while queued (a late upload raced ahead of
                # this redelivery): drop the copy, never re-execute
                continue
            excluded = self.excluded.get(job_id, set())
            # starvation valve: once every LIVE worker has refused or
            # lost this job, exclusion has nothing left to route around
            # (a dead worker must not hold the valve shut forever)
            if worker_name in excluded and not live <= excluded:
                remaining.append(job)
                continue
            if self.max_jobs_per_poll and \
                    len(handed) >= self.max_jobs_per_poll:
                remaining.append(job)
                continue
            handed.append(job)
        self.pending_jobs = remaining
        out: list[dict[str, Any]] = []
        for job in handed:
            job_id = str(job.get("id"))
            attempt = self.attempts.get(job_id, 0) + 1
            self.attempts[job_id] = attempt
            self.leases[job_id] = {
                "job": job, "worker": worker_name, "attempt": attempt,
                "expires_at": self._clock() + self.lease_s,
            }
            self._leases_granted.inc()
            # the wire copy carries its lineage + resume state; the
            # queued original stays pristine for the next redelivery
            payload = dict(job)
            payload["attempt"] = attempt
            submitted = self.submitted_at.get(job_id)
            if submitted is not None:
                # total time since FIRST submission (across attempts):
                # the worker's admission estimator charges this against
                # the job's deadline budget
                payload["queued_s"] = round(
                    max(0.0, self._clock() - submitted), 4)
            checkpoint = self.checkpoints.get(job_id)
            if checkpoint is not None:
                payload["resume"] = checkpoint
            # swarmsight (ISSUE 13): every delivery carries the job's
            # trace context — trace_id for the whole lifetime, a span
            # id for THIS attempt — and the grant lands on the flight
            # record's hive-clock timeline
            resume_step = None
            if isinstance(checkpoint, dict):
                try:
                    resume_step = int(checkpoint.get("step") or 0) or None
                except (TypeError, ValueError):
                    resume_step = None
            # swarmdurable (ISSUE 14): a journaled hive stamps its epoch
            # into the payload (the worker echoes it on upload) and
            # makes the grant durable BEFORE the payload leaves;
            # without a journal neither key exists — wire parity.
            epoch = self.hive_epoch if self.journal is not None else None
            if epoch is not None:
                payload[HIVE_EPOCH_KEY] = epoch
            payload[obs_flight.TRACE_CTX_KEY] = self.flights.grant(
                job_id, attempt=attempt, worker=worker_name,
                t=self._clock(), queued_s=payload.get("queued_s"),
                resume_step=resume_step, epoch=epoch)
            self._journal("grant", id=job_id, t=self._clock(),
                          attempt=attempt, worker=worker_name,
                          queued_s=payload.get("queued_s"),
                          resume_step=resume_step, epoch=epoch)
            out.append(payload)
        self._journal_commit()
        return out

    # ---- settling (ChaoticHive seam) ------------------------------------

    def _record_result(self, result: dict[str, Any],
                       worker_name: str) -> dict[str, Any]:
        self.sweep()
        job_id = str(result.get("id"))
        # swarmdurable (ISSUE 14): the worker echoes the grant's epoch
        # stamp; popped like the digest so stored results keep their
        # historical shape. A pre-epoch stamp on a settling upload is
        # the crash-straddling case — counted as epoch salvage below.
        upload_epoch = result.pop(HIVE_EPOCH_KEY, None)
        try:
            upload_epoch = (None if upload_epoch is None
                            else int(upload_epoch))
        except (TypeError, ValueError):
            upload_epoch = None
        # swarmsight (ISSUE 13): the worker's span digest is popped OFF
        # the envelope into the flight record — every upload's, even a
        # duplicate's or a refusal's (they are attempts in the story) —
        # so stored/settled results keep their historical shape
        digest = result.pop(obs_flight.SPAN_DIGEST_KEY, None)
        if digest is not None:
            self.flights.add_digest(job_id, digest)
            self._journal("digest", id=job_id, t=self._clock(),
                          digest=digest)
        if job_id in self.completed:
            # the redelivery race settled already: ack idempotently so
            # the uploader stops retrying, but never double-count —
            # journal-backed across epochs: a recovered hive's replayed
            # settle set dedupes pre-crash grants' retried uploads too
            self.duplicate_results.append(result)
            self._duplicates.inc()
            self.flights.note(job_id, "duplicate_upload",
                              t=self._clock(), worker=worker_name)
            self._journal("duplicate", id=job_id, t=self._clock(),
                          worker=worker_name)
            self._journal_commit()
            log.info("duplicate result for %s from %s acked (job already "
                     "settled)", job_id, worker_name or "unknown")
            return {"status": "duplicate"}
        kind = result_error_kind(result)
        lease = self.leases.get(job_id)
        # does the refuser still hold the lease? A refusal can also land
        # LATE — after its lease expired (sweep already requeued the
        # job) or after redelivery to another worker (the job is in
        # flight elsewhere). In both cases there is nothing to requeue,
        # but the refusal still must not settle the job as an error.
        held_by_refuser = lease is not None and \
            (not worker_name or lease["worker"] == worker_name)
        if (kind in self.redispatch_kinds
                and not result.get("fatal_error")
                and job_id not in self.abandoned
                and (self.attempts.get(job_id, 0) < self.max_attempts
                     or not held_by_refuser)):
            # THIS worker cannot serve the model; another may. Requeue
            # with the refuser excluded instead of settling the error.
            # A refusal from a worker that no longer holds the lease
            # never settles, even at max_attempts — the live copy
            # (queued or running elsewhere) owns the outcome; only the
            # current holder's refusal on the final attempt is final.
            refuser = worker_name or (lease["worker"] if lease else "")
            if refuser:
                self.excluded.setdefault(job_id, set()).add(refuser)
            if held_by_refuser:
                self.leases.pop(job_id, None)
                self.pending_jobs.append(lease["job"])
            self._redispatched.inc(kind=kind)
            self.flights.note(job_id, "redispatched", t=self._clock(),
                              kind=kind, worker=refuser or None)
            self._journal("redispatched", id=job_id, t=self._clock(),
                          kind=kind, worker=refuser or None,
                          requeued=bool(held_by_refuser))
            self._journal_commit()
            log.warning("job %s refused by %s (%s); redispatching with "
                        "the refuser excluded", job_id,
                        refuser or "unknown", kind)
            return {"status": "requeued", "kind": kind}
        # exactly-once settle: first envelope wins, whoever sent it —
        # even a worker whose lease already expired (salvaged chip time).
        # Withdraw any queued redelivery copy too: without this, a late
        # upload landing after its lease expired would leave the requeued
        # copy to burn a full re-execution on another worker.
        if job_id in self.abandoned:
            # a straggler upload for a job policy already gave up on:
            # the work EXISTS, so the job settles and leaves the
            # abandoned list — one job must never read as both
            # abandoned AND completed (the reconciliation invariant
            # tests/test_minihive.py holds at harness scale)
            self.abandoned.remove(job_id)
            self._salvaged.inc()
            self.flights.note(job_id, "salvaged", t=self._clock(),
                              worker=worker_name)
            self._journal("salvaged", id=job_id, t=self._clock(),
                          worker=worker_name)
            log.warning("job %s salvaged by a straggler upload after "
                        "abandonment", job_id)
        self.completed[job_id] = result
        self.results.append(result)
        self.result_event.set()
        self.leases.pop(job_id, None)
        self.checkpoints.pop(job_id, None)  # hive-side checkpoint GC
        self.pending_jobs = [j for j in self.pending_jobs
                             if str(j.get("id")) != job_id]
        self._completed.inc()
        # epoch salvage (ISSUE 14): a settling upload for a grant from a
        # PREVIOUS epoch — work that straddled the hive crash lands
        # exactly once, never double-counted (billing parity)
        from_epoch = None
        if upload_epoch is not None and self.journal is not None \
                and upload_epoch < self.hive_epoch:
            from_epoch = upload_epoch
            self._epoch_salvaged.inc()
            self.flights.note(job_id, "epoch_salvage", t=self._clock(),
                              from_epoch=from_epoch,
                              epoch=self.hive_epoch)
            log.warning("job %s settled by a pre-epoch upload (granted "
                        "in epoch %d, settled in epoch %d)", job_id,
                        from_epoch, self.hive_epoch)
        # the exactly-once settle closes the flight record and computes
        # its deadline-budget attribution (obs/flight.py)
        settle_attempt = None
        if isinstance(digest, dict):
            # a LATE upload can settle attempt 1 while attempt 2 is in
            # flight: the digest knows which attempt's work this is.
            # Coerced defensively — the field crossed the wire from a
            # possibly version-skewed worker, and a garbage value must
            # degrade to the lease books, never crash an already-
            # counted settle into an unsettled flight record
            try:
                settle_attempt = int(digest.get("attempt"))
            except (TypeError, ValueError):
                settle_attempt = None
        settle_worker = worker_name or str(result.get("worker_name") or "")
        resolved_attempt = (settle_attempt if settle_attempt is not None
                            else self.attempts.get(job_id))
        self.flights.settle(
            job_id, t=self._clock(), worker=settle_worker,
            outcome=kind or "ok", attempt=resolved_attempt,
            epoch=self.hive_epoch if self.journal is not None else None)
        # write-ahead: the settle is durable before the ack leaves, so a
        # crash between counting and answering can never double-settle
        self._journal("settled", id=job_id, t=self._clock(),
                      worker=settle_worker, outcome=kind or "ok",
                      attempt=resolved_attempt,
                      epoch=self.hive_epoch if self.journal is not None
                      else None, from_epoch=from_epoch)
        self._journal_commit()
        return {"status": "ok"}

    # ---- heartbeats ------------------------------------------------------

    async def _heartbeat(self, request):
        from aiohttp import web

        try:
            payload = await request.json()
        except Exception:
            return web.Response(status=400, text="unparseable heartbeat")
        worker_name = str(payload.get("worker_name") or "")
        if not self._worker_reachable(worker_name):
            request.transport.close()
            raise ConnectionResetError("chaos: partitioned heartbeat")
        self.known_workers.add(worker_name)
        self.worker_seen[worker_name] = self._clock()
        self.sweep()
        self._heartbeats.inc()
        # fleet plane (ISSUE 13): heartbeats may push a per-worker
        # metric snapshot (arrival EWMAs, lane occupancy, chips in
        # service, residency ledger, overload state) — stored latest-
        # wins and aggregated at GET /api/fleet
        metrics = payload.get("metrics")
        if isinstance(metrics, dict):
            self.fleet[worker_name] = {"at": self._clock(),
                                       "metrics": metrics}
        # epoch handshake (swarmdurable, ISSUE 14): a beat claiming a
        # PRE-restart epoch is stale — its lease claims and checkpoint
        # pushes describe a hive that no longer exists. Reject the
        # whole beat (no extension, no custody), report every claimed
        # job lost, and hand back the current epoch so the worker
        # re-registers; its next beat (new epoch) is served normally.
        if self.journal is not None:
            claimed = payload.get("hive_epoch")
            try:
                claimed = None if claimed is None else int(claimed)
            except (TypeError, ValueError):
                claimed = None
            if claimed is not None and claimed != self.hive_epoch:
                self._stale_epoch_beats.inc()
                stale_jobs = payload.get("jobs") or []
                for entry in stale_jobs:
                    if entry.get("checkpoint") is not None:
                        self._ckpt_stale.inc()
                log.warning("stale-epoch heartbeat from %s (claimed %s, "
                            "current %d); rejecting its lease claims",
                            worker_name, claimed, self.hive_epoch)
                return web.json_response({
                    "status": "stale_epoch",
                    "hive_epoch": self.hive_epoch,
                    "lost": [str(entry.get("id"))
                             for entry in stale_jobs],
                })
        expiry = self._clock() + self.lease_s
        lost: list[str] = []
        for entry in payload.get("jobs") or []:
            job_id = str(entry.get("id"))
            lease = self.leases.get(job_id)
            if lease is None or lease["worker"] != worker_name:
                settled = self.completed.get(job_id)
                if settled is not None and \
                        settled.get("worker_name") in (worker_name, "", None):
                    # the sender's OWN upload just raced this beat: NOT
                    # a lost lease — its ack path clears the in-flight
                    # entry, and counting it would show phantom lease
                    # churn on every healthy run. (Settled by a DIFFERENT
                    # worker still reports lost below: the sender is
                    # burning chip time on a finished job.)
                    continue
                # the lease moved on (expired + redelivered): tell the
                # sender so it can stop burning chip time on it; a stale
                # checkpoint must NOT shadow the new holder's progress
                lost.append(job_id)
                if entry.get("checkpoint") is not None:
                    self._ckpt_stale.inc()
                continue
            lease["expires_at"] = expiry
            checkpoint = entry.get("checkpoint")
            if checkpoint is not None:
                self.checkpoints[job_id] = checkpoint
                self._ckpt_stored.inc()
                # checkpoint marker on the flight timeline: the worker
                # only re-pushes on change, so this is progress, not
                # heartbeat noise. Custody is journaled — a recovered
                # hive redelivers WITH this resume state, which is the
                # whole point of pushing it here.
                step = None
                if isinstance(checkpoint, dict):
                    step = checkpoint.get("step")
                self.flights.note(job_id, "checkpoint", t=self._clock(),
                                  worker=worker_name, step=step)
                self._journal("checkpoint", id=job_id, t=self._clock(),
                              worker=worker_name, checkpoint=checkpoint)
        self._journal_commit()
        ack: dict[str, Any] = {"status": "ok", "lost": lost}
        if self.journal is not None:
            ack["hive_epoch"] = self.hive_epoch
        # swarmplan (ISSUE 19): piggyback the plan's model assignment
        # for THIS worker on the ack — the worker's residency ledger
        # warms hinted models on idle polls, so placement shifts ahead
        # of the traffic instead of behind it. No planner (or no
        # assignment) adds no key: exact wire parity with the
        # pre-planner heartbeat contract.
        if self.planner is not None:
            placement = self.planner.placement_for(worker_name)
            if placement:
                ack["placement"] = list(placement)
        return web.json_response(ack)

    # ---- crash-safe recovery (swarmdurable, ISSUE 14) -------------------

    #: counters that represent journaled state transitions — dumped into
    #: compaction snapshots and rebuilt identically by tail replay, so
    #: /api/stats reconciles across restarts. Liveness chatter
    #: (heartbeats, stale rejections) is deliberately NOT here: it is
    #: per-process, not state.
    _DURABLE_COUNTERS = (
        ("leases_granted", "_leases_granted"),
        ("leases_expired", "_leases_expired"),
        ("redelivered", "_redelivered"),
        ("completed", "_completed"),
        ("duplicates", "_duplicates"),
        ("abandoned", "_abandoned"),
        ("salvaged", "_salvaged"),
        ("ckpt_stored", "_ckpt_stored"),
        ("epoch_salvaged", "_epoch_salvaged"),
    )

    @staticmethod
    def _settle_marker(job_id: str, result: dict[str, Any]
                       ) -> dict[str, Any]:
        """Compact dedupe marker for a settled job — what snapshots and
        replay rebuild ``completed`` entries as (full artifact payloads
        never enter the journal; the settle SET is the durable truth)."""
        if result.get("recovered"):
            return dict(result)
        return {"id": job_id,
                "worker_name": str(result.get("worker_name") or ""),
                "outcome": result_error_kind(result) or "ok",
                "recovered": True}

    def _counter_dump(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            name: getattr(self, attr).value()
            for name, attr in self._DURABLE_COUNTERS
        }
        out["redispatched"] = {
            key[0]: value
            for key, value in self._redispatched.series().items()
        }
        return out

    def _counter_restore(self, dump: dict[str, Any]) -> None:
        for name, attr in self._DURABLE_COUNTERS:
            try:
                getattr(self, attr).inc(max(0.0, float(
                    dump.get(name) or 0.0)))
            except (TypeError, ValueError):
                continue
        for kind, value in (dump.get("redispatched") or {}).items():
            try:
                self._redispatched.inc(max(0.0, float(value)), kind=kind)
            except (TypeError, ValueError):
                continue

    def dump_state(self) -> dict[str, Any]:
        """JSON-safe full-state capture for a compaction snapshot.
        Settled results dump as dedupe markers, never artifacts —
        replay(snapshot + tail) must equal replay(full log), which the
        marker normalization here guarantees (both paths rebuild the
        same marker shape)."""
        return {
            "version": 1,
            "pending": [dict(job) for job in self.pending_jobs],
            "issued": list(self.issued_ids),
            "leases": {
                job_id: {"worker": lease["worker"],
                         "attempt": lease["attempt"],
                         "job": dict(lease["job"])}
                for job_id, lease in self.leases.items()
            },
            "attempts": dict(self.attempts),
            "excluded": {job_id: sorted(workers)
                         for job_id, workers in self.excluded.items()},
            "checkpoints": dict(self.checkpoints),
            "completed": {job_id: self._settle_marker(job_id, result)
                          for job_id, result in self.completed.items()},
            "abandoned": list(self.abandoned),
            "submitted_at": dict(self.submitted_at),
            "duplicates": [
                {"id": str(r.get("id")),
                 "worker_name": str(r.get("worker_name") or ""),
                 "recovered": True}
                for r in self.duplicate_results
            ],
            "known_workers": sorted(self.known_workers),
            "counters": self._counter_dump(),
            "flights": self.flights.dump(),
            "last_plan": (None if self.last_plan is None
                          else dict(self.last_plan)),
        }

    def _restore_state(self, state: dict[str, Any],
                       jobs: dict[str, dict[str, Any]]) -> None:
        self.pending_jobs = [dict(job)
                             for job in state.get("pending") or ()]
        self.issued_ids = [str(j) for j in state.get("issued") or ()]
        for job in self.pending_jobs:
            jobs[str(job.get("id"))] = job
        for job_id, entry in (state.get("leases") or {}).items():
            job = dict(entry.get("job") or {})
            jobs[str(job_id)] = job
            self.leases[str(job_id)] = {
                "job": job, "worker": str(entry.get("worker") or ""),
                "attempt": int(entry.get("attempt") or 1),
                "expires_at": float("-inf"),  # recover() re-times these
            }
        self.attempts.update({str(k): int(v) for k, v in
                              (state.get("attempts") or {}).items()})
        for job_id, workers in (state.get("excluded") or {}).items():
            self.excluded[str(job_id)] = {str(w) for w in workers}
        self.checkpoints.update(state.get("checkpoints") or {})
        for job_id, marker in (state.get("completed") or {}).items():
            # one marker per settle, shared between the dedupe map and
            # the upload list — exactly the live _record_result shape,
            # so uploaded_ids() stays exactly-once across restarts
            self.completed[str(job_id)] = marker
            self.results.append(marker)
        self.abandoned.extend(str(j)
                              for j in state.get("abandoned") or ())
        self.submitted_at.update(
            {str(k): float(v)
             for k, v in (state.get("submitted_at") or {}).items()})
        self.duplicate_results.extend(state.get("duplicates") or ())
        self.known_workers.update(
            str(w) for w in state.get("known_workers") or ())
        self._counter_restore(state.get("counters") or {})
        self.flights.restore(state.get("flights") or {})
        plan = state.get("last_plan")
        if isinstance(plan, dict):
            self.last_plan = dict(plan)

    def _apply_journal_event(self, record: dict[str, Any],
                             jobs: dict[str, dict[str, Any]]) -> None:
        """Replay ONE journaled transition into hive state — the exact
        mirror of the live mutation paths, counters included, so a
        recovered /api/stats reconciles with the settle lists."""
        ev = str(record.get("ev") or "")
        t = float(record.get("t") or 0.0)
        job_id = (None if record.get("id") is None
                  else str(record.get("id")))
        if ev == "submit":
            job = dict(record.get("job") or {})
            jobs[job_id] = job
            self.submitted_at.setdefault(job_id, t)
            self.flights.open(job_id, job, t=t,
                              trace_id=record.get("trace_id"))
            self._submit_rate.note(t)
            self._note_model_arrival(job, t)
            self.pending_jobs.append(job)
            self.issued_ids.append(job_id)
        elif ev == "grant":
            attempt = int(record.get("attempt") or 1)
            worker = str(record.get("worker") or "")
            job = jobs.get(job_id)
            if job is None:
                log.warning("journal grant for unknown job %s; skipped",
                            job_id)
                return
            self.attempts[job_id] = attempt
            self.pending_jobs = [j for j in self.pending_jobs
                                 if str(j.get("id")) != job_id]
            self.leases[job_id] = {
                "job": job, "worker": worker, "attempt": attempt,
                "expires_at": t + self.lease_s,
            }
            self.known_workers.add(worker)
            self._leases_granted.inc()
            self.flights.grant(job_id, attempt=attempt, worker=worker,
                               t=t, queued_s=record.get("queued_s"),
                               resume_step=record.get("resume_step"),
                               epoch=record.get("epoch"))
        elif ev == "checkpoint":
            checkpoint = record.get("checkpoint")
            self.checkpoints[job_id] = checkpoint
            self._ckpt_stored.inc()
            step = (checkpoint.get("step")
                    if isinstance(checkpoint, dict) else None)
            self.flights.note(job_id, "checkpoint", t=t,
                              worker=record.get("worker"), step=step)
        elif ev == "lease_expired":
            self.leases.pop(job_id, None)
            self._leases_expired.inc()
            self.excluded.setdefault(job_id, set()).add(
                str(record.get("worker") or ""))
            self.flights.note(job_id, "lease_expired", t=t,
                              worker=record.get("worker"),
                              attempt=record.get("attempt"))
        elif ev == "redelivered":
            job = jobs.get(job_id)
            if job is not None:
                self.pending_jobs.append(job)
            self._redelivered.inc()
            self.flights.note(job_id, "redelivered", t=t)
        elif ev == "abandoned":
            self.abandoned.append(job_id)
            self._abandoned.inc()
            self.checkpoints.pop(job_id, None)
            self.flights.note(job_id, "abandoned", t=t,
                              attempts=record.get("attempts"))
        elif ev == "redispatched":
            kind = str(record.get("kind") or "")
            worker = record.get("worker")
            if worker:
                self.excluded.setdefault(job_id, set()).add(str(worker))
            if record.get("requeued"):
                lease = self.leases.pop(job_id, None)
                if lease is not None:
                    self.pending_jobs.append(lease["job"])
            self._redispatched.inc(kind=kind)
            self.flights.note(job_id, "redispatched", t=t, kind=kind,
                              worker=worker or None)
        elif ev == "duplicate":
            self.duplicate_results.append(
                {"id": job_id,
                 "worker_name": str(record.get("worker") or ""),
                 "recovered": True})
            self._duplicates.inc()
            self.flights.note(job_id, "duplicate_upload", t=t,
                              worker=record.get("worker"))
        elif ev == "salvaged":
            if job_id in self.abandoned:
                self.abandoned.remove(job_id)
            self._salvaged.inc()
            self.flights.note(job_id, "salvaged", t=t,
                              worker=record.get("worker"))
        elif ev == "digest":
            self.flights.add_digest(job_id, record.get("digest"))
        elif ev == "settled":
            worker = str(record.get("worker") or "")
            outcome = str(record.get("outcome") or "ok")
            marker = {"id": job_id, "worker_name": worker,
                      "outcome": outcome, "recovered": True}
            self.completed[job_id] = marker
            self.results.append(marker)
            self.leases.pop(job_id, None)
            self.checkpoints.pop(job_id, None)
            self.pending_jobs = [j for j in self.pending_jobs
                                 if str(j.get("id")) != job_id]
            self._completed.inc()
            if record.get("from_epoch") is not None:
                self._epoch_salvaged.inc()
                self.flights.note(job_id, "epoch_salvage", t=t,
                                  from_epoch=record.get("from_epoch"),
                                  epoch=record.get("epoch"))
            self.flights.settle(job_id, t=t, worker=worker,
                                outcome=outcome,
                                attempt=record.get("attempt"),
                                epoch=record.get("epoch"))
        elif ev == "plan":
            # swarmplan (ISSUE 19): replay the decision into last_plan
            # (newest wins) and re-note the flight timeline — the exact
            # mirror of record_plan, so a re-attached planner seeds its
            # cooldowns from the same intent the dead process journaled
            plan = dict(record.get("plan") or {})
            self.last_plan = plan
            self.flights.note("fleet-planner", "plan", t=t,
                              direction=plan.get("direction"),
                              reason=plan.get("reason"),
                              target=plan.get("target"),
                              actual=plan.get("actual"),
                              drain=list(plan.get("drain") or ()))
        elif ev == "epoch":
            pass  # consumed by recover()'s epoch fold
        else:
            log.warning("unknown journal event %r (seq %s) ignored",
                        ev, record.get("seq"))

    @classmethod
    def recover(cls, journal: HiveJournal, *,
                lease_grace_s: float = 0.0,
                **kwargs: Any) -> "MiniHive":
        """Rebuild a hive from its journal: restore the newest snapshot,
        replay the tail (repairing torn/corrupt records into ``.bad``
        parks), bump the epoch, and re-attach the journal for the new
        life. Pre-crash leases are restored EXPIRED (or with
        ``lease_grace_s``): the workers holding them watched the hive
        die and assumed as much (HiveSession ride-through), so the
        first sweep redelivers those jobs — with their journaled resume
        checkpoints — while any late pre-epoch upload still settles
        exactly once as epoch salvage."""
        kwargs.pop("journal", None)
        hive = cls(**kwargs)
        snapshot, records = journal.replay()
        epoch_seen = journal.stored_epoch()
        jobs: dict[str, dict[str, Any]] = {}
        if snapshot is not None:
            epoch_seen = max(epoch_seen, int(snapshot.get("epoch") or 0))
            hive._restore_state(snapshot.get("state") or {}, jobs)
        for record in records:
            if record.get("ev") == "epoch":
                try:
                    epoch_seen = max(epoch_seen,
                                     int(record.get("epoch") or 0))
                except (TypeError, ValueError):
                    pass
                continue
            try:
                hive._apply_journal_event(record, jobs)
            except Exception:  # one bad record must not lose the rest
                log.exception("journal replay failed on seq %s; record "
                              "skipped", record.get("seq"))
        now = hive._clock()
        expiry = (now + lease_grace_s if lease_grace_s > 0
                  else float("-inf"))
        for lease in hive.leases.values():
            lease["expires_at"] = expiry
        hive.hive_epoch = epoch_seen + 1
        hive.journal = journal
        journal.begin_epoch(hive.hive_epoch, t=now)
        hive._recoveries.inc()
        hive._epoch_gauge.set(hive.hive_epoch)
        # the restart lands on every open story: a stitched flight
        # record shows the epoch bump between its attempts
        for job_id in hive.flights.unsettled_ids():
            hive.flights.note(job_id, "hive_recovered", t=now,
                              epoch=hive.hive_epoch)
        log.warning(
            "hive recovered from journal %s: epoch %d, %d pending, "
            "%d expired lease(s) to redeliver, %d completed marker(s), "
            "%d checkpoint(s), %d abandoned", journal.directory,
            hive.hive_epoch, len(hive.pending_jobs), len(hive.leases),
            len(hive.completed), len(hive.checkpoints),
            len(hive.abandoned))
        return hive

    # ---- observability ---------------------------------------------------

    def stats(self) -> dict[str, Any]:
        """Lease-table view + the counter snapshot — the registry the
        exactly-once tests reconcile against the result lists."""
        self.sweep()
        if self.journal is not None:
            # mirror the WAL's own counters into the registry snapshot
            counters = self.journal.snapshot_counters()
            self._journal_records.set_to(counters["records_written"])
            self._journal_fsyncs.set_to(counters["fsyncs"])
            self._journal_parked.set_to(counters["tails_parked"])
            self._journal_snapshots.set_to(counters["snapshots_written"])
        return {
            "pending": len(self.pending_jobs),
            "leased": {job_id: {"worker": lease["worker"],
                                "attempt": lease["attempt"]}
                       for job_id, lease in self.leases.items()},
            "completed": len(self.completed),
            "duplicates": len(self.duplicate_results),
            "abandoned": list(self.abandoned),
            "checkpoints": sorted(self.checkpoints),
            "hive_epoch": self.hive_epoch,
            "journal": (None if self.journal is None
                        else self.journal.snapshot_counters()),
            "metrics": self.metrics.snapshot(),
            "flights": self.flights.snapshot(),
        }

    def fleet_snapshot(self) -> dict[str, Any]:
        """The ``GET /api/fleet`` aggregate: latest per-worker metric
        snapshots (heartbeat-pushed) plus hive-level queue state and the
        observed-arrival EWMA — the data plane the ROADMAP item-5
        capacity planner consumes (arrival rates, occupancy, chips in
        service, residency, health, all in one place)."""
        now = self._clock()
        live = self.live_workers()
        workers: dict[str, Any] = {}
        for name, entry in sorted(self.fleet.items()):
            workers[name] = dict(
                entry["metrics"],
                age_s=round(max(0.0, now - entry["at"]), 3),
                live=name in live,
                partitioned=name in self.partitioned,
                leased_jobs=len(self.leased_ids(name)))
        # aggregate over LIVE, reachable workers only: a dead worker's
        # last snapshot stays in the per-worker map (debugging), but
        # counting its chips/arrival rate forever would overstate fleet
        # capacity to exactly the autoscaler this plane feeds
        active = {name: w for name, w in workers.items()
                  if w["live"] and not w["partitioned"]}

        def total(key: str) -> float:
            value = sum(float(w.get(key) or 0.0)
                        for w in active.values())
            return round(value, 4)

        return {
            "at_s": round(now, 6),
            "workers": workers,
            "aggregate": {
                "workers_reporting": len(workers),
                "workers_live": len(live),
                "chips_in_service": int(total("chips_in_service")),
                "arrival_rate_rows_s": total("arrival_rate_rows_s"),
                "lane_occupancy_mean": round(
                    total("lane_occupancy") / max(1, len(active)), 4),
                "queue_depth": int(total("queue_depth")),
                "inflight_jobs": int(total("inflight_jobs")),
                "jobs_done": int(total("jobs_done")),
                "jobs_shed": int(total("jobs_shed")),
                "workers_in_brownout": sum(
                    1 for w in active.values()
                    if (w.get("overload") or {}).get("state")
                    == "brownout"),
                "observed_arrival_jobs_s": round(
                    self._submit_rate.rate(now), 4),
                # per-model demand split (swarmplan, ISSUE 19): what
                # the planner's placement plan ranks models by
                "model_arrival_jobs_s": {
                    model: round(rate.rate(now), 4)
                    for model, rate in sorted(self._model_rates.items())},
                "pending_jobs": len(self.pending_jobs),
                "leased_jobs": len(self.leases),
                "completed_jobs": len(self.completed),
                "abandoned_jobs": len(self.abandoned),
            },
        }

    async def _stats_endpoint(self, request):
        from aiohttp import web

        return web.json_response(self.stats())

    async def _fleet_endpoint(self, request):
        from aiohttp import web

        return web.json_response(self.fleet_snapshot())

    async def _plan_endpoint(self, request):
        """``GET /api/plan`` (swarmplan, ISSUE 19): the supervisor
        contract — a real deployment's supervisor polls this and
        converges the fleet on ``decision.target``. 404 when no
        planner is attached (this hive is not autoscaled)."""
        from aiohttp import web

        if self.planner is None:
            return web.json_response({"error": "no planner attached"},
                                     status=404)
        return web.json_response(self.planner.plan_snapshot())

    async def _flights_endpoint(self, request):
        from aiohttp import web

        return web.json_response(dict(self.flights.snapshot(),
                                      jobs=self.flights.job_ids()))

    async def _flight_endpoint(self, request):
        from aiohttp import web

        job_id = request.match_info.get("job_id", "")
        record = self.flights.get(job_id)
        if record is None:
            return web.json_response(
                {"status": "unknown",
                 "error": f"no flight record for job {job_id!r} (evicted "
                          f"or never submitted)"}, status=404)
        return web.json_response(record)


# ---------------------------------------------------------------------------
# hive-side chaos seams (swarmdurable, ISSUE 14)
# ---------------------------------------------------------------------------


async def kill_hive(hive: MiniHive) -> int:
    """SIGKILL the hive in-process: stop serving NOW, mid-whatever.
    In-flight requests see dropped connections; every worker's next
    poll/upload/heartbeat fails (flipping its HiveSession into OUTAGE
    ride-through). The hive OBJECT survives only so the test can read
    what was lost — the recovery contract is that nothing in memory
    matters, only what the journal committed. Returns the port so
    :func:`restart_hive` can come back where the workers are looking."""
    port = await hive.die()
    # detach the journal: the dead object must never append again (a
    # stray sweep()/stats() on it would interleave with the recovered
    # hive's writes), and nothing it buffered uncommitted survives —
    # exactly like a real SIGKILL
    hive.journal = None
    log.warning("hive killed on port %d (in-memory state is now "
                "garbage; the journal is the only survivor)", port)
    return port


async def restart_hive(journal: HiveJournal, *, port: int,
                       hive_cls: type | None = None,
                       lease_grace_s: float = 0.0,
                       **kwargs: Any) -> MiniHive:
    """Bring a killed hive back from its journal ON THE SAME PORT, so
    riding-through workers (whose hive URI is fixed) heal on their next
    poll. ``hive_cls`` lets harnesses restart subclasses (LoadHive)."""
    cls = hive_cls or MiniHive
    hive = cls.recover(journal, lease_grace_s=lease_grace_s, **kwargs)
    await hive.start(port=port)
    return hive
