"""MiniHive: a lease-tracking in-process hive — fleet-scale fault seams.

The PR-2 :class:`~chiaswarm_tpu.node.chaos.ChaoticHive` proves ONE worker
is fault-contained; the failure mode that dominates real TPU fleets — a
whole worker preempted mid-job — needs the hive side of the contract.
This module grows the chaos hive into a real mini-hive with the standard
lease-and-redeliver recipe of large-scale serving systems:

- **Leases**: every job handed out by ``GET /api/work`` is leased to the
  polling worker for ``lease_s`` seconds. Polls and ``POST
  /api/heartbeat`` calls from the holder extend its leases.
- **Redelivery**: an expired lease (worker died, was partitioned, or
  went silent) puts the job back in the queue with an incremented
  attempt count and the late worker on the job's excluded list, so the
  next poll hands it to a DIFFERENT worker.
- **Resume state**: heartbeats carry the worker's latest step-boundary
  checkpoint per in-flight job (node/resilience.py::CheckpointSpool,
  serving/stepper.py lane snapshots). The redelivered job rides out
  with a ``resume`` field, so the surviving worker splices it into a
  lane at step k instead of restarting at step 0.
- **Exactly-once completion**: the first success-or-error envelope for
  a job id settles it; any later upload — the classic race of a
  presumed-dead worker's stale result against the redelivered copy — is
  acked idempotently (``{"status": "duplicate"}``) and never counted
  twice. Chip time is salvaged whichever copy lands first.
- **Redispatch by error kind**: envelopes whose ``error_kind`` is in
  :data:`~chiaswarm_tpu.node.resilience.REDISPATCH_KINDS`
  (``model_unavailable``, ``quarantined``) are NOT settled: the job
  requeues with the refusing worker excluded. This resolves the
  reference-parity taxonomy tension ROADMAP carried since PR 2 — a
  node-local model-unavailable is a routing problem, not a fatal error.

Chaos composition: all of :class:`ChaoticHive`'s scripted poll/result
faults still apply, plus :meth:`partition`/:meth:`heal` cut one worker
off from every endpoint (its requests see connection resets) — the
deterministic stand-in for a network partition outliving the lease.

Like the chaos harness, this is product code: operators smoke a
multi-worker build against one MiniHive in one process
(tests/test_minihive.py is the executable spec), and the ROADMAP's
fleet-scale load harness builds on the same queue.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Iterable

from chiaswarm_tpu.node.chaos import ChaoticHive
from chiaswarm_tpu.node.resilience import REDISPATCH_KINDS, classify_result
from chiaswarm_tpu.obs import flight as obs_flight
from chiaswarm_tpu.obs.metrics import Registry

log = logging.getLogger("chiaswarm.minihive")


def result_error_kind(result: dict[str, Any]) -> str | None:
    """The ``error_kind`` an envelope carries, or None for a success.

    Delegates to the worker-side classifier so hive and worker can never
    disagree about what counts as an error envelope."""
    kind = classify_result(result)
    return None if kind == "ok" else kind


class MiniHive(ChaoticHive):
    """In-process hive with leases, heartbeats, redelivery, and
    exactly-once completion. See the module docstring for semantics.

    ``lease_s``             seconds a handed-out job stays leased without
                            a heartbeat/poll from its holder
    ``max_attempts``        delivery attempts per job before it is
                            abandoned (parked in ``self.abandoned``)
    ``max_jobs_per_poll``   cap per poll (0 = reference semantics: the
                            first poller drains the queue)
    ``clock``               injectable monotonic clock for tests
    """

    def __init__(self, poll_faults: Iterable[str] | None = None,
                 result_faults: dict[str, Iterable[str]] | None = None,
                 delay_s: float = 0.05, *,
                 lease_s: float = 30.0,
                 max_attempts: int = 4,
                 max_jobs_per_poll: int = 0,
                 redispatch_kinds: frozenset[str] = REDISPATCH_KINDS,
                 clock: Callable[[], float] = time.monotonic) -> None:
        super().__init__(poll_faults, result_faults, delay_s)
        self.lease_s = float(lease_s)
        self.max_attempts = max(1, int(max_attempts))
        self.max_jobs_per_poll = max(0, int(max_jobs_per_poll))
        self.redispatch_kinds = frozenset(redispatch_kinds)
        self._clock = clock
        # job id -> {job, worker, expires_at, attempt}
        self.leases: dict[str, dict[str, Any]] = {}
        self.attempts: dict[str, int] = {}
        self.excluded: dict[str, set[str]] = {}
        self.checkpoints: dict[str, dict[str, Any]] = {}
        self.completed: dict[str, dict[str, Any]] = {}
        self.duplicate_results: list[dict[str, Any]] = []
        self.abandoned: list[str] = []
        # first-submission stamp per job id: every delivery carries the
        # job's total queue age as ``queued_s`` so a worker's overload
        # controller (ISSUE 9, node/overload.py) can count hive-side
        # waiting against the deadline — under overload the backlog
        # lives HERE, not in the worker's bounded local queue
        self.submitted_at: dict[str, float] = {}
        self.known_workers: set[str] = set()
        self.worker_seen: dict[str, float] = {}  # last poll/heartbeat
        self.partitioned: set[str] = set()
        # swarmsight (ISSUE 13): the per-job flight recorder (trace
        # context out, span digests in, hive-clock event timeline,
        # settle-time budget attribution) + the fleet plane — latest
        # per-worker metric snapshot pushed by heartbeats, and the
        # hive's own observed-arrival EWMA (the item-5 autoscaler's
        # demand signal)
        self.flights = obs_flight.FlightRecorder()
        self.fleet: dict[str, dict[str, Any]] = {}
        self._submit_rate = obs_flight.RateEwma(window_s=30.0)
        self._app.router.add_post("/api/heartbeat", self._heartbeat)
        self._app.router.add_get("/api/stats", self._stats_endpoint)
        self._app.router.add_get("/api/fleet", self._fleet_endpoint)
        self._app.router.add_get("/api/flight", self._flights_endpoint)
        self._app.router.add_get("/api/flight/{job_id}",
                                 self._flight_endpoint)
        # per-hive registry (hermetic, like the worker's): the snapshot
        # is the accounting tests reconcile against the result lists
        self.metrics = Registry()
        m = self.metrics
        self._leases_granted = m.counter(
            "chiaswarm_hive_leases_granted_total",
            "jobs handed out under a lease")
        self._leases_expired = m.counter(
            "chiaswarm_hive_leases_expired_total",
            "leases that expired without a settling upload")
        self._redelivered = m.counter(
            "chiaswarm_hive_jobs_redelivered_total",
            "expired-lease jobs requeued for another worker")
        self._redispatched = m.counter(
            "chiaswarm_hive_jobs_redispatched_total",
            "jobs requeued because a worker refused them", ("kind",))
        self._completed = m.counter(
            "chiaswarm_hive_results_completed_total",
            "results settled exactly once")
        self._duplicates = m.counter(
            "chiaswarm_hive_results_duplicate_total",
            "late/racing uploads acked idempotently, never counted")
        self._heartbeats = m.counter(
            "chiaswarm_hive_heartbeats_total", "heartbeats received")
        self._ckpt_stored = m.counter(
            "chiaswarm_hive_checkpoints_stored_total",
            "resume checkpoints accepted from lease holders")
        self._ckpt_stale = m.counter(
            "chiaswarm_hive_checkpoints_stale_total",
            "checkpoints rejected because the sender lost the lease")
        self._abandoned = m.counter(
            "chiaswarm_hive_jobs_abandoned_total",
            "jobs parked after exhausting max_attempts deliveries")
        self._salvaged = m.counter(
            "chiaswarm_hive_jobs_salvaged_total",
            "abandoned jobs settled late by a straggler upload "
            "(chip time recovered; the job leaves the abandoned list)")

    def submit(self, job: dict[str, Any]) -> None:
        job_id = str(job.get("id"))
        now = self._clock()
        self.submitted_at.setdefault(job_id, now)
        # flight record opens at submit (idempotent for resubmitted
        # ids); the observed-arrival EWMA feeds /api/fleet
        self.flights.open(job_id, job, t=now)
        self._submit_rate.note(now)
        super().submit(job)

    # ---- chaos controls -------------------------------------------------

    def partition(self, worker_name: str) -> None:
        """Cut ``worker_name`` off: its polls, heartbeats, and uploads
        all see dropped connections until :meth:`heal`. Its leases expire
        on schedule — the deterministic worker-vanished fault."""
        self.partitioned.add(str(worker_name))

    def heal(self, worker_name: str) -> None:
        self.partitioned.discard(str(worker_name))

    def _worker_reachable(self, worker_name: str) -> bool:
        return worker_name not in self.partitioned

    # ---- leases ---------------------------------------------------------

    def sweep(self) -> list[str]:
        """Expire overdue leases; requeue (or abandon) their jobs.
        Runs on every poll/heartbeat/upload — callers never wait on a
        background timer — and returns the redelivered job ids."""
        now = self._clock()
        redelivered: list[str] = []
        for job_id in [j for j, lease in self.leases.items()
                       if now >= lease["expires_at"]]:
            lease = self.leases.pop(job_id)
            self._leases_expired.inc()
            self.excluded.setdefault(job_id, set()).add(lease["worker"])
            self.flights.note(job_id, "lease_expired", t=now,
                              worker=lease["worker"],
                              attempt=lease["attempt"])
            if self.attempts.get(job_id, 0) >= self.max_attempts:
                log.error("job %s abandoned after %d deliveries",
                          job_id, self.attempts.get(job_id, 0))
                self.abandoned.append(job_id)
                self._abandoned.inc()
                self.flights.note(job_id, "abandoned", t=now,
                                  attempts=self.attempts.get(job_id, 0))
                # GC like the settle path does: an abandoned job's
                # latent-sized checkpoint blob is never resumed again
                self.checkpoints.pop(job_id, None)
                continue
            log.warning("lease for job %s (worker %s) expired; "
                        "redelivering (attempt %d done)", job_id,
                        lease["worker"], lease["attempt"])
            self.pending_jobs.append(lease["job"])
            self._redelivered.inc()
            self.flights.note(job_id, "redelivered", t=now)
            redelivered.append(job_id)
        return redelivered

    def expire_worker(self, worker_name: str) -> list[str]:
        """Declare ``worker_name`` dead NOW: every lease it holds expires
        immediately and redelivers on this very sweep, without waiting
        out ``lease_s``. The TPU-fleet analog is a preemption notice —
        the scheduler knows the node is gone before the lease clock
        does. Pairs with :meth:`partition` (cut it off first, so nothing
        it still uploads can race ahead of the revocation)."""
        for lease in self.leases.values():
            if lease["worker"] == worker_name:
                lease["expires_at"] = float("-inf")
        return self.sweep()

    def _extend_leases(self, worker_name: str) -> None:
        expiry = self._clock() + self.lease_s
        for lease in self.leases.values():
            if lease["worker"] == worker_name:
                lease["expires_at"] = expiry

    def live_workers(self) -> set[str]:
        """Workers seen (poll or heartbeat) within the last two lease
        periods. The starvation valve compares exclusion against THIS
        set, not ``known_workers``: a dead worker stays known forever,
        and waiting for its refusal would strand a job that every
        surviving worker has already refused."""
        horizon = self._clock() - 2 * self.lease_s
        return {name for name, seen in self.worker_seen.items()
                if seen >= horizon}

    def lease_holder(self, job_id: Any) -> str | None:
        lease = self.leases.get(str(job_id))
        return None if lease is None else lease["worker"]

    def leased_ids(self, worker_name: str) -> list[str]:
        return sorted(job_id for job_id, lease in self.leases.items()
                      if lease["worker"] == worker_name)

    # ---- handout (ChaoticHive seam) ------------------------------------

    def _take_jobs(self, worker_name: str) -> list[dict[str, Any]]:
        self.known_workers.add(worker_name)
        self.worker_seen[worker_name] = self._clock()
        self.sweep()
        self._extend_leases(worker_name)  # a poll proves liveness
        live = self.live_workers()
        handed: list[dict[str, Any]] = []
        remaining: list[dict[str, Any]] = []
        for job in self.pending_jobs:
            job_id = str(job.get("id"))
            if job_id in self.completed:
                # settled while queued (a late upload raced ahead of
                # this redelivery): drop the copy, never re-execute
                continue
            excluded = self.excluded.get(job_id, set())
            # starvation valve: once every LIVE worker has refused or
            # lost this job, exclusion has nothing left to route around
            # (a dead worker must not hold the valve shut forever)
            if worker_name in excluded and not live <= excluded:
                remaining.append(job)
                continue
            if self.max_jobs_per_poll and \
                    len(handed) >= self.max_jobs_per_poll:
                remaining.append(job)
                continue
            handed.append(job)
        self.pending_jobs = remaining
        out: list[dict[str, Any]] = []
        for job in handed:
            job_id = str(job.get("id"))
            attempt = self.attempts.get(job_id, 0) + 1
            self.attempts[job_id] = attempt
            self.leases[job_id] = {
                "job": job, "worker": worker_name, "attempt": attempt,
                "expires_at": self._clock() + self.lease_s,
            }
            self._leases_granted.inc()
            # the wire copy carries its lineage + resume state; the
            # queued original stays pristine for the next redelivery
            payload = dict(job)
            payload["attempt"] = attempt
            submitted = self.submitted_at.get(job_id)
            if submitted is not None:
                # total time since FIRST submission (across attempts):
                # the worker's admission estimator charges this against
                # the job's deadline budget
                payload["queued_s"] = round(
                    max(0.0, self._clock() - submitted), 4)
            checkpoint = self.checkpoints.get(job_id)
            if checkpoint is not None:
                payload["resume"] = checkpoint
            # swarmsight (ISSUE 13): every delivery carries the job's
            # trace context — trace_id for the whole lifetime, a span
            # id for THIS attempt — and the grant lands on the flight
            # record's hive-clock timeline
            resume_step = None
            if isinstance(checkpoint, dict):
                try:
                    resume_step = int(checkpoint.get("step") or 0) or None
                except (TypeError, ValueError):
                    resume_step = None
            payload[obs_flight.TRACE_CTX_KEY] = self.flights.grant(
                job_id, attempt=attempt, worker=worker_name,
                t=self._clock(), queued_s=payload.get("queued_s"),
                resume_step=resume_step)
            out.append(payload)
        return out

    # ---- settling (ChaoticHive seam) ------------------------------------

    def _record_result(self, result: dict[str, Any],
                       worker_name: str) -> dict[str, Any]:
        self.sweep()
        job_id = str(result.get("id"))
        # swarmsight (ISSUE 13): the worker's span digest is popped OFF
        # the envelope into the flight record — every upload's, even a
        # duplicate's or a refusal's (they are attempts in the story) —
        # so stored/settled results keep their historical shape
        digest = result.pop(obs_flight.SPAN_DIGEST_KEY, None)
        if digest is not None:
            self.flights.add_digest(job_id, digest)
        if job_id in self.completed:
            # the redelivery race settled already: ack idempotently so
            # the uploader stops retrying, but never double-count
            self.duplicate_results.append(result)
            self._duplicates.inc()
            self.flights.note(job_id, "duplicate_upload",
                              t=self._clock(), worker=worker_name)
            log.info("duplicate result for %s from %s acked (job already "
                     "settled)", job_id, worker_name or "unknown")
            return {"status": "duplicate"}
        kind = result_error_kind(result)
        lease = self.leases.get(job_id)
        # does the refuser still hold the lease? A refusal can also land
        # LATE — after its lease expired (sweep already requeued the
        # job) or after redelivery to another worker (the job is in
        # flight elsewhere). In both cases there is nothing to requeue,
        # but the refusal still must not settle the job as an error.
        held_by_refuser = lease is not None and \
            (not worker_name or lease["worker"] == worker_name)
        if (kind in self.redispatch_kinds
                and not result.get("fatal_error")
                and job_id not in self.abandoned
                and (self.attempts.get(job_id, 0) < self.max_attempts
                     or not held_by_refuser)):
            # THIS worker cannot serve the model; another may. Requeue
            # with the refuser excluded instead of settling the error.
            # A refusal from a worker that no longer holds the lease
            # never settles, even at max_attempts — the live copy
            # (queued or running elsewhere) owns the outcome; only the
            # current holder's refusal on the final attempt is final.
            refuser = worker_name or (lease["worker"] if lease else "")
            if refuser:
                self.excluded.setdefault(job_id, set()).add(refuser)
            if held_by_refuser:
                self.leases.pop(job_id, None)
                self.pending_jobs.append(lease["job"])
            self._redispatched.inc(kind=kind)
            self.flights.note(job_id, "redispatched", t=self._clock(),
                              kind=kind, worker=refuser or None)
            log.warning("job %s refused by %s (%s); redispatching with "
                        "the refuser excluded", job_id,
                        refuser or "unknown", kind)
            return {"status": "requeued", "kind": kind}
        # exactly-once settle: first envelope wins, whoever sent it —
        # even a worker whose lease already expired (salvaged chip time).
        # Withdraw any queued redelivery copy too: without this, a late
        # upload landing after its lease expired would leave the requeued
        # copy to burn a full re-execution on another worker.
        if job_id in self.abandoned:
            # a straggler upload for a job policy already gave up on:
            # the work EXISTS, so the job settles and leaves the
            # abandoned list — one job must never read as both
            # abandoned AND completed (the reconciliation invariant
            # tests/test_minihive.py holds at harness scale)
            self.abandoned.remove(job_id)
            self._salvaged.inc()
            self.flights.note(job_id, "salvaged", t=self._clock(),
                              worker=worker_name)
            log.warning("job %s salvaged by a straggler upload after "
                        "abandonment", job_id)
        self.completed[job_id] = result
        self.results.append(result)
        self.result_event.set()
        self.leases.pop(job_id, None)
        self.checkpoints.pop(job_id, None)  # hive-side checkpoint GC
        self.pending_jobs = [j for j in self.pending_jobs
                             if str(j.get("id")) != job_id]
        self._completed.inc()
        # the exactly-once settle closes the flight record and computes
        # its deadline-budget attribution (obs/flight.py)
        settle_attempt = None
        if isinstance(digest, dict):
            # a LATE upload can settle attempt 1 while attempt 2 is in
            # flight: the digest knows which attempt's work this is.
            # Coerced defensively — the field crossed the wire from a
            # possibly version-skewed worker, and a garbage value must
            # degrade to the lease books, never crash an already-
            # counted settle into an unsettled flight record
            try:
                settle_attempt = int(digest.get("attempt"))
            except (TypeError, ValueError):
                settle_attempt = None
        self.flights.settle(
            job_id, t=self._clock(),
            worker=worker_name or str(result.get("worker_name") or ""),
            outcome=kind or "ok",
            attempt=settle_attempt
            if settle_attempt is not None else self.attempts.get(job_id))
        return {"status": "ok"}

    # ---- heartbeats ------------------------------------------------------

    async def _heartbeat(self, request):
        from aiohttp import web

        try:
            payload = await request.json()
        except Exception:
            return web.Response(status=400, text="unparseable heartbeat")
        worker_name = str(payload.get("worker_name") or "")
        if not self._worker_reachable(worker_name):
            request.transport.close()
            raise ConnectionResetError("chaos: partitioned heartbeat")
        self.known_workers.add(worker_name)
        self.worker_seen[worker_name] = self._clock()
        self.sweep()
        self._heartbeats.inc()
        # fleet plane (ISSUE 13): heartbeats may push a per-worker
        # metric snapshot (arrival EWMAs, lane occupancy, chips in
        # service, residency ledger, overload state) — stored latest-
        # wins and aggregated at GET /api/fleet
        metrics = payload.get("metrics")
        if isinstance(metrics, dict):
            self.fleet[worker_name] = {"at": self._clock(),
                                       "metrics": metrics}
        expiry = self._clock() + self.lease_s
        lost: list[str] = []
        for entry in payload.get("jobs") or []:
            job_id = str(entry.get("id"))
            lease = self.leases.get(job_id)
            if lease is None or lease["worker"] != worker_name:
                settled = self.completed.get(job_id)
                if settled is not None and \
                        settled.get("worker_name") in (worker_name, "", None):
                    # the sender's OWN upload just raced this beat: NOT
                    # a lost lease — its ack path clears the in-flight
                    # entry, and counting it would show phantom lease
                    # churn on every healthy run. (Settled by a DIFFERENT
                    # worker still reports lost below: the sender is
                    # burning chip time on a finished job.)
                    continue
                # the lease moved on (expired + redelivered): tell the
                # sender so it can stop burning chip time on it; a stale
                # checkpoint must NOT shadow the new holder's progress
                lost.append(job_id)
                if entry.get("checkpoint") is not None:
                    self._ckpt_stale.inc()
                continue
            lease["expires_at"] = expiry
            checkpoint = entry.get("checkpoint")
            if checkpoint is not None:
                self.checkpoints[job_id] = checkpoint
                self._ckpt_stored.inc()
                # checkpoint marker on the flight timeline: the worker
                # only re-pushes on change, so this is progress, not
                # heartbeat noise
                step = None
                if isinstance(checkpoint, dict):
                    step = checkpoint.get("step")
                self.flights.note(job_id, "checkpoint", t=self._clock(),
                                  worker=worker_name, step=step)
        return web.json_response({"status": "ok", "lost": lost})

    # ---- observability ---------------------------------------------------

    def stats(self) -> dict[str, Any]:
        """Lease-table view + the counter snapshot — the registry the
        exactly-once tests reconcile against the result lists."""
        self.sweep()
        return {
            "pending": len(self.pending_jobs),
            "leased": {job_id: {"worker": lease["worker"],
                                "attempt": lease["attempt"]}
                       for job_id, lease in self.leases.items()},
            "completed": len(self.completed),
            "duplicates": len(self.duplicate_results),
            "abandoned": list(self.abandoned),
            "checkpoints": sorted(self.checkpoints),
            "metrics": self.metrics.snapshot(),
            "flights": self.flights.snapshot(),
        }

    def fleet_snapshot(self) -> dict[str, Any]:
        """The ``GET /api/fleet`` aggregate: latest per-worker metric
        snapshots (heartbeat-pushed) plus hive-level queue state and the
        observed-arrival EWMA — the data plane the ROADMAP item-5
        capacity planner consumes (arrival rates, occupancy, chips in
        service, residency, health, all in one place)."""
        now = self._clock()
        live = self.live_workers()
        workers: dict[str, Any] = {}
        for name, entry in sorted(self.fleet.items()):
            workers[name] = dict(
                entry["metrics"],
                age_s=round(max(0.0, now - entry["at"]), 3),
                live=name in live,
                partitioned=name in self.partitioned,
                leased_jobs=len(self.leased_ids(name)))
        # aggregate over LIVE, reachable workers only: a dead worker's
        # last snapshot stays in the per-worker map (debugging), but
        # counting its chips/arrival rate forever would overstate fleet
        # capacity to exactly the autoscaler this plane feeds
        active = {name: w for name, w in workers.items()
                  if w["live"] and not w["partitioned"]}

        def total(key: str) -> float:
            value = sum(float(w.get(key) or 0.0)
                        for w in active.values())
            return round(value, 4)

        return {
            "at_s": round(now, 6),
            "workers": workers,
            "aggregate": {
                "workers_reporting": len(workers),
                "workers_live": len(live),
                "chips_in_service": int(total("chips_in_service")),
                "arrival_rate_rows_s": total("arrival_rate_rows_s"),
                "lane_occupancy_mean": round(
                    total("lane_occupancy") / max(1, len(active)), 4),
                "queue_depth": int(total("queue_depth")),
                "inflight_jobs": int(total("inflight_jobs")),
                "jobs_done": int(total("jobs_done")),
                "jobs_shed": int(total("jobs_shed")),
                "workers_in_brownout": sum(
                    1 for w in active.values()
                    if (w.get("overload") or {}).get("state")
                    == "brownout"),
                "observed_arrival_jobs_s": round(
                    self._submit_rate.rate(now), 4),
                "pending_jobs": len(self.pending_jobs),
                "leased_jobs": len(self.leases),
                "completed_jobs": len(self.completed),
                "abandoned_jobs": len(self.abandoned),
            },
        }

    async def _stats_endpoint(self, request):
        from aiohttp import web

        return web.json_response(self.stats())

    async def _fleet_endpoint(self, request):
        from aiohttp import web

        return web.json_response(self.fleet_snapshot())

    async def _flights_endpoint(self, request):
        from aiohttp import web

        return web.json_response(dict(self.flights.snapshot(),
                                      jobs=self.flights.job_ids()))

    async def _flight_endpoint(self, request):
        from aiohttp import web

        job_id = request.match_info.get("job_id", "")
        record = self.flights.get(job_id)
        if record is None:
            return web.json_response(
                {"status": "unknown",
                 "error": f"no flight record for job {job_id!r} (evicted "
                          f"or never submitted)"}, status=404)
        return web.json_response(record)
