"""swarmload: the mini-hive load harness (ISSUE 9 / ROADMAP item 5).

PR 6 built the fleet substrate — :class:`~chiaswarm_tpu.node.minihive.
MiniHive` is a real lease-tracking queue already running multiple
Workers in one process under kill/partition faults — but nothing drove
it at fleet scale. This module is the LOAD side:

- **Synthetic users**: :class:`UserPopulation` builds thousands of
  users, each with a workload profile (txt2img burst, img2img trickle,
  inpaint/ControlNet tail), an activity weight, and a model affinity —
  the per-user structure real hive traffic has and a flat Poisson
  stream does not.
- **Arrival curves**: :class:`DiurnalCurve` compresses a day into the
  run — a seeded sinusoid with seeded spike windows layered on top.
  :func:`generate_schedule` expands (population x curve x duration)
  into a deterministic arrival schedule: same seed, same jobs, same
  timestamps, forever.
- **The drive**: :func:`run_load` submits the schedule into a
  :class:`LoadHive` (a MiniHive stamping submit/grant/settle times per
  job) against real :class:`~chiaswarm_tpu.node.worker.Worker`
  processes — their actual poll loops, overload controllers, queues,
  and upload paths. Workers execute through the chaos-harness executor
  seam by default (:class:`SyntheticExecutor`, deterministic
  per-workload service times, no compiles), or through real pipelines
  when the caller passes its own factory; an optional scripted worker
  kill lands mid-run through the PR-6 partition + preemption path.
- **Scoring**: :func:`score_run` reconciles exactly-once settlement
  (every issued job completed, shed-redispatched, or abandoned-by-
  policy — zero lost), folds per-workload p50/p99 latency, admitted-
  within-deadline conformance, the workers' ``/metrics``-level
  snapshots (occupancy, padding waste, breaker trips, overload and
  residency families), and publishes a **capacity model**: jobs/s per
  chip per workload mix, with models-resident as the second axis —
  the numbers that turn "fast in a benchmark" into "provisionable".

The same arrival model doubles as the tuning harness (the ISSUE-9
satellite): :func:`sweep_lane_gains` replays seeded traces through
:class:`~chiaswarm_tpu.serving.stepper.LaneWidthController` in pure
host simulation to score grow/shrink/patience gains, and
:func:`sweep_prefetch_window` scores the residency
:class:`~chiaswarm_tpu.serving.residency.ArrivalEwma` prefetch-ranking
window the same way; ``benchmark.py`` stamps both sweeps (and a
compact overload run) into BENCH json.

Like the chaos harness, this is product code: operators smoke a build's
overload behavior with ``python -m chiaswarm_tpu.node.loadgen``
(JSON report on stdout; ``CHIASWARM_LOAD_*`` knobs below), and
``tests/test_loadgen.py`` is the executable spec — including THE
ISSUE-9 acceptance gate: scripted 10x overload, mixed workloads, one
mid-run worker kill, zero job loss, p99 of admitted jobs within
deadline.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import math
import os
import random
import threading
import time
from typing import Any, Callable, Iterable, Sequence

from chiaswarm_tpu.node.federation import FederatedHive, ShardHive
from chiaswarm_tpu.node.minihive import MiniHive
from chiaswarm_tpu.node.output_processor import make_text_result
from chiaswarm_tpu.node.resilience import classify_result
from chiaswarm_tpu.obs import trace as obs_trace
from chiaswarm_tpu.obs.flight import ATTRIBUTION_PHASES

log = logging.getLogger("chiaswarm.loadgen")


def _suggest_hang_budget() -> dict:
    """The guard's measured hang-budget derivation over THIS process's
    step-seconds histogram (import deferred: loadgen is host-only)."""
    from chiaswarm_tpu.serving.guard import suggest_hang_budget

    return suggest_hang_budget()


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 1]) of an unsorted sequence;
    0.0 for an empty one. Shared by the scorer and the BENCH config so
    a p99 always means the same thing."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = min(len(ordered) - 1, max(0, math.ceil(q * len(ordered)) - 1))
    return float(ordered[rank])


# ---------------------------------------------------------------------------
# workload profiles + users
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class WorkloadProfile:
    """One workload class in the mix.

    ``weight`` is the share of the user population on this profile;
    ``deadline_s`` rides each job as its ``deadline_s`` field (the
    overload controller's admission budget and the scorer's
    conformance bound); ``steps`` bounds the sampled step count;
    ``service_s`` is the synthetic executor's base wall time."""

    name: str
    weight: float
    deadline_s: float
    steps: tuple[int, int]
    service_s: float


#: the default mix the ISSUE names: txt2img burst, img2img trickle,
#: inpaint + ControlNet tail — plus the few-step class (ISSUE 12):
#: LCM/turbo-style 2–8 step jobs are interactive traffic, so they carry
#: the SHORTEST deadline in the mix and the smallest service time
#: (steps x per-step cost collapses ~7x vs the 30-step baseline).
#: Service times are the synthetic stand-in scale (hermetic runs);
#: real-pipeline factories ignore them.
DEFAULT_PROFILES: tuple[WorkloadProfile, ...] = (
    WorkloadProfile("txt2img", 0.50, 2.0, (10, 30), 0.10),
    WorkloadProfile("txt2img_fewstep", 0.15, 0.8, (2, 8), 0.04),
    WorkloadProfile("img2img", 0.22, 2.5, (10, 25), 0.13),
    WorkloadProfile("inpaint", 0.08, 3.0, (10, 25), 0.16),
    WorkloadProfile("controlnet", 0.05, 3.0, (15, 30), 0.20),
)


# ---------------------------------------------------------------------------
# per-model-family deadline tables (ISSUE 10 satellite, ROADMAP 5b)
# ---------------------------------------------------------------------------
#
# The PR-2 deadlines were static per-WORKFLOW guesses; a family that
# costs 3x the denoise FLOPs deserves 3x the budget. The harness closes
# the loop two ways: score_run() emits a measured suggested-deadline
# table (p99 x margin) per family from every run, and
# sweep_deadline_table() is the pure deterministic derivation whose
# output ships as DEFAULT_FAMILY_DEADLINES — pinned defaults == winner
# by tests/test_loadgen.py, exactly like the PR-9 controller-gain
# sweep. Operators apply a table via the ``family_deadline_s`` settings
# map (node/settings.py; the worker consults it between a job's
# explicit deadline_s and the workflow table).

#: headroom multiplier over the measured p99 — an admitted job that
#: misses by 50 ms still misses, and the estimator cannot see ack
#: jitter (the PR-9 margin lesson, applied to the budget side)
DEADLINE_MARGIN = 1.5

#: relative denoise cost per model family (sd15 = 1.0; sdxl from the
#: BASELINE.md step-time ratio at default sizes, tiny from the test
#: family's measured share) — scales the synthetic service model the
#: same way the family scales the real denoise loop. ``sdxl_turbo``
#: (ISSUE 12) is the few-step-distilled SDXL class: the per-step cost
#: stays SDXL's 3.2 but 4 steps replace 30, so 3.2 x 4/30 ≈ 0.43 —
#: the family-deadline table prices few-step jobs at their collapsed
#: cost instead of billing them the 30-step budget.
FAMILY_COST_FACTORS = {"tiny": 0.12, "sd15": 1.0, "sdxl": 3.2,
                       "sdxl_turbo": 0.43, "sd_turbo": 0.13}


def model_family(name: Any) -> str:
    """Family bucket of a model name for the deadline table. A light
    name heuristic on purpose: the scorer must run without jax or the
    model-config registry (the worker side uses the real catalog,
    node/worker.py::_model_family)."""
    lowered = str(name or "").lower()
    if "turbo" in lowered or "lcm" in lowered or "lightning" in lowered:
        # the distilled few-step classes, checked BEFORE the "xl" hint
        # ("sdxl-turbo" names both); non-XL distillations (sd-turbo,
        # sd15-lcm) price at the SD-class per-step cost, not SDXL's
        return "sdxl_turbo" if "xl" in lowered else "sd_turbo"
    if "xl" in lowered:
        return "sdxl"
    if "tiny" in lowered:
        return "tiny"
    return "sd15"


def sweep_deadline_table(seed: Any = "swarmload", *,
                         margin: float = DEADLINE_MARGIN,
                         samples: int = 4000,
                         profiles: Sequence[WorkloadProfile] =
                         DEFAULT_PROFILES,
                         factors: dict[str, float] | None = None,
                         ) -> dict[str, float]:
    """Derive a per-family deadline table from the harness's service
    model: seeded mix-weighted service draws (the SyntheticExecutor's
    jitter model) scaled by each family's cost factor, doubled for one
    queued-peer drain (the admission estimator's occupancy~1 term),
    p99 x margin. Pure host arithmetic, deterministic per seed — the
    shipped DEFAULT_FAMILY_DEADLINES is this function's output at the
    default seed, pinned by test."""
    factors = dict(FAMILY_COST_FACTORS if factors is None else factors)
    weights = [max(0.0, p.weight) for p in profiles]
    table: dict[str, float] = {}
    for family, factor in sorted(factors.items()):
        rng = random.Random(f"deadline:{seed}:{family}")
        draws = []
        for _ in range(max(1, int(samples))):
            profile = rng.choices(list(profiles), weights=weights)[0]
            jitter = 1.0 + 0.3 * (2.0 * rng.random() - 1.0)
            draws.append(profile.service_s * factor * jitter * 2.0)
        table[family] = round(percentile(draws, 0.99) * margin, 3)
    return table


#: the shipped per-family deadline defaults — sweep_deadline_table()'s
#: output at the default seed (pinned defaults == winner,
#: tests/test_loadgen.py::test_family_deadline_defaults_pinned).
#: ``sdxl_turbo`` (ISSUE 12) prices the few-step-distilled SDXL class
#: at its collapsed step count — ~7x tighter than full SDXL.
DEFAULT_FAMILY_DEADLINES = {"sd15": 0.713, "sd_turbo": 0.094,
                            "sdxl": 2.257, "sdxl_turbo": 0.31,
                            "tiny": 0.086}


@dataclasses.dataclass(frozen=True)
class SyntheticUser:
    user_id: int
    profile: WorkloadProfile
    activity: float        # relative arrival weight within the population
    model: str             # the checkpoint this user's jobs name


class UserPopulation:
    """``n_users`` seeded synthetic users over a workload mix.

    Activity weights are heavy-tailed (a few power users, a long tail
    of occasional ones — ``0.2 + Pareto``), and each user sticks to one
    model from ``models`` so the stream has the per-model locality the
    residency ledger's prefetch ranking feeds on."""

    def __init__(self, n_users: int = 2000,
                 profiles: Sequence[WorkloadProfile] = DEFAULT_PROFILES,
                 models: Sequence[str] = ("swarm/sd15",),
                 seed: Any = "swarmload") -> None:
        if not profiles:
            raise ValueError("need at least one workload profile")
        self.profiles = tuple(profiles)
        self.seed = seed
        rng = random.Random(f"users:{seed}")
        weights = [max(0.0, p.weight) for p in self.profiles]
        names = list(models) or ["swarm/sd15"]
        self.users: list[SyntheticUser] = []
        for uid in range(max(1, int(n_users))):
            profile = rng.choices(self.profiles, weights=weights)[0]
            activity = 0.2 + rng.paretovariate(2.0)
            model = rng.choices(names,
                                weights=range(len(names), 0, -1))[0]
            self.users.append(SyntheticUser(uid, profile, activity, model))
        self._cum_activity = []
        total = 0.0
        for user in self.users:
            total += user.activity
            self._cum_activity.append(total)
        self.total_activity = total

    def pick(self, rng: random.Random) -> SyntheticUser:
        """Activity-weighted user draw (bisect over the cumulative
        weights — O(log n) per arrival at thousands of users)."""
        import bisect

        x = rng.uniform(0.0, self.total_activity)
        return self.users[min(len(self.users) - 1,
                              bisect.bisect_left(self._cum_activity, x))]

    def mix(self) -> dict[str, float]:
        counts: dict[str, int] = {}
        for user in self.users:
            counts[user.profile.name] = counts.get(user.profile.name, 0) + 1
        return {name: round(n / len(self.users), 4)
                for name, n in sorted(counts.items())}


# ---------------------------------------------------------------------------
# arrival curves
# ---------------------------------------------------------------------------


class DiurnalCurve:
    """Seeded diurnal + spike rate multiplier over one compressed "day".

    ``multiplier(frac)`` (frac = t / duration in [0, 1]) is a sinusoid
    — trough at the start, peak mid-run — of ``amplitude`` around 1.0,
    with ``spikes`` seeded spike windows (each ``spike_frac`` of the
    run at ``spike_mult`` x) layered on top: the flash-crowd shape that
    makes overload control earn its keep. Deterministic per seed."""

    def __init__(self, *, amplitude: float = 0.6, spikes: int = 2,
                 spike_mult: float = 4.0, spike_frac: float = 0.06,
                 seed: Any = "swarmload") -> None:
        self.amplitude = max(0.0, min(1.0, float(amplitude)))
        self.spike_mult = max(1.0, float(spike_mult))
        rng = random.Random(f"curve:{seed}")
        width = max(1e-3, float(spike_frac))
        self.spike_windows = sorted(
            (start, min(1.0, start + width))
            for start in (rng.uniform(0.15, 0.9 - width)
                          for _ in range(max(0, int(spikes)))))

    def multiplier(self, frac: float) -> float:
        frac = max(0.0, min(1.0, float(frac)))
        base = 1.0 + self.amplitude * math.sin(
            2.0 * math.pi * (frac - 0.25))
        for start, end in self.spike_windows:
            if start <= frac < end:
                return base * self.spike_mult
        return base


@dataclasses.dataclass(frozen=True)
class ScheduledJob:
    at_s: float
    user_id: int
    workload: str
    job: dict[str, Any]


def generate_schedule(population: UserPopulation,
                      curve: DiurnalCurve, *,
                      duration_s: float,
                      rate_jobs_s: float,
                      seed: Any = "swarmload",
                      id_prefix: str = "load",
                      content_type: str = "application/json",
                      ) -> list[ScheduledJob]:
    """Expand (population x curve) into a deterministic arrival list.

    Arrivals are a thinned Poisson process: exponential inter-arrival
    gaps at the peak rate, each kept with probability
    ``multiplier / peak`` — so the instantaneous accepted rate tracks
    ``rate_jobs_s x curve.multiplier`` exactly, with no time-bucket
    artifacts. Each accepted arrival draws an activity-weighted user,
    whose profile supplies workload, steps, deadline, and model."""
    rng = random.Random(f"schedule:{seed}")
    duration_s = max(1e-3, float(duration_s))
    rate = max(1e-6, float(rate_jobs_s))
    peak = rate * max(curve.multiplier(f / 200.0) for f in range(201))
    out: list[ScheduledJob] = []
    t = 0.0
    n = 0
    while True:
        t += rng.expovariate(peak)
        if t >= duration_s:
            break
        if rng.uniform(0.0, peak) > rate * curve.multiplier(t / duration_s):
            continue  # thinned: off-peak slack
        user = population.pick(rng)
        profile = user.profile
        steps = rng.randint(*profile.steps)
        job_id = f"{id_prefix}-{n}"
        job: dict[str, Any] = {
            "id": job_id,
            "model_name": user.model,
            "workflow": profile.name,
            "prompt": f"user {user.user_id} {profile.name} {n}",
            "num_inference_steps": steps,
            "guidance_scale": 7.5,
            "height": 64, "width": 64,
            "seed": rng.randrange(1 << 31),
            "deadline_s": profile.deadline_s,
            # "application/json" for synthetic executors; the REAL-lane
            # soak passes "image/png" so real pipelines encode actual
            # frames (ISSUE 10 satellite / ROADMAP 5a)
            "content_type": content_type,
        }
        if profile.name == "txt2img_fewstep":
            # the few-step class IS the lcm-kind CFG-free path
            # (ISSUE 12): real-pipeline runs must exercise the fewstep
            # lane eligibility + per-row CFG-free combine, not a short
            # dpm job wearing the class name
            job["guidance_scale"] = 1.0
            job["parameters"] = {"scheduler_type": "LCMScheduler"}
        out.append(ScheduledJob(at_s=t, user_id=user.user_id,
                                workload=profile.name, job=job))
        n += 1
    return out


# ---------------------------------------------------------------------------
# the drive: LoadHive + synthetic workers
# ---------------------------------------------------------------------------


class LoadHive(MiniHive):
    """MiniHive with per-job timing stamps for the scorer.

    ``submitted_at`` comes from MiniHive (it also rides the wire as
    each delivery's ``queued_s`` age stamp); ``granted_at`` re-stamps
    on every delivery (the "admitted latency" view runs from the LAST
    grant — the delivery that produced the settling envelope);
    ``settled_at`` stamps the exactly-once settle."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        # submitted_at comes from MiniHive itself (it also feeds the
        # wire "queued_s" stamp every delivery carries)
        self.granted_at: dict[str, float] = {}
        self.settled_at: dict[str, float] = {}

    def submit_job(self, job: dict[str, Any]) -> None:
        self.submit(job)

    def _take_jobs(self, worker_name: str):
        out = super()._take_jobs(worker_name)
        now = self._clock()
        for payload in out:
            self.granted_at[str(payload.get("id"))] = now
        return out

    def _record_result(self, result, worker_name):
        ack = super()._record_result(result, worker_name)
        if ack.get("status") == "ok":
            self.settled_at[str(result.get("id"))] = self._clock()
        return ack


class _ShardLoad(ShardHive, LoadHive):
    """One federated load shard: ShardHive's steal/forward seams
    stacked over LoadHive's timing stamps. steal_to's cooperative
    ``super()._take_jobs`` resolves through LoadHive here, so STOLEN
    grants stamp ``granted_at`` exactly like owned ones, and a
    forwarded wrong-shard upload settles (and stamps ``settled_at``)
    on the owner — the scorer never sees federation seams."""


class _StitchedFlights:
    """score_run's flight view over a federation: each lookup routes
    to the job's OWNING shard (the only book that flight lives in)."""

    def __init__(self, federation: "FederatedLoadHive") -> None:
        self._federation = federation

    def get(self, job_id: Any) -> dict | None:
        shard = self._federation.owner_shard(job_id)
        return None if shard is None else shard.flights.get(job_id)

    def verify(self, job_ids: Iterable[Any]) -> list:
        return self._federation.verify_flights(job_ids)


class FederatedLoadHive(FederatedHive):
    """The federation wired for the load harness (swarmfed, ISSUE 17):
    _ShardLoad shards plus the merged timing views :func:`score_run`
    folds. Everything else — routing, stealing, per-shard journals —
    is stock FederatedHive."""

    def __init__(self, n_shards: int = 3, **kwargs: Any) -> None:
        kwargs.setdefault("hive_cls", _ShardLoad)
        super().__init__(n_shards, **kwargs)

    def _merged(self, attr: str) -> dict[str, float]:
        out: dict[str, float] = {}
        for shard in self.shards:
            out.update(getattr(shard, attr, {}))
        return out

    @property
    def granted_at(self) -> dict[str, float]:
        return self._merged("granted_at")

    @property
    def settled_at(self) -> dict[str, float]:
        return self._merged("settled_at")

    @property
    def flights(self) -> _StitchedFlights:
        return _StitchedFlights(self)


class SyntheticExecutor:
    """Executor seam stand-in with deterministic per-workload service
    times (the load-harness analog of ChaoticExecutor: exercises the
    REAL worker — poll loop, queues, shed gate, backpressure, uploads —
    without compiling a pipeline). Service = the job workload's base
    time x a seeded jitter factor, reproducible per (job, attempt)."""

    def __init__(self, profiles: Sequence[WorkloadProfile] =
                 DEFAULT_PROFILES, *, jitter: float = 0.3,
                 seed: Any = "swarmload") -> None:
        self.service_s = {p.name: p.service_s for p in profiles}
        self.default_s = min(self.service_s.values(), default=0.1)
        self.jitter = max(0.0, min(0.9, float(jitter)))
        self.seed = seed
        self.attempts: dict[str, int] = {}
        self.executed: list[str] = []

    def _service(self, job: dict[str, Any]) -> float:
        job_id = str(job.get("id"))
        attempt = self.attempts.get(job_id, 0) + 1
        self.attempts[job_id] = attempt
        rng = random.Random(f"svc:{self.seed}:{job_id}:{attempt}")
        base = self.service_s.get(str(job.get("workflow") or "txt2img"),
                                  self.default_s)
        return base * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))

    async def _run_one(self, job: dict[str, Any]) -> dict[str, Any]:
        # the synthetic service time stands in for the denoise loop, so
        # it records as a "step" span under the job's execute phase —
        # the flight record's budget attribution (ISSUE 13) then books
        # it as steps, not unattributed residue. Manual child (not
        # span()): custom executors run on the event loop where the
        # trace contextvar is never activated.
        trace = obs_trace.job_trace(job)
        step = trace.tail().child("step") if trace is not None else None
        await asyncio.sleep(self._service(job))
        if step is not None:
            step.end()
        self.executed.append(str(job.get("id")))
        return {
            "id": job.get("id"),
            "artifacts": {"primary": make_text_result(
                f"load ok: {job.get('id')}")},
            "nsfw": False,
            "worker_version": "loadgen",
            "pipeline_config": {
                "workload": str(job.get("workflow") or "txt2img"),
                "attempt": self.attempts.get(str(job.get("id")), 1)},
        }

    async def do_work(self, job, slot, registry) -> dict:
        return await self._run_one(job)

    async def do_work_batch(self, jobs, slot, registry) -> list[dict]:
        return [await self._run_one(job) for job in jobs]


def default_worker_factory(profiles: Sequence[WorkloadProfile] =
                           DEFAULT_PROFILES, seed: Any = "swarmload",
                           **settings_over: Any) -> Callable[[str, str],
                                                             Any]:
    """A factory building overload-controlled synthetic workers — the
    harness default. Callers with real pipelines pass their own
    ``worker_factory(uri, name) -> Worker`` instead."""
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.node.settings import Settings
    from chiaswarm_tpu.node.worker import Worker

    class _StubSlot:
        # deeper than the chip-pool default: the worker's work_queue
        # bound is the slot depth, and backpressure needs a few queued
        # jobs' drain estimate to meaningfully exceed its budget
        depth = 6
        data_width = 1

        def __init__(self, name: str) -> None:
            self.name = name

        def descriptor(self) -> str:
            return self.name

    def factory(uri: str, name: str):
        base = dict(
            hive_uri=uri, hive_token="t", worker_name=name,
            poll_busy_s=0.02, poll_idle_s=0.05,
            poll_backoff_base_s=0.02, poll_backoff_cap_s=0.2,
            upload_retries=5, upload_retry_delay_s=0.02,
            transient_retries=1, retry_backoff_s=0.01,
            retry_backoff_cap_s=0.05,
            drain_timeout_s=10.0, result_drain_timeout_s=10.0,
            install_signal_handlers=False,
            heartbeat_s=0.1,
            overload_control=True,
            # the execution cap stays generous (it is the PR-2 timeout
            # envelope, not the admission budget); backpressure keys on
            # the harness's seconds-scale job deadlines instead
            job_deadline_s=30.0,
            backpressure_s=0.5,
            # shed with headroom: the estimator cannot see the next
            # poll's latency or ack jitter, and an admitted job that
            # misses by 50 ms still misses — 0.8 holds zero deadline
            # violations across the seeded 10x + worker-kill runs
            overload_margin=0.8,
        )
        base.update(settings_over)
        return Worker(settings=Settings(**base),
                      pool=[_StubSlot(name)],
                      registry=ModelRegistry(catalog=[],
                                             allow_random=True),
                      executor=SyntheticExecutor(profiles, seed=seed))

    return factory


@dataclasses.dataclass(frozen=True)
class KillPlan:
    """Scripted mid-run worker kill: once ``after_frac`` of the
    schedule has been submitted, the first worker holding a lease is
    partitioned, cancelled, and lease-revoked (the PR-6 preemption
    path) — its jobs redeliver to the survivors."""

    after_frac: float = 0.5


@dataclasses.dataclass(frozen=True)
class RosterPlan:
    """Scripted fleet churn (ROADMAP item 5 residue, ISSUE 14
    satellite): workers JOIN and LEAVE mid-run, exercising the capacity
    model and ``GET /api/fleet`` under elastic rosters rather than only
    kills. Each entry is a fraction of the schedule: at ``join_at``
    fractions a NEW worker (from the same factory) starts polling; at
    ``leave_at`` fractions one running worker drains GRACEFULLY
    (request_stop — in-flight jobs complete and upload; nothing
    redelivers) and leaves. Distinct from :class:`KillPlan` on purpose:
    an autoscaler's scale-down is a drain, not a preemption."""

    join_at: tuple[float, ...] = ()
    leave_at: tuple[float, ...] = ()


@dataclasses.dataclass(frozen=True)
class AutoscalePlan:
    """swarmplan (ISSUE 19): run the fleet ELASTICALLY under the
    hive-side :class:`~chiaswarm_tpu.node.planner.FleetPlanner` instead
    of a scripted roster. The harness starts ``min_workers``, ticks the
    planner every ``tick_every_s`` wall seconds, and actuates its
    decisions through the SAME seams a real deployment uses: scale-up
    spawns workers from the run's factory (the supervisor leg —
    real deployments poll ``GET /api/plan``); scale-down drains
    gracefully (``request_stop`` + lease preemption via
    ``expire_worker`` — never the kill path; mid-lane rows checkpoint
    and redeliver-with-resume to survivors). The remaining fields are
    :class:`~chiaswarm_tpu.node.planner.PlannerConfig` passthrough."""

    min_workers: int = 1
    max_workers: int = 6
    tick_every_s: float = 0.25
    target_utilization: float = 0.6
    smoothing_window_s: float = 2.0
    hysteresis: float = 0.15
    cooldown_up_s: float = 0.5
    cooldown_down_s: float = 2.5
    backlog_drain_s: float = 2.0
    capacity_jobs_s_per_worker: float = 6.0
    capacity_alpha: float = 0.3
    replicate_max: int = 3

    def planner_config(self):
        from chiaswarm_tpu.node.planner import PlannerConfig

        return PlannerConfig(
            min_workers=int(self.min_workers),
            max_workers=int(self.max_workers),
            target_utilization=float(self.target_utilization),
            smoothing_window_s=float(self.smoothing_window_s),
            hysteresis=float(self.hysteresis),
            cooldown_up_s=float(self.cooldown_up_s),
            cooldown_down_s=float(self.cooldown_down_s),
            backlog_drain_s=float(self.backlog_drain_s),
            capacity_jobs_s_per_worker=float(
                self.capacity_jobs_s_per_worker),
            capacity_alpha=float(self.capacity_alpha),
            replicate_max=int(self.replicate_max),
        )


class ContentionProbe:
    """Host-contention sampler (ISSUE 12, promoted to a reusable class
    for the ISSUE 17 guard-gate deflake): a daemon THREAD measures how
    late ``time.sleep`` fires while a harness runs (~1.0 on an idle
    host). Timing gates bound their clauses against the measured
    factor instead of absolute wall clock, so a contended CI host
    loosens a bound by exactly the measured sleep stretch — never by
    an arbitrary fudge. Deliberately NOT an asyncio task on the
    harness loop: loop lag caused by the code under test must count
    against the gate, not loosen it — the thread sees only host-level
    scheduling delay."""

    def __init__(self, tick_s: float = 0.02) -> None:
        self.tick_s = max(1e-4, float(tick_s))
        self.overshoots: list[float] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._sample, name="contention-probe", daemon=True)

    def _sample(self) -> None:
        while not self._stop.is_set():
            t0 = time.perf_counter()
            time.sleep(self.tick_s)
            self.overshoots.append(
                (time.perf_counter() - t0) / self.tick_s)

    def start(self) -> "ContentionProbe":
        self._thread.start()
        return self

    def stop(self) -> float:
        """Stop sampling; returns the factor (callers may also keep
        reading :attr:`factor` afterwards)."""
        self._stop.set()
        self._thread.join(timeout=1.0)
        return self.factor

    @property
    def factor(self) -> float:
        """p90 sleep overshoot, floored at 1.0 (a bound scaled by this
        can loosen under contention but never tighten below nominal)."""
        if not self.overshoots:
            return 1.0
        return max(1.0, percentile(self.overshoots, 0.9))

    def report(self) -> dict[str, Any]:
        return {
            "sleep_overshoot_p90": (round(
                percentile(self.overshoots, 0.9), 4)
                if self.overshoots else 1.0),
            "samples": len(self.overshoots),
            "factor": round(self.factor, 4),
        }


async def run_load(schedule: Sequence[ScheduledJob], *,
                   n_workers: int = 3,
                   n_shards: int = 1,
                   worker_factory: Callable[[str, str], Any] | None = None,
                   hive: LoadHive | None = None,
                   lease_s: float = 5.0,
                   max_jobs_per_poll: int = 2,
                   max_attempts: int = 4,
                   kill: KillPlan | None = None,
                   roster: "RosterPlan | None" = None,
                   autoscale: "AutoscalePlan | None" = None,
                   on_submit: Callable[[int, Any], Any] | None = None,
                   time_scale: float = 1.0,
                   settle_timeout_s: float = 300.0,
                   seed: Any = "swarmload") -> dict[str, Any]:
    """Drive ``schedule`` through a LoadHive + ``n_workers`` Workers;
    returns :func:`score_run`'s report (plus the kill record). The
    harness owns worker lifecycle end to end — every worker drains (or
    is killed by plan) before scoring.

    ``n_shards > 1`` (swarmfed, ISSUE 17) drives the SAME schedule
    through a :class:`FederatedLoadHive` instead: jobs route by the
    stable hash, workers multiplex one session per shard (the
    comma-joined shard uris parse back through Settings.hive_uris),
    and idle shards steal from deep ones — the report's reconciliation
    and latency folds are fleet-wide.

    ``autoscale`` (swarmplan, ISSUE 19) replaces the static roster with
    the planner loop: ``n_workers`` is ignored, the fleet starts at
    ``autoscale.min_workers`` and grows/shrinks per planning tick.
    Every run (elastic or static) reports ``worker_time`` — summed
    worker lifetime seconds — so the autoscaler gate can compare
    worker-hours against static rosters on equal terms."""
    if hive is None:
        if int(n_shards) > 1:
            hive = FederatedLoadHive(
                int(n_shards), lease_s=lease_s, delay_s=0.0,
                max_attempts=max_attempts,
                max_jobs_per_poll=max_jobs_per_poll)
        else:
            hive = LoadHive(lease_s=lease_s, delay_s=0.0,
                            max_attempts=max_attempts,
                            max_jobs_per_poll=max_jobs_per_poll)
    factory = worker_factory or default_worker_factory(seed=seed)
    uri = await hive.start()
    if hasattr(hive, "worker_uri"):  # federation: workers dial shards
        uri = hive.worker_uri()
    initial_n = (max(1, int(autoscale.min_workers))
                 if autoscale is not None else max(1, int(n_workers)))
    workers = [factory(uri, f"load-{seed}-w{i}")
               for i in range(initial_n)]
    # per-worker lifetime ledger (swarmplan): every task stamps its
    # start at creation and its stop via done-callback, so the report's
    # worker-hours mean the same thing for static and elastic fleets
    worker_started: dict[str, float] = {}
    worker_stopped: dict[str, float] = {}
    tasks: dict[str, asyncio.Task] = {}

    def _track(name: str, task: "asyncio.Task") -> "asyncio.Task":
        worker_started[name] = time.perf_counter()
        task.add_done_callback(
            lambda _t, n=name: worker_stopped.setdefault(
                n, time.perf_counter()))
        tasks[name] = task
        return task

    for w in workers:
        _track(w.settings.worker_name, asyncio.create_task(w.run()))
    ordered = sorted(schedule, key=lambda s: s.at_s)
    issued = [str(s.job["id"]) for s in ordered]
    kill_at = (math.ceil(len(ordered) * max(0.0, min(1.0,
                                                     kill.after_frac)))
               if kill is not None else None)
    killed: dict[str, Any] = {}
    # fleet churn (ISSUE 14 satellite): scripted joins/leaves become
    # per-index thresholds like the kill plan; events are recorded for
    # the report so a soak can assert the churn actually happened
    def _fracs_to_indices(fracs) -> list[int]:
        return sorted(math.ceil(len(ordered) * max(0.0, min(1.0, f)))
                      for f in (fracs or ()))

    joins_due = _fracs_to_indices(roster.join_at if roster else ())
    leaves_due = _fracs_to_indices(roster.leave_at if roster else ())
    roster_events: list[dict[str, Any]] = []
    joined_n = 0
    departed: set[str] = set()
    t_start = time.perf_counter()

    # contention probe (ISSUE 12 deflake): the harness runs on real
    # wall clocks, so a contended CI host stretches every latency in
    # the report — including the deadline-conformance numbers the
    # acceptance gate asserts on; the gate bounds latency ratios
    # against the measured factor instead of absolute wall clock.
    probe = ContentionProbe().start()

    async def maybe_kill() -> None:
        # first leaseholder found after the threshold dies NOW:
        # partition (nothing it uploads lands) + cancel (the process
        # "dies") + expire (the preemption notice redelivers its jobs)
        for worker in workers:
            name = worker.settings.worker_name
            leased = hive.leased_ids(name)
            if leased:
                killed.update(worker=name, jobs=list(leased))
                hive.partition(name)
                tasks[name].cancel()
                await asyncio.gather(tasks[name], return_exceptions=True)
                hive.expire_worker(name)
                log.warning("load kill: %s (held %d lease(s))", name,
                            len(leased))
                return

    async def apply_roster(done: int) -> None:
        nonlocal joined_n
        while joins_due and done >= joins_due[0]:
            joins_due.pop(0)
            joined_n += 1
            name = f"load-{seed}-join{joined_n}"
            worker = factory(uri, name)
            workers.append(worker)
            _track(name, asyncio.create_task(worker.run()))
            roster_events.append({"at_job": done, "action": "join",
                                  "worker": name})
            log.info("roster: %s joined after %d submissions", name,
                     done)
        while leaves_due and done >= leaves_due[0]:
            # first worker still serving (never killed, never left)
            candidate = next(
                (w for w in workers
                 if w.settings.worker_name not in departed
                 and w.settings.worker_name != killed.get("worker")),
                None)
            if candidate is None:
                leaves_due.clear()
                break
            leaves_due.pop(0)
            name = candidate.settings.worker_name
            departed.add(name)
            candidate.request_stop()  # graceful: drains, uploads, exits
            # shield: a slow drain must NOT be cancelled into a covert
            # kill (that would redeliver its jobs and contradict the
            # clean "leave" this records) — on timeout the worker keeps
            # draining and the final cleanup reaps it; the event says so
            drained = True
            try:
                await asyncio.wait_for(asyncio.shield(tasks[name]),
                                       timeout=60)
            except Exception:
                drained = tasks[name].done()
            roster_events.append({"at_job": done, "action": "leave",
                                  "worker": name, "drained": drained})
            log.info("roster: %s %s after %d submissions", name,
                     "drained and left" if drained
                     else "leaving (drain still in progress)", done)

    # swarmplan (ISSUE 19): the observe -> decide -> actuate loop. The
    # planner only DECIDES; this harness is the actuator — the same
    # division a real deployment has, where a supervisor polls
    # GET /api/plan and runs the container orchestration.
    planner = None
    auto_task: asyncio.Task | None = None
    auto_events: list[dict[str, Any]] = []
    auto_sizes: list[list[float]] = []
    auto_drains: dict[str, asyncio.Task] = {}
    auto_spawned = 0
    if autoscale is not None:
        from chiaswarm_tpu.node.planner import FleetPlanner

        planner = FleetPlanner(hive, autoscale.planner_config())

        def _spawn_auto(count: int) -> None:
            nonlocal auto_spawned
            for _ in range(count):
                auto_spawned += 1
                name = f"load-{seed}-auto{auto_spawned}"
                worker = factory(uri, name)
                workers.append(worker)
                _track(name, asyncio.create_task(worker.run()))
                log.info("autoscale: spawned %s", name)

        async def _drain_auto(name: str) -> None:
            # graceful scale-down, NEVER the kill path: stop polling
            # (in-flight work checkpoints and uploads), then preempt
            # the leases so mid-lane rows redeliver-with-resume to
            # survivors; the hive's exactly-once settle dedupes the
            # race between the victim's final upload and the resume
            worker = next((w for w in workers
                           if w.settings.worker_name == name), None)
            if worker is not None:
                worker.request_stop()
            hive.expire_worker(name)
            task = tasks.get(name)
            if task is not None:
                try:
                    await asyncio.wait_for(asyncio.shield(task),
                                           timeout=60)
                except Exception:
                    pass
            log.info("autoscale: drained %s", name)

        async def _autoscale_loop() -> None:
            while True:
                await asyncio.sleep(max(1e-3,
                                        float(autoscale.tick_every_s)))
                decision = planner.tick()
                rel_s = round(time.perf_counter() - t_start, 3)
                auto_sizes.append([rel_s, int(decision["actual"])])
                if decision["direction"] == "up" and decision["spawn"]:
                    # spawn against the HARNESS's liveness ledger, not
                    # the snapshot's: freshly spawned workers take a
                    # heartbeat to register, and re-spawning for them
                    # would overshoot the target
                    alive = sum(
                        1 for w in workers
                        if w.settings.worker_name not in departed
                        and w.settings.worker_name != killed.get(
                            "worker"))
                    _spawn_auto(min(int(decision["spawn"]),
                                    max(0, int(decision["target"])
                                        - alive)))
                elif decision["direction"] == "down":
                    for name in decision["drain"]:
                        if name in departed or name in auto_drains:
                            continue
                        departed.add(name)
                        auto_drains[name] = asyncio.create_task(
                            _drain_auto(name))
                if decision["direction"] != "hold":
                    auto_events.append({
                        "rel_s": rel_s,
                        **{k: decision[k] for k in (
                            "direction", "reason", "target", "actual",
                            "spawn", "drain")},
                    })

        auto_task = asyncio.create_task(_autoscale_loop())

    try:
        for i, item in enumerate(ordered):
            target = t_start + item.at_s * max(1e-3, float(time_scale))
            delay = target - time.perf_counter()
            if delay > 0:
                await asyncio.sleep(delay)
            hive.submit_job(dict(item.job))
            if kill_at is not None and not killed and i + 1 >= kill_at:
                await maybe_kill()
            await apply_roster(i + 1)
            if on_submit is not None:
                # scripted mid-run chaos seam (the swarmplan soak kills
                # and recovers a shard through it); awaited so the hook
                # can run kill/restart cycles inline with submission
                maybe_coro = on_submit(i + 1, hive)
                if asyncio.iscoroutine(maybe_coro):
                    await maybe_coro
        if kill_at is not None and not killed:
            await maybe_kill()
        await apply_roster(len(ordered))

        deadline = time.monotonic() + float(settle_timeout_s)
        while time.monotonic() < deadline:
            hive.sweep()
            done = sum(1 for job_id in issued
                       if job_id in hive.completed
                       or job_id in hive.abandoned)
            if done >= len(issued):
                break
            await asyncio.sleep(0.05)
    finally:
        duration_s = time.perf_counter() - t_start
        probe.stop()
        if auto_task is not None:
            auto_task.cancel()
            await asyncio.gather(auto_task, return_exceptions=True)
        for worker in workers:
            worker.request_stop()
        await asyncio.gather(*(asyncio.wait_for(t, timeout=30)
                               for t in tasks.values()),
                             return_exceptions=True)
        if auto_drains:
            await asyncio.gather(*auto_drains.values(),
                                 return_exceptions=True)
        await hive.stop()

    report = score_run(hive, issued, workers, ordered,
                       duration_s=duration_s)
    report["kill"] = killed or None
    # fleet-churn record (ISSUE 14 satellite): the roster satellite's
    # proof that /api/fleet (score_run's "fleet" stamp) and the
    # capacity model saw an ELASTIC fleet, not a static one
    report["roster"] = roster_events or None
    # measured host-contention factor (>= 1.0; ~1.0 idle). The gate's
    # contention-adjusted deadline clause scales its bound by this, so
    # a contended host loosens the bound by exactly the measured sleep
    # stretch — never by an arbitrary fudge.
    report["contention"] = probe.report()
    ad = report["admitted_deadline"]
    ad["p99_within_deadline_contention_adjusted"] = bool(
        ad["p99_latency_over_deadline"] <= probe.factor)
    # worker-hours ledger (swarmplan): the cost axis of the autoscaler
    # gate — stamped for EVERY run so static rosters and the elastic
    # fleet compare on identical accounting
    end_t = time.perf_counter()
    per_worker = {
        name: round(max(0.0, worker_stopped.get(name, end_t) - t0), 3)
        for name, t0 in sorted(worker_started.items())}
    total_s = sum(per_worker.values())
    report["worker_time"] = {
        "worker_seconds": round(total_s, 3),
        "worker_hours": round(total_s / 3600.0, 6),
        "peak_workers": len(per_worker),
        "per_worker": per_worker,
    }
    if autoscale is not None:
        report["autoscale"] = {
            "plan": dataclasses.asdict(autoscale),
            "events": auto_events,
            "sizes": auto_sizes,
            "ticks": planner.ticks,
            "decision": planner.last_decision,
            "drained": sorted(auto_drains),
        }
    else:
        report["autoscale"] = None
    return report


def _comparison_row(label: Any, report: dict[str, Any]) -> dict[str, Any]:
    """One row of the autoscaler comparison table: service quality
    (zero-loss, ok count, shed fraction, contention-adjusted deadline
    conformance) on one side, worker-hours on the other."""
    rec = report["reconciliation"]
    out = report["outcomes"]
    ad = report["admitted_deadline"]
    issued = max(1, int(rec["issued"]))
    return {
        "config": label,
        "zero_loss": bool(rec["zero_loss"]),
        "ok": int(out.get("ok", 0)),
        "shed_frac": round(int(out.get("shed", 0)) / issued, 4),
        "abandoned": int(out.get("abandoned", 0)),
        "p99_latency_over_deadline": ad["p99_latency_over_deadline"],
        "p99_ok": bool(ad["p99_within_deadline_contention_adjusted"]),
        "worker_seconds": report["worker_time"]["worker_seconds"],
        "worker_hours": report["worker_time"]["worker_hours"],
        "peak_workers": report["worker_time"]["peak_workers"],
    }


async def autoscale_comparison(schedule: Sequence[ScheduledJob], *,
                               autoscale: AutoscalePlan,
                               static_rosters: Sequence[int],
                               n_shards: int = 1,
                               seed: Any = "swarmplan",
                               shed_slack: float = 0.02,
                               **run_kwargs: Any) -> dict[str, Any]:
    """THE swarmplan headline (ISSUE 19 gate + BENCH ``autoscaler``
    config): drive the SAME seeded schedule once under the planner and
    once per static roster size, then compare worker-hours among the
    rosters that actually served the traffic.

    A static roster is **feasible** when it settles with zero loss, its
    admitted p99 sits within deadline (contention-adjusted, the PR-12
    clause), and its shed fraction is no worse than the planner's plus
    ``shed_slack`` — the last clause keeps a tiny roster that sheds
    half the peak from "winning" on hours while silently serving less
    traffic (shed fractions compare stably across host speeds, where
    raw ok counts wobble with planner ramp timing). The gate claim is:
    planner worker-hours STRICTLY below the cheapest feasible static
    roster, at equal-or-better service."""
    planner_report = await run_load(schedule, autoscale=autoscale,
                                    n_shards=n_shards, seed=seed,
                                    **run_kwargs)
    planner_row = _comparison_row("autoscaler", planner_report)
    static_rows: list[dict[str, Any]] = []
    for n in static_rosters:
        static_report = await run_load(schedule, n_workers=int(n),
                                       n_shards=n_shards,
                                       seed=f"{seed}-static{n}",
                                       **run_kwargs)
        static_rows.append(_comparison_row(int(n), static_report))
    feasible = [row for row in static_rows
                if row["zero_loss"] and row["p99_ok"]
                and row["shed_frac"]
                <= planner_row["shed_frac"] + float(shed_slack)]
    best_static = (min(feasible, key=lambda r: r["worker_seconds"])
                   if feasible else None)
    gate = {
        "planner_zero_loss": planner_row["zero_loss"],
        "planner_p99_ok": planner_row["p99_ok"],
        "feasible_static": sorted(r["config"] for r in feasible),
        "best_static": (best_static or {}).get("config"),
        "best_static_worker_seconds":
            (best_static or {}).get("worker_seconds"),
        "planner_worker_seconds": planner_row["worker_seconds"],
        "planner_beats_best_static": bool(
            best_static is not None
            and planner_row["worker_seconds"]
            < best_static["worker_seconds"]),
    }
    return {
        "planner": planner_row,
        "static": static_rows,
        "gate": gate,
        "planner_report": planner_report,
    }


# ---------------------------------------------------------------------------
# scoring + the capacity model
# ---------------------------------------------------------------------------


def reconcile(hive: MiniHive, issued: Iterable[str]) -> dict[str, Any]:
    """THE zero-loss check: every issued job settled exactly once —
    completed (success or final error envelope) XOR abandoned-by-policy
    — and the settle lists carry no duplicates. Shared by the scorer,
    the acceptance gate, and the reconciliation tests."""
    issued = [str(j) for j in issued]
    completed = set(hive.completed)
    abandoned = set(hive.abandoned)
    uploaded = hive.uploaded_ids()
    missing = [j for j in issued if j not in completed
               and j not in abandoned]
    double = [j for j in issued if j in completed and j in abandoned]
    return {
        "issued": len(issued),
        "completed": len([j for j in issued if j in completed]),
        "abandoned": len([j for j in issued if j in abandoned]),
        "duplicate_uploads_acked": len(hive.duplicate_results),
        "missing": missing,
        "settled_twice": double,
        "result_list_unique": len(uploaded) == len(set(uploaded)),
        "zero_loss": (not missing and not double
                      and len(uploaded) == len(set(uploaded))),
    }


def _worker_snapshot(worker: Any) -> dict[str, Any]:
    stats = worker.stats.snapshot()
    stepper = worker._stepper_health()
    breakers = worker.breakers.states()
    snap = {
        "jobs_shed": stats.get("jobs_shed", 0),
        "polls_backpressured": stats.get("polls_backpressured", 0),
        "jobs_failed": stats.get("jobs_failed", 0),
        "jobs_timed_out": stats.get("jobs_timed_out", 0),
        "lane_occupancy": stepper.get("lane_occupancy", 0.0),
        "padding_waste": stepper.get("padding_waste", 0.0),
        "lane_resizes": stepper.get("lane_resizes", 0),
        "breaker_trips": sum(1 for b in breakers.values()
                             if b.get("state") != "closed"),
        "overload": worker.overload.snapshot(),
    }
    residency = getattr(worker.registry, "residency", None)
    if residency is not None:
        try:
            r = residency.snapshot()
            snap["residency"] = {
                "resident_models": len(r.get("resident_models", [])),
                "resident_bytes": r.get("resident_bytes", 0),
                "evictions": r.get("evictions", 0),
            }
        except Exception:  # stub registries
            pass
    return snap


def score_run(hive: LoadHive, issued: Sequence[str], workers: Sequence[Any],
              schedule: Sequence[ScheduledJob], *,
              duration_s: float) -> dict[str, Any]:
    """Fold one run into the report: settlement reconciliation, outcome
    buckets, per-workload latency percentiles, admitted-deadline
    conformance, worker snapshots, and the capacity model."""
    workload_by_id = {str(s.job["id"]): s.workload for s in schedule}
    deadline_by_id = {str(s.job["id"]): float(s.job.get("deadline_s") or 0)
                      for s in schedule}
    family_by_id = {str(s.job["id"]): model_family(s.job.get("model_name"))
                    for s in schedule}
    family_latencies: dict[str, list[float]] = {}
    # deadline-budget attribution (swarmsight, ISSUE 13): per-family
    # phase decompositions folded from the hive's flight records — one
    # bucket over every completed job, one over the deadline MISSES so
    # the conformance report can name the dominant overshoot phase
    flights = getattr(hive, "flights", None)
    fam_attr: dict[str, dict[str, list[float]]] = {}
    fam_miss_attr: dict[str, dict[str, list[float]]] = {}
    outcomes = {"ok": 0, "shed": 0, "abandoned": len(hive.abandoned)}
    end_to_end: dict[str, list[float]] = {}
    admitted: dict[str, list[float]] = {}
    deadline_ratios: list[float] = []
    deadline_violations: list[str] = []
    admitted_latencies: list[float] = []
    for job_id, result in hive.completed.items():
        kind = classify_result(result)
        if kind == "ok":
            outcomes["ok"] += 1
        elif kind == "overloaded":
            outcomes["shed"] += 1
        else:
            outcomes[kind] = outcomes.get(kind, 0) + 1
        workload = workload_by_id.get(job_id, "unknown")
        submitted = hive.submitted_at.get(job_id)
        granted = hive.granted_at.get(job_id)
        settled = hive.settled_at.get(job_id)
        if settled is None:
            continue
        if submitted is not None:
            end_to_end.setdefault(workload, []).append(settled - submitted)
        if kind != "ok":
            continue
        if granted is not None:
            latency = settled - granted
            admitted.setdefault(workload, []).append(latency)
            admitted_latencies.append(latency)
        if submitted is not None:
            family = family_by_id.get(job_id, "sd15")
            family_latencies.setdefault(family, []).append(
                settled - submitted)
            attribution = None
            if flights is not None:
                record = flights.get(job_id)
                attribution = (record or {}).get("attribution")
            if attribution:
                bucket = fam_attr.setdefault(family, {})
                for phase, seconds in attribution["phases"].items():
                    bucket.setdefault(phase, []).append(float(seconds))
            # deadline conformance is END TO END (submit -> settle):
            # queue age rides every delivery as "queued_s", so a worker
            # that admits a stale job owns the whole budget it spent.
            # Pooled as latency/deadline RATIOS: workloads carry
            # different deadlines, and the ratio normalizes them into
            # ONE p99 over all admitted jobs (per-workload p99 with a
            # handful of samples degenerates to the max).
            e2e = settled - submitted
            deadline = deadline_by_id.get(job_id, 0.0)
            if deadline:
                deadline_ratios.append(e2e / deadline)
                if e2e > deadline:
                    deadline_violations.append(job_id)
                    if attribution:
                        miss = fam_miss_attr.setdefault(family, {})
                        for phase, seconds in \
                                attribution["phases"].items():
                            miss.setdefault(phase, []).append(
                                float(seconds))

    def fold(samples: dict[str, list[float]]) -> dict[str, dict]:
        return {w: {"p50": round(percentile(v, 0.50), 4),
                    "p99": round(percentile(v, 0.99), 4),
                    "n": len(v)}
                for w, v in sorted(samples.items())}

    def attribution_table(samples: dict[str, dict[str, list[float]]]
                          ) -> dict[str, dict]:
        """Per-family budget-attribution table: mean seconds + share
        per phase, plus the argmax phase (ISSUE 13 — the table the
        BENCH load_harness config stamps)."""
        table: dict[str, dict] = {}
        for family, phases in sorted(samples.items()):
            mean = {phase: round(sum(vals) / max(1, len(vals)), 4)
                    for phase, vals in sorted(phases.items())}
            total = sum(mean.values())
            table[family] = {
                "n": max((len(v) for v in phases.values()), default=0),
                "mean_s": mean,
                "share": {phase: round(v / total, 4) if total else 0.0
                          for phase, v in mean.items()},
                # None when nothing was measured: an argmax over
                # all-zero means would crown the first phase and send
                # an operator chasing a queue that never dominated
                "dominant_phase": (max(
                    ATTRIBUTION_PHASES,
                    key=lambda p: mean.get(p, 0.0)) if total else None),
            }
        return table

    mix: dict[str, int] = {}
    for item in schedule:
        mix[item.workload] = mix.get(item.workload, 0) + 1
    chips = sum(int(getattr(slot, "data_width", 1) or 1)
                for worker in workers for slot in worker.pool)
    models_resident = 0
    for worker in workers:
        residency = getattr(worker.registry, "residency", None)
        if residency is not None:
            try:
                models_resident = max(
                    models_resident,
                    len(residency.snapshot().get("resident_models", [])))
            except Exception:
                pass
    if not models_resident:
        models_resident = len({s.job.get("model_name")
                               for s in schedule})
    completed_ok = outcomes["ok"]
    duration_s = max(1e-6, float(duration_s))
    report = {
        "reconciliation": reconcile(hive, issued),
        "outcomes": outcomes,
        "offered": {
            "jobs": len(schedule),
            "duration_s": round(duration_s, 3),
            "rate_jobs_s": round(len(schedule) / duration_s, 3),
            "workload_mix": {w: round(n / max(1, len(schedule)), 4)
                             for w, n in sorted(mix.items())},
        },
        "latency_s": {
            "end_to_end": fold(end_to_end),
            "admitted": fold(admitted),
        },
        "admitted_deadline": {
            "violations": len(deadline_violations),
            "violating_ids": deadline_violations[:10],
            # THE acceptance bound: p99 of end-to-end latency/deadline
            # over every ADMITTED (completed-ok) job must sit at <= 1
            "p99_latency_over_deadline": round(
                percentile(deadline_ratios, 0.99), 4),
            "p99_within_deadline":
                percentile(deadline_ratios, 0.99) <= 1.0,
        },
        # per-model-family deadline derivation (ISSUE 10 satellite,
        # ROADMAP 5b): measured p99 of completed-ok end-to-end latency
        # per family x the margin — the table an operator copies into
        # the ``family_deadline_s`` settings map. The SHIPPED defaults
        # come from the pure sweep (sweep_deadline_table, pinned by
        # test); this is the live-measurement refinement of them.
        "suggested_deadlines": {
            "margin": DEADLINE_MARGIN,
            "families": {
                family: {
                    "p99_s": round(percentile(values, 0.99), 4),
                    "suggested_s": round(
                        percentile(values, 0.99) * DEADLINE_MARGIN, 4),
                    "n": len(values),
                }
                for family, values in sorted(family_latencies.items())
            },
        },
        # measured watchdog-knob suggestion (swarmlens, ISSUE 11): from
        # the process-global step-seconds histogram — populated by runs
        # that drive REAL lanes (the nightly real-lane soak); synthetic
        # executors step no lanes, so those runs report measured=False
        # rather than inventing numbers from simulated service times
        "suggested_hang_budget": _suggest_hang_budget(),
        # per-family deadline-BUDGET attribution (swarmsight, ISSUE 13):
        # where each family's end-to-end seconds actually went, folded
        # from the flight records; misses get their own table so a p99
        # overshoot names a phase, not just a number
        "budget_attribution": {
            "families": attribution_table(fam_attr),
            "misses": attribution_table(fam_miss_attr),
        },
        # the /api/fleet aggregate at scoring time — the observed data
        # plane (arrival rates, occupancy, chips, residency, overload)
        # the ROADMAP item-5 autoscaler consumes
        "fleet": (hive.fleet_snapshot()
                  if hasattr(hive, "fleet_snapshot") else None),
        "workers": {w.settings.worker_name: _worker_snapshot(w)
                    for w in workers},
        "hive": hive.stats(),
        "capacity": {
            "chips": chips,
            "jobs_per_s_per_chip": round(
                completed_ok / duration_s / max(1, chips), 4),
            "admitted_p99_s": round(percentile(admitted_latencies, 0.99),
                                    4),
            "models_resident": models_resident,
            "workload_mix": {w: round(n / max(1, len(schedule)), 4)
                             for w, n in sorted(mix.items())},
        },
    }
    # the deadline-conformance satellite (ISSUE 13): each family's p99
    # miss points at a PHASE — the miss-table argmax rides next to the
    # suggested deadline so "raise the budget" and "fix the phase" are
    # distinguishable actions
    for family, entry in report["suggested_deadlines"]["families"].items():
        miss = report["budget_attribution"]["misses"].get(family)
        entry["dominant_overshoot_phase"] = (miss["dominant_phase"]
                                             if miss else None)
    return report


# ---------------------------------------------------------------------------
# tuning sweeps (pure host simulation — the harness's arrival model
# replayed through the controllers; no jax, fully deterministic)
# ---------------------------------------------------------------------------


def arrival_trace(curve: DiurnalCurve, *, boundaries: int,
                  mean_rows: float, seed: Any) -> list[int]:
    """Rows arriving at each of ``boundaries`` step boundaries: seeded
    Poisson draws scaled by the curve — the discrete twin of
    :func:`generate_schedule` at lane-step resolution."""
    rng = random.Random(f"trace:{seed}")
    out = []
    for b in range(max(1, int(boundaries))):
        lam = mean_rows * curve.multiplier(b / max(1, boundaries - 1))
        # inverse-CDF Poisson (stdlib-only, fine for small lambda)
        x, p, s = 0, math.exp(-lam), math.exp(-lam)
        u = rng.random()
        while u > s and x < 1000:
            x += 1
            p *= lam / x
            s += p
        out.append(x)
    return out


def simulate_lane_controller(*, grow_at: float, shrink_at: float,
                             patience: int, trace: Sequence[int],
                             steps_per_row: int = 12,
                             max_width: int = 16) -> dict[str, float]:
    """Replay one arrival trace through a synthetic lane driven by
    :class:`~chiaswarm_tpu.serving.stepper.LaneWidthController`:
    rows admitted up to the width each boundary run ``steps_per_row``
    boundaries, the controller decides between dispatches. Scored on
    the two costs the gains trade off — padded row-steps (batched UNet
    FLOPs burned) and queue wait (rows x boundaries spent pending)."""
    from chiaswarm_tpu.serving.stepper import LaneWidthController

    ctl = LaneWidthController(min_width=1, max_width=max_width,
                              grow_at=grow_at, shrink_at=shrink_at,
                              patience=patience)
    width = 2
    resident: list[int] = []   # remaining steps per occupied row
    pending = 0
    padded = active = waited = resizes = 0
    for b, arriving in enumerate(list(trace) + [0] * steps_per_row):
        pending += int(arriving)
        free = width - len(resident)
        admit = min(pending, free)
        resident.extend([steps_per_row] * admit)
        pending -= admit
        if resident:
            active += len(resident)
            padded += width - len(resident)
            resident = [r - 1 for r in resident if r > 1]
        waited += pending
        target = ctl.decide(width, len(resident), pending, float(arriving))
        if target != width:
            resizes += 1
            width = target
    denom = max(1, active + padded)
    return {
        "padding_waste": round(padded / denom, 4),
        "queue_wait_row_steps": waited,
        "resizes": resizes,
        # one scalar to rank by: padding plus normalized wait (a padded
        # row-step and a waited row-step burn comparable wall time)
        "cost": round(padded / denom + waited / denom, 4),
    }


def sweep_lane_gains(seed: Any = "swarmload",
                     grid: Sequence[tuple[float, float, int]] | None = None,
                     panel: int = 4) -> dict[str, Any]:
    """Score LaneWidthController gain triples over the harness's three
    canonical regimes (steady trickle, diurnal, spiky burst), each
    replayed over a ``panel`` of seed-derived traces so one lucky trace
    cannot crown a winner. ``benchmark.py`` stamps the table into BENCH
    json; the shipped defaults are asserted against the default-seed
    winner in tests/test_loadgen.py so a default and the harness can
    never silently disagree."""
    if grid is None:
        grid = [(g, s, p)
                for g in (0.625, 0.75, 0.875)
                for s in (0.25, 0.375)
                for p in (2, 4, 6)]
    regimes = {}
    for k in range(max(1, int(panel))):
        regimes[f"trickle:{k}"] = arrival_trace(
            DiurnalCurve(amplitude=0.2, spikes=0, seed=f"{seed}:{k}"),
            boundaries=600, mean_rows=0.15, seed=f"{seed}:trickle:{k}")
        regimes[f"diurnal:{k}"] = arrival_trace(
            DiurnalCurve(amplitude=0.7, spikes=1, seed=f"{seed}:{k}"),
            boundaries=600, mean_rows=0.5, seed=f"{seed}:diurnal:{k}")
        regimes[f"burst:{k}"] = arrival_trace(
            DiurnalCurve(amplitude=0.4, spikes=3, spike_mult=6.0,
                         seed=f"{seed}:{k}"),
            boundaries=600, mean_rows=0.8, seed=f"{seed}:burst:{k}")
    results = []
    for grow_at, shrink_at, patience in grid:
        scores = {name: simulate_lane_controller(
            grow_at=grow_at, shrink_at=shrink_at, patience=patience,
            trace=trace) for name, trace in regimes.items()}
        by_regime: dict[str, float] = {}
        for name, score in scores.items():
            regime = name.split(":", 1)[0]
            by_regime[regime] = round(
                by_regime.get(regime, 0.0) + score["cost"], 4)
        results.append({
            "grow_at": grow_at, "shrink_at": shrink_at,
            "patience": patience,
            "cost": round(sum(s["cost"] for s in scores.values()), 4),
            "cost_by_regime": by_regime,
            "resizes": sum(s["resizes"] for s in scores.values()),
        })
    results.sort(key=lambda r: (r["cost"], r["grow_at"], r["shrink_at"],
                                r["patience"]))
    winner = results[0]
    from chiaswarm_tpu.serving.stepper import LaneWidthController

    defaults = LaneWidthController()
    return {
        "winner": {k: winner[k] for k in
                   ("grow_at", "shrink_at", "patience", "cost")},
        "defaults": {"grow_at": defaults.grow_at,
                     "shrink_at": defaults.shrink_at,
                     "patience": defaults.patience},
        "defaults_match_winner": (
            (defaults.grow_at, defaults.shrink_at, defaults.patience)
            == (winner["grow_at"], winner["shrink_at"],
                winner["patience"])),
        "table": results,
    }


def simulate_prefetch(window_s: float, *, models: int = 4,
                      events: int = 400, seed: Any = "swarmload",
                      ) -> dict[str, float]:
    """Score one ArrivalEwma window as the prefetch ranking signal:
    a one-free-slot cache prefetches the top-ranked non-resident model
    between accesses; hit rate over a seeded stream with per-model
    periodicity + regime shifts (the pattern the ranking must track —
    too short a window chases noise, too long one lags the shift)."""
    from chiaswarm_tpu.serving.residency import ArrivalEwma

    rng = random.Random(f"prefetch:{seed}")
    # per-model base weights, re-drawn mid-stream (the regime shift)
    weights = [rng.uniform(0.5, 2.0) for _ in range(models)]
    ewmas = [ArrivalEwma(window_s=window_s) for _ in range(models)]
    resident: set[int] = {0}
    capacity = max(1, models // 2)
    now = 0.0
    hits = misses = 0
    for event in range(max(1, int(events))):
        if event == events // 2:
            weights = [rng.uniform(0.5, 2.0) for _ in range(models)]
        now += rng.expovariate(1.0)
        model = rng.choices(range(models), weights=weights)[0]
        ewmas[model].note(1, now)
        if model in resident:
            hits += 1
        else:
            misses += 1
            resident.add(model)
            if len(resident) > capacity:   # LRU-free stand-in: evict
                resident.discard(min(     # the coldest by the EWMA
                    (m for m in resident if m != model),
                    key=lambda m: ewmas[m].rate(now)))
        # idle prefetch: warm the hottest non-resident model
        if len(resident) < capacity:
            candidates = [m for m in range(models) if m not in resident]
            if candidates:
                resident.add(max(candidates,
                                 key=lambda m: ewmas[m].rate(now)))
    return {"window_s": window_s,
            "hit_rate": round(hits / max(1, hits + misses), 4)}


def sweep_prefetch_window(seed: Any = "swarmload",
                          windows: Sequence[float] = (5.0, 10.0, 20.0,
                                                      40.0),
                          panel: int = 6) -> dict[str, Any]:
    """Rank candidate ArrivalEwma windows for the residency prefetch
    ranking (ISSUE 9 satellite: tune prefetch aggressiveness from
    harness sweeps), averaged over a ``panel`` of seed-derived streams;
    stamped into BENCH json beside the gains table. The shipped value
    is ``serving.residency.PREFETCH_RANK_WINDOW_S`` — deliberately
    separate from the lane demand EWMA's short window (model reuse has
    minutes-scale locality, lane demand has seconds-scale)."""
    from chiaswarm_tpu.serving.residency import PREFETCH_RANK_WINDOW_S

    table = []
    for window in windows:
        runs = [simulate_prefetch(window, seed=f"{seed}:{k}")
                for k in range(max(1, int(panel)))]
        table.append({
            "window_s": window,
            "hit_rate": round(sum(r["hit_rate"] for r in runs)
                              / len(runs), 4),
        })
    winner = max(table, key=lambda r: (r["hit_rate"], -r["window_s"]))
    return {
        "winner": winner,
        "default_window_s": PREFETCH_RANK_WINDOW_S,
        "defaults_match_winner":
            PREFETCH_RANK_WINDOW_S == winner["window_s"],
        "table": table,
    }


# ---------------------------------------------------------------------------
# operator entry point
# ---------------------------------------------------------------------------


def build_scenario(*, seed: Any, n_users: int, duration_s: float,
                   rate_jobs_s: float,
                   profiles: Sequence[WorkloadProfile] = DEFAULT_PROFILES,
                   models: Sequence[str] = ("swarm/sd15",),
                   ) -> list[ScheduledJob]:
    population = UserPopulation(n_users=n_users, profiles=profiles,
                                models=models, seed=seed)
    curve = DiurnalCurve(seed=seed)
    return generate_schedule(population, curve, duration_s=duration_s,
                             rate_jobs_s=rate_jobs_s, seed=seed,
                             id_prefix=f"load-{seed}")


def main() -> None:  # `python -m chiaswarm_tpu.node.loadgen`
    """Operator smoke: a seeded diurnal scenario against synthetic
    overload-controlled workers, JSON report on stdout. Knobs:
    CHIASWARM_LOAD_SEED / _USERS / _DURATION_S / _RATE / _WORKERS /
    _KILL (1 = kill a worker mid-run)."""
    seed = os.environ.get("CHIASWARM_LOAD_SEED", "swarmload")
    schedule = build_scenario(
        seed=seed,
        n_users=int(os.environ.get("CHIASWARM_LOAD_USERS", "2000")),
        duration_s=float(os.environ.get("CHIASWARM_LOAD_DURATION_S",
                                        "10")),
        rate_jobs_s=float(os.environ.get("CHIASWARM_LOAD_RATE", "20")))
    kill = (KillPlan() if os.environ.get("CHIASWARM_LOAD_KILL", "")
            .strip().lower() in ("1", "true", "on", "yes") else None)
    report = asyncio.run(run_load(
        schedule,
        n_workers=int(os.environ.get("CHIASWARM_LOAD_WORKERS", "3")),
        kill=kill, seed=seed))
    report["sweeps"] = {
        "lane_gains": sweep_lane_gains(seed),
        "prefetch_window": sweep_prefetch_window(seed),
    }
    print(json.dumps(report))


if __name__ == "__main__":
    main()
