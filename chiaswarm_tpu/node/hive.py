"""Hive protocol client — the HTTP control plane to the job queue.

Wire-compatible with the reference's endpoints so a node can join the same
swarm (SURVEY.md §2c):

- ``GET  {uri}/api/work``    long-poll for jobs     (swarm/worker.py:58-110)
- ``POST {uri}/api/results`` upload artifact envelopes (swarm/worker.py:145-163)
- ``GET  {uri}/api/models``  model catalog          (swarm/initialize.py:97-116)

Bearer-token auth; worker version + name ride as query params. The adaptive
poll cadence (1 s after work, 11 s idle, 121 s after an error) is the
protocol's congestion control and is preserved as constants here.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from typing import Any

import aiohttp

from chiaswarm_tpu import WORKER_VERSION
from chiaswarm_tpu.obs.metrics import REGISTRY
from chiaswarm_tpu.obs.trace import span

log = logging.getLogger("chiaswarm.hive")

# control-plane request accounting (process-global: the HTTP client is
# worker-agnostic; worker-scoped health lives on the worker's registry)
_REQUESTS = REGISTRY.counter(
    "chiaswarm_hive_requests_total",
    "hive API requests by endpoint and coarse result",
    labelnames=("endpoint", "result"))
_REQUEST_SECONDS = REGISTRY.histogram(
    "chiaswarm_hive_request_seconds",
    "hive API request latency",
    labelnames=("endpoint",))

# the adaptive poll cadence constants are protocol-level but live in the
# pure-config settings module (so config never imports aiohttp);
# re-exported here because this file documents the wire protocol. The
# reference polls a flat POLL_ERROR_S=121 s after any error; the worker
# now backs off exponentially (base node/settings.py:
# poll_backoff_base_s) with jitter up to that cap, resetting on the
# first successful poll (node/resilience.py::Backoff).
from chiaswarm_tpu.node.settings import (  # noqa: F401
    POLL_BUSY_S,
    POLL_ERROR_S,
    POLL_IDLE_S,
)


@contextlib.contextmanager
def _observe(endpoint: str):
    """Count + time one hive API request (coarse ok/error result; the
    timer spans the whole request including retried body reads)."""
    t0 = time.perf_counter()
    try:
        yield
    except BaseException:
        _REQUESTS.inc(endpoint=endpoint, result="error")
        raise
    else:
        _REQUESTS.inc(endpoint=endpoint, result="ok")
    finally:
        _REQUEST_SECONDS.observe(time.perf_counter() - t0,
                                 endpoint=endpoint)


class BadWorkerError(RuntimeError):
    """HTTP 400 from the hive: this worker is misbehaving (e.g. not
    returning results within expectations) — parity with
    swarm/worker.py:92-97 where the hive does timeout-based failure
    detection."""


class HiveClient:
    def __init__(self, uri: str, token: str, worker_name: str) -> None:
        self.api = f"{uri.rstrip('/')}/api"
        self.token = token
        self.worker_name = worker_name

    def _headers(self) -> dict[str, str]:
        return {
            "Content-type": "application/json",
            "Authorization": f"Bearer {self.token}",
            "user-agent": f"chiaSWARM.worker/{WORKER_VERSION}",
        }

    async def get_work(self, session: aiohttp.ClientSession) -> list[dict]:
        """Fetch queued jobs; raises on non-200 (caller applies backoff)."""
        with _observe("work"):
            async with session.get(
                f"{self.api}/work",
                params={
                    "worker_version": WORKER_VERSION,
                    "worker_name": self.worker_name,
                },
                headers=self._headers(),
                timeout=aiohttp.ClientTimeout(total=10),
            ) as response:
                if response.status == 200:
                    payload = await response.json()
                    return list(payload.get("jobs", []))
                if response.status == 400:
                    # parse defensively: a misbehaving-worker signal must
                    # stay a BadWorkerError even when the hive (or an
                    # intermediary proxy) sends a non-JSON 400 body —
                    # letting json() raise here would demote it to a
                    # generic poll failure
                    message = "bad worker"
                    try:
                        payload = await response.json(content_type=None)
                        if isinstance(payload, dict):
                            message = str(payload.get("message", message))
                    except Exception:
                        try:
                            body = (await response.text()).strip()
                            if body:
                                message = body[:200]
                        except Exception:
                            pass
                    raise BadWorkerError(message)
                response.raise_for_status()
                return []

    async def post_result(self, session: aiohttp.ClientSession,
                          result: dict[str, Any]) -> dict[str, Any]:
        # the span lands under the job's "upload" phase when the worker
        # delivers with the trace active (node/worker.py::_deliver)
        with _observe("results"), span("upload.http"):
            async with session.post(
                f"{self.api}/results",
                data=json.dumps(result),
                headers=self._headers(),
                timeout=aiohttp.ClientTimeout(total=60),
            ) as response:
                if response.status >= 400:
                    log.error("hive rejected result (%s): %s",
                              response.status, response.reason)
                    response.raise_for_status()
                try:
                    return await response.json()
                except Exception:  # non-JSON 2xx body — accept upload
                    return {"status": response.status}

    async def post_heartbeat(self, session: aiohttp.ClientSession,
                             payload: dict[str, Any]) -> dict[str, Any]:
        """Lease keep-alive for lease-aware hives (node/minihive.py):
        ``payload`` carries the worker name, its in-flight job ids, and
        their latest resume checkpoints. NOT part of the reference wire
        protocol — the worker only calls this when ``heartbeat_s`` > 0
        (node/settings.py) and tolerates any failure."""
        with _observe("heartbeat"):
            async with session.post(
                f"{self.api}/heartbeat",
                data=json.dumps(payload),
                headers=self._headers(),
                timeout=aiohttp.ClientTimeout(total=10),
            ) as response:
                response.raise_for_status()
                try:
                    return await response.json()
                except Exception:  # non-JSON 2xx: the beat still landed
                    return {"status": response.status}

    async def get_models(self, session: aiohttp.ClientSession) -> list[dict]:
        async with session.get(
            f"{self.api}/models",
            headers=self._headers(),
            timeout=aiohttp.ClientTimeout(total=30),
        ) as response:
            response.raise_for_status()
            payload = await response.json()
            return payload.get("models", payload) if isinstance(payload, dict) \
                else payload
