"""Job dispatch + argument normalization — THE routing table.

Capability parity with swarm/job_arguments.py:17-190: a hive job dict maps
to ``(callback, kwargs)`` by workflow; stable-diffusion jobs get their
inputs rationalized (size clamp, input-image fetch with guards, ControlNet
rewiring, instruct-pix2pix strength remap, default steps, server-listed
unsupported-argument stripping).

TPU-first differences: the server's diffusers *class names* don't resolve
to classes here — ``pipeline_type`` folds into the unified jitted pipeline's
static mode flags and ``scheduler_type`` maps through
schedulers.resolve (same server contract, no dynamic imports); a
``registry`` (node/registry.py) rides along so callbacks bind resident
compiled models instead of loading weights per job.
"""

from __future__ import annotations

import io
import logging
from typing import Any, Callable

import numpy as np
from PIL import Image, ImageOps

from chiaswarm_tpu.node.registry import ModelRegistry

log = logging.getLogger("chiaswarm.dispatch")

MAX_SIZE = 1024
MAX_IMAGE_BYTES = 3 * 1048576   # input guard, job_arguments.py:172-176
DEFAULT_STEPS = 30              # job_arguments.py:139-141

FormatResult = tuple[Callable[..., tuple[dict, dict]], dict[str, Any]]


def format_args(job: dict[str, Any], registry: ModelRegistry) -> FormatResult:
    """Route one hive job. Raises on malformed input (treated as a fatal,
    non-retryable error by the executor — swarm/generator.py:34-41)."""
    args = dict(job)
    args["registry"] = registry
    workflow = args.pop("workflow", None)

    if workflow == "txt2audio":
        from chiaswarm_tpu.workloads.audio import (
            tts_callback, txt2audio_callback,
        )

        from chiaswarm_tpu.pipelines.tts import is_tts_model

        if is_tts_model(str(args.get("model_name", ""))):
            return tts_callback, args
        return _format_audio_args(args)

    if workflow == "stitch":
        from chiaswarm_tpu.workloads.stitch import stitch_callback

        return stitch_callback, args

    if workflow == "img2txt":
        from chiaswarm_tpu.workloads.caption import caption_callback

        if "start_image_uri" in args:
            args["image"] = np.asarray(
                get_image(args.pop("start_image_uri"), None)
            )
        return caption_callback, args

    if workflow == "vid2vid":
        from chiaswarm_tpu.workloads.video import vid2vid_callback

        return vid2vid_callback, args

    if workflow == "img2vid":
        from chiaswarm_tpu.workloads.video import img2vid_callback

        parameters = _pop_parameters(args)
        args.pop("prompt", None)        # image-conditioned: no text tower
        args["scheduler_type"] = parameters.pop("scheduler_type", None)
        _strip_unsupported(args, parameters)
        if "start_image_uri" in args:
            args["image"] = np.asarray(
                get_image(args.pop("start_image_uri"), None))
        return img2vid_callback, args

    if workflow == "txt2vid":
        from chiaswarm_tpu.workloads.video import txt2vid_callback

        return _format_txt2vid_args(args)

    if str(args.get("model_name", "")).startswith("DeepFloyd/"):
        from chiaswarm_tpu.workloads.cascade import cascade_callback

        return cascade_callback, args

    return _format_stable_diffusion_args(args)


def _pop_parameters(args: dict[str, Any]) -> dict[str, Any]:
    parameters = args.pop("parameters", {}) or {}
    args.setdefault("prompt", "")
    return parameters


def _strip_unsupported(args: dict[str, Any], parameters: dict[str, Any]) -> None:
    """Server-driven capability negotiation (job_arguments.py:150-151)."""
    for name in parameters.get("unsupported_pipeline_arguments", []):
        args.pop(name, None)


def _format_audio_args(args: dict[str, Any]) -> FormatResult:
    from chiaswarm_tpu.workloads.audio import txt2audio_callback

    parameters = _pop_parameters(args)
    # AudioLDM default is 20 steps (swarm/audio/audioldm.py:15-16)
    args.setdefault("num_inference_steps", 20)
    args["scheduler_type"] = parameters.pop("scheduler_type", None)
    _strip_unsupported(args, parameters)
    return txt2audio_callback, args


def _format_txt2vid_args(args: dict[str, Any]) -> FormatResult:
    from chiaswarm_tpu.workloads.video import txt2vid_callback

    parameters = _pop_parameters(args)
    args.setdefault("num_inference_steps", 25)
    args.pop("num_images_per_prompt", None)
    args["scheduler_type"] = parameters.pop("scheduler_type", None)
    _strip_unsupported(args, parameters)
    return txt2vid_callback, args


def _format_stable_diffusion_args(args: dict[str, Any]) -> FormatResult:
    from chiaswarm_tpu.workloads.diffusion import diffusion_callback

    size = None
    if "height" in args and "width" in args:
        size = (int(args["height"]), int(args["width"]))
        if size[0] > MAX_SIZE or size[1] > MAX_SIZE:
            raise ValueError(
                f"The max image size is ({MAX_SIZE}, {MAX_SIZE}); "
                f"got ({size[0]}, {size[1]})."
            )

    parameters = _pop_parameters(args)
    args["upscale"] = parameters.get("upscale", False)

    if "start_image_uri" in args:
        args.pop("height", None)
        args.pop("width", None)
        controlnet = parameters.get("controlnet")
        image = get_image(args.pop("start_image_uri"), size, controlnet)
        args["image"] = np.asarray(image)

        if controlnet is not None:
            args["controlnet_model_name"] = controlnet.get(
                "controlnet_model_name", "lllyasviel/control_v11p_sd15_canny"
            )
            args["save_preprocessed_input"] = controlnet.get("preprocess",
                                                             False)
        if args.get("model_name") == "timbrooks/instruct-pix2pix":
            # pix2pix conditions on image_guidance_scale (1-5), the hive
            # sends strength (0-1) — same remap as job_arguments.py:128-131
            args["image_guidance_scale"] = args.pop("strength", 0.6) * 5

    if "mask_image_uri" in args:
        args.pop("height", None)
        args.pop("width", None)
        mask = get_image(args.pop("mask_image_uri"), size)
        args["mask_image"] = np.asarray(mask)

    args.setdefault("num_inference_steps", DEFAULT_STEPS)
    # server-named diffusers scheduler class -> our sampler registry
    args["scheduler_type"] = parameters.pop("scheduler_type", None)
    _strip_unsupported(args, parameters)
    return diffusion_callback, args


# ---- input fetching with trust-boundary guards ------------------------


def download_image(url: str) -> Image.Image:
    import requests

    response = requests.get(url, allow_redirects=True, timeout=60)
    response.raise_for_status()
    # re-check after download: HEAD Content-Length can be absent or forged
    if len(response.content) > MAX_IMAGE_BYTES:
        raise ValueError(
            f"Input image too large.\nMax size is {MAX_IMAGE_BYTES} bytes.\n"
            f"Image was {len(response.content)}."
        )
    image = Image.open(io.BytesIO(response.content))
    image = ImageOps.exif_transpose(image)
    return image.convert("RGB")


def get_image(uri: str, size: tuple[int, int] | None,
              controlnet: dict | None = None) -> Image.Image:
    """Fetch an input image with the open-network guards the reference
    enforces (job_arguments.py:162-190): content-type must be an image,
    payload capped at 3 MiB, downscaled to the requested / max size."""
    import requests

    head = requests.head(uri, allow_redirects=True, timeout=30)
    content_type = head.headers.get("Content-Type", "")
    content_length = int(head.headers.get("Content-Length", 0) or 0)
    if not content_type.startswith("image"):
        raise ValueError(
            "Input does not appear to be an image.\n"
            f"Content type was {content_type}."
        )
    if content_length > MAX_IMAGE_BYTES:
        raise ValueError(
            f"Input image too large.\nMax size is {MAX_IMAGE_BYTES} bytes.\n"
            f"Image was {content_length}."
        )

    image = download_image(uri)
    if size is not None and (image.height > size[0] or image.width > size[1]):
        # PIL thumbnail takes (max_width, max_height); size is (H, W)
        image.thumbnail((size[1], size[0]), Image.Resampling.LANCZOS)
    elif image.height > MAX_SIZE or image.width > MAX_SIZE:
        image.thumbnail((MAX_SIZE, MAX_SIZE), Image.Resampling.LANCZOS)

    if controlnet is not None:
        from chiaswarm_tpu.workloads.controlnet import preprocess_image

        image = preprocess_image(image, controlnet)
    return image
