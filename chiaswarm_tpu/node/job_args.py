"""Job dispatch + argument normalization — THE routing table.

Capability parity with swarm/job_arguments.py:17-190: a hive job dict maps
to ``(callback, kwargs)`` by workflow; stable-diffusion jobs get their
inputs rationalized (size clamp, input-image fetch with guards, ControlNet
rewiring, instruct-pix2pix strength remap, default steps, server-listed
unsupported-argument stripping).

TPU-first differences: the server's diffusers *class names* don't resolve
to classes here — ``pipeline_type`` folds into the unified jitted pipeline's
static mode flags and ``scheduler_type`` maps through
schedulers.resolve (same server contract, no dynamic imports); a
``registry`` (node/registry.py) rides along so callbacks bind resident
compiled models instead of loading weights per job.
"""

from __future__ import annotations

import io
import logging
from typing import Any, Callable

import numpy as np
from PIL import Image, ImageOps

from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.node.resilience import BadAssetError

log = logging.getLogger("chiaswarm.dispatch")

MAX_SIZE = 1024
MAX_IMAGE_BYTES = 3 * 1048576   # input guard, job_arguments.py:172-176
DEFAULT_STEPS = 30              # job_arguments.py:139-141

# ---- asset trust-boundary hardening (ISSUE 10 satellite) ----
# Asset fetches cross an open-network trust boundary with hostile
# parties on the far side. Beyond the reference's byte cap: explicit
# connect/read timeouts (a stalling asset host must not wedge an
# executor thread into its job deadline), a STREAMED read capped at
# MAX_IMAGE_BYTES (a body larger than its Content-Length claim is cut
# off without buffering it), and a decoded-pixel-dimension cap (a
# 20 KB PNG claiming 30000x30000 pixels is a decompression bomb — PIL
# exposes the dimensions before decoding, so the bomb never inflates).
# Violations raise resilience.BadAssetError -> non-fatal "bad_asset";
# network faults stay "transient" (the PR-2 taxonomy).
CONNECT_TIMEOUT_S = 10.0
READ_TIMEOUT_S = 60.0
MAX_IMAGE_PIXELS = 16 * 1024 * 1024  # 16 Mpx; served max is ~1 Mpx

FormatResult = tuple[Callable[..., tuple[dict, dict]], dict[str, Any]]


def format_args(job: dict[str, Any], registry: ModelRegistry) -> FormatResult:
    """Route one hive job. Raises on malformed input (treated as a fatal,
    non-retryable error by the executor — swarm/generator.py:34-41)."""
    args = dict(job)
    args["registry"] = registry
    workflow = args.pop("workflow", None)

    if workflow == "txt2audio":
        from chiaswarm_tpu.workloads.audio import (
            tts_callback, txt2audio_callback,
        )

        from chiaswarm_tpu.pipelines.tts import is_tts_model

        if is_tts_model(str(args.get("model_name", ""))):
            return tts_callback, args
        return _format_audio_args(args)

    if workflow == "stitch":
        from chiaswarm_tpu.workloads.stitch import stitch_callback

        return stitch_callback, args

    if workflow == "img2txt":
        from chiaswarm_tpu.workloads.caption import caption_callback

        if "start_image_uri" in args:
            args["image"] = np.asarray(
                get_image(args.pop("start_image_uri"), None)
            )
        return caption_callback, args

    if workflow == "vid2vid":
        from chiaswarm_tpu.workloads.video import vid2vid_callback

        return vid2vid_callback, args

    if workflow == "img2vid":
        from chiaswarm_tpu.workloads.video import img2vid_callback

        parameters = _pop_parameters(args)
        args.pop("prompt", None)        # image-conditioned: no text tower
        args["scheduler_type"] = parameters.pop("scheduler_type", None)
        _strip_unsupported(args, parameters)
        if "start_image_uri" in args:
            args["image"] = np.asarray(
                get_image(args.pop("start_image_uri"), None))
        return img2vid_callback, args

    if workflow == "txt2vid":
        from chiaswarm_tpu.workloads.video import txt2vid_callback

        return _format_txt2vid_args(args)

    if str(args.get("model_name", "")).startswith("DeepFloyd/"):
        from chiaswarm_tpu.workloads.cascade import cascade_callback

        return cascade_callback, args

    return _format_stable_diffusion_args(args)


def _pop_parameters(args: dict[str, Any]) -> dict[str, Any]:
    parameters = args.pop("parameters", {}) or {}
    args.setdefault("prompt", "")
    return parameters


def _strip_unsupported(args: dict[str, Any], parameters: dict[str, Any]) -> None:
    """Server-driven capability negotiation (job_arguments.py:150-151)."""
    for name in parameters.get("unsupported_pipeline_arguments", []):
        args.pop(name, None)


def _format_audio_args(args: dict[str, Any]) -> FormatResult:
    from chiaswarm_tpu.workloads.audio import txt2audio_callback

    parameters = _pop_parameters(args)
    # AudioLDM default is 20 steps (swarm/audio/audioldm.py:15-16)
    args.setdefault("num_inference_steps", 20)
    args["scheduler_type"] = parameters.pop("scheduler_type", None)
    _strip_unsupported(args, parameters)
    return txt2audio_callback, args


def _format_txt2vid_args(args: dict[str, Any]) -> FormatResult:
    from chiaswarm_tpu.workloads.video import txt2vid_callback

    parameters = _pop_parameters(args)
    args.setdefault("num_inference_steps", 25)
    args.pop("num_images_per_prompt", None)
    args["scheduler_type"] = parameters.pop("scheduler_type", None)
    _strip_unsupported(args, parameters)
    return txt2vid_callback, args


def _format_stable_diffusion_args(args: dict[str, Any]) -> FormatResult:
    from chiaswarm_tpu.workloads.diffusion import diffusion_callback

    size = None
    if "height" in args and "width" in args:
        size = (int(args["height"]), int(args["width"]))
        if size[0] > MAX_SIZE or size[1] > MAX_SIZE:
            raise ValueError(
                f"The max image size is ({MAX_SIZE}, {MAX_SIZE}); "
                f"got ({size[0]}, {size[1]})."
            )

    parameters = _pop_parameters(args)
    args["upscale"] = parameters.get("upscale", False)

    if "start_image_uri" in args:
        args.pop("height", None)
        args.pop("width", None)
        controlnet = parameters.get("controlnet")
        image = get_image(args.pop("start_image_uri"), size, controlnet)
        args["image"] = np.asarray(image)

        if controlnet is not None:
            args["controlnet_model_name"] = controlnet.get(
                "controlnet_model_name", "lllyasviel/control_v11p_sd15_canny"
            )
            args["save_preprocessed_input"] = controlnet.get("preprocess",
                                                             False)
        if args.get("model_name") == "timbrooks/instruct-pix2pix":
            # pix2pix conditions on image_guidance_scale (1-5), the hive
            # sends strength (0-1) — same remap as job_arguments.py:128-131
            args["image_guidance_scale"] = args.pop("strength", 0.6) * 5

    if "mask_image_uri" in args:
        args.pop("height", None)
        args.pop("width", None)
        mask = get_image(args.pop("mask_image_uri"), size)
        args["mask_image"] = np.asarray(mask)

    args.setdefault("num_inference_steps", DEFAULT_STEPS)
    # server-named diffusers scheduler class -> our sampler registry
    args["scheduler_type"] = parameters.pop("scheduler_type", None)
    # DeepCache step-level reuse (ISSUE 12): a per-job schedule (list of
    # ladder indices or "every:N"); tuple-ized so the burst coalescer
    # can hash it as part of COALESCE_KEYS
    reuse = parameters.pop("reuse_schedule", None)
    if reuse is not None:
        args["reuse_schedule"] = (tuple(reuse)
                                  if isinstance(reuse, (list, tuple))
                                  else reuse)
    _strip_unsupported(args, parameters)
    return diffusion_callback, args


# ---- input fetching with trust-boundary guards ------------------------


def _read_capped(response, cap: int) -> bytes:
    """Stream a response body up to ``cap`` bytes; one byte more is a
    :class:`BadAssetError` — the body is never buffered past the cap,
    so a hostile server cannot make this worker hold a multi-GB asset
    in memory no matter what Content-Length it claimed."""
    chunks: list[bytes] = []
    total = 0
    for chunk in response.iter_content(chunk_size=65536):
        total += len(chunk)
        if total > cap:
            raise BadAssetError(
                f"Input image too large.\nMax size is {cap} bytes.\n"
                f"Stream exceeded the cap at {total} bytes.")
        chunks.append(chunk)
    return b"".join(chunks)


def _check_decoded_dims(image: Image.Image) -> None:
    """Decompression-bomb guard: PIL exposes the claimed dimensions
    before decoding any pixels — reject the bomb while it is still a
    few KB of compressed bytes."""
    pixels = int(image.size[0]) * int(image.size[1])
    if pixels > MAX_IMAGE_PIXELS:
        raise BadAssetError(
            f"Input image decodes to {image.size[0]}x{image.size[1]} "
            f"({pixels} pixels), over the {MAX_IMAGE_PIXELS}-pixel cap "
            f"(decompression-bomb guard).")


def download_image(url: str,
                   max_bytes: int = MAX_IMAGE_BYTES) -> Image.Image:
    """Guarded image fetch. ``max_bytes`` defaults to the user-INPUT
    cap; callers fetching the system's own outputs (stitch pulls prior
    RESULT images, which an upscaled 2048px PNG legitimately pushes
    past 3 MiB) pass a larger cap — the decoded-dimension bomb guard
    and content-type/timeout checks still apply unchanged."""
    import requests

    # the context manager closes the streamed response on EVERY path —
    # a guard violation raised mid-stream must not leave the pooled
    # connection checked out until GC (a burst of hostile assets would
    # otherwise pin one dead socket per executor thread)
    with requests.get(url, allow_redirects=True, stream=True,
                      timeout=(CONNECT_TIMEOUT_S,
                               READ_TIMEOUT_S)) as response:
        response.raise_for_status()
        content_type = response.headers.get("Content-Type", "")
        if content_type and not content_type.startswith("image"):
            # the GET's own content type — a host that passed the HEAD
            # check must not switch to text/html for the real body
            raise BadAssetError(
                "Input does not appear to be an image.\n"
                f"Content type was {content_type}.")
        # streamed + capped: Content-Length can be absent or forged; a
        # compliant header says nothing about the body that follows
        data = _read_capped(response, max_bytes)
    image = Image.open(io.BytesIO(data))
    _check_decoded_dims(image)
    image = ImageOps.exif_transpose(image)
    return image.convert("RGB")


def get_image(uri: str, size: tuple[int, int] | None,
              controlnet: dict | None = None) -> Image.Image:
    """Fetch an input image with the open-network guards the reference
    enforces (job_arguments.py:162-190) plus the ISSUE-10 hardening:
    content-type must be an image, payload streamed and capped at 3 MiB,
    decoded dimensions capped (decompression-bomb guard), explicit
    connect/read timeouts, downscaled to the requested / max size.
    Guard violations raise :class:`BadAssetError` (non-fatal
    ``bad_asset``); network faults classify ``transient``."""
    import requests

    head = requests.head(uri, allow_redirects=True,
                         timeout=(CONNECT_TIMEOUT_S, 30.0))
    content_type = head.headers.get("Content-Type", "")
    content_length = int(head.headers.get("Content-Length", 0) or 0)
    if not content_type.startswith("image"):
        raise BadAssetError(
            "Input does not appear to be an image.\n"
            f"Content type was {content_type}."
        )
    if content_length > MAX_IMAGE_BYTES:
        raise BadAssetError(
            f"Input image too large.\nMax size is {MAX_IMAGE_BYTES} bytes.\n"
            f"Image was {content_length}."
        )

    image = download_image(uri)
    if size is not None and (image.height > size[0] or image.width > size[1]):
        # PIL thumbnail takes (max_width, max_height); size is (H, W)
        image.thumbnail((size[1], size[0]), Image.Resampling.LANCZOS)
    elif image.height > MAX_SIZE or image.width > MAX_SIZE:
        image.thumbnail((MAX_SIZE, MAX_SIZE), Image.Resampling.LANCZOS)

    if controlnet is not None:
        from chiaswarm_tpu.workloads.controlnet import preprocess_image

        image = preprocess_image(image, controlnet)
    return image
