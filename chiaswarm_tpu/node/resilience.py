"""Failure classification, backoff, circuit breakers, and result durability.

The reference worker has exactly one failure story: a job that crashes on
the node is silently eaten, the hive waits out its deadline and flags the
whole worker with HTTP 400 (swarm/worker.py:92-97). This module is the
node-side opposite — failures contained at the JOB level and reported
explicitly:

- :func:`classify_exception` / :func:`classify_result` sort failures into
  kinds that drive the worker's degradation ladder (node/worker.py):
  ``transient`` faults (image-fetch blips, 5xx) and ``oom`` retry locally
  with capped backoff; ``oom``'d coalesced bursts additionally split and
  re-run serially; ``fatal`` input errors upload immediately and are never
  retried anywhere; ``model``/``timeout``/``error`` feed the breaker.
- :class:`BreakerBoard` keeps one :class:`CircuitBreaker` per model:
  ``BREAKER_KINDS`` failures in a row quarantine the model (mirrored into
  ``ModelRegistry``) so one broken checkpoint cannot poison the node;
  after a cooldown one half-open probe may close it again. Deliberately
  NOT counted: ``fatal`` (bad *user* inputs — K bad requests in a row must
  not quarantine a healthy model) and ``transient`` (network, not the
  model).
- :class:`Backoff` / :func:`backoff_delay` give capped exponential backoff
  with deterministic seeded jitter (equal-jitter: half fixed, half drawn),
  shared by the poll loop, the retry ladder, and upload retries.
- :class:`DeadLetterSpool` persists result envelopes that exhausted their
  upload retries to disk; the worker replays them on the next startup, so
  paid chip time survives even a hive outage spanning a node restart.

Everything here is stdlib-only and synchronous — deliberately importable
without jax, aiohttp, or an event loop, so the chaos suite and the linter
job can load it anywhere.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import logging
import random
import re
import threading
import time
from pathlib import Path
from typing import Any, Callable

log = logging.getLogger("chiaswarm.resilience")

# ---------------------------------------------------------------------------
# failure classification
# ---------------------------------------------------------------------------

#: kinds the worker's ladder retries locally (with backoff; oom also splits
#: coalesced bursts into serial solo re-runs first)
RETRYABLE_KINDS = frozenset({"transient", "oom"})

#: kinds that count as a model-level failure toward its circuit breaker.
#: ``invalid_output`` (swarmguard, serving/guard.py) counts: a checkpoint
#: that keeps producing NaN trajectories is broken the same way a
#: checkpoint that keeps crashing is — K poisoned rows in a row
#: quarantine it here while the guard's per-device ledger decides
#: whether the DEVICE (not the model) is the sick one.
BREAKER_KINDS = frozenset({"model_unavailable", "timeout", "error", "oom",
                           "invalid_output"})

#: kinds a lease-aware hive redispatches to ANOTHER worker instead of
#: settling (node/minihive.py): this node cannot serve the model — by
#: load failure or by an open breaker — but a different node may. These
#: envelopes upload WITHOUT the fatal flag (node/executor.py), resolving
#: the reference-parity taxonomy tension where a node-local
#: model-unavailable used to read as fatal and strand the job.
#: ``overloaded`` (ISSUE 9, node/overload.py) is the admission-control
#: shed: THIS node predicts the job would miss its deadline behind the
#: local backlog — a less-loaded node may still make it. Deliberately
#: NOT breaker fodder: shedding says nothing about the model.
#: ``invalid_output`` (ISSUE 10, serving/guard.py) is the poisoned-row
#: retirement: THIS node's trajectory went NaN — a healthy node (or a
#: healthy device) may render the same job fine, so the hive re-runs it
#: elsewhere instead of settling garbage-or-error.
REDISPATCH_KINDS = frozenset({"model_unavailable", "quarantined",
                              "overloaded", "invalid_output"})

#: kinds whose error envelopes upload WITHOUT the fatal flag — locally
#: retryable kinds plus hive-side redispatch kinds, plus ``bad_asset``
#: (ISSUE 10 satellite, node/job_args.py): an input asset that violated
#: the trust-boundary guards (size/content-type/decoded-dimension caps).
#: Not retried locally (the caps are deterministic) and not breaker
#: fodder (says nothing about the model), but non-fatal — the hive may
#: retry elsewhere or surface it, exactly like a generic ``error``. The
#: executor derives its fatal/non-fatal split from this set so a kind
#: added to any family above can never silently stay fatal (drift
#: between the taxonomy here and hand-written literals was a real
#: near-miss).
NONFATAL_KINDS = RETRYABLE_KINDS | REDISPATCH_KINDS | frozenset(
    {"bad_asset"})

_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
    "OOM",
    "Allocation failure",
)

# exception type names (checked across the MRO so requests/urllib3/aiohttp
# subclasses match without importing any of them) that mean "the outside
# world hiccuped": worth a local retry, never the model's fault
_TRANSIENT_TYPE_NAMES = frozenset({
    "ConnectionError",
    "ConnectionResetError",
    "ConnectionAbortedError",
    "ConnectionRefusedError",
    "BrokenPipeError",
    "ConnectTimeout",
    "ReadTimeout",
    "Timeout",
    "TimeoutError",
    "ChunkedEncodingError",
    "ContentDecodingError",
    "SSLError",
    "ProxyError",
    "ServerDisconnectedError",
    "ClientConnectorError",
    "ClientOSError",
    # swarmguard (serving/guard.py): a hung compiled call that finally
    # returned — the call was declared dead wall-clock-wise, but the
    # job's inputs are fine; the ladder re-runs it (a hung LANE is
    # handled explicitly by the executor's lane-heal path first)
    "StepHung",
    "LaneHung",
})

class BadAssetError(ValueError):
    """An input asset violated the trust-boundary guards (ISSUE 10
    satellite, node/job_args.py): payload over the byte cap, wrong
    content type, or decoded pixel dimensions over the
    decompression-bomb cap. Subclasses ValueError so pre-existing
    fatal-input handling still matches, but classifies as the
    NON-fatal ``bad_asset`` kind (the job's PROMPT may be fine — the
    asset host misbehaved; the hive decides whether to retry)."""


_MODEL_UNAVAILABLE_MARKERS = (
    # node/registry.py load errors AND the residency bounce
    # (serving/residency.py::ModelUnavailable — the model cannot fit
    # this node's HBM even transiently; a different node may have the
    # room, so the hive should redispatch)
    "is not available on this node",
    "quarantined",                     # breaker refusal re-entering a load
)


def classify_exception(exc: BaseException) -> str:
    """Sort an exception into a failure kind for the degradation ladder.

    Returns one of ``oom`` / ``model_unavailable`` / ``transient`` /
    ``fatal`` / ``error``:

    - ``oom``: device memory exhaustion (XLA RESOURCE_EXHAUSTED et al).
    - ``model_unavailable``: this node cannot load the model
      (missing/broken checkpoint, quarantine) — breaker fodder, and a
      hive-side redispatch signal (REDISPATCH_KINDS): other nodes may
      hold the checkpoint this one lacks.
    - ``transient``: network-shaped (input-image fetch, 5xx upstream) —
      retried locally.
    - ``fatal``: the job's inputs are bad; no node can succeed, do not
      redispatch (reference taxonomy, swarm/generator.py:34-41).
    - ``error``: everything else — uploaded without the fatal flag so the
      hive may retry elsewhere; counts toward the model's breaker.
    """
    text = f"{type(exc).__name__}: {exc}"
    if any(marker in text for marker in _OOM_MARKERS):
        return "oom"
    if any(marker in str(exc) for marker in _MODEL_UNAVAILABLE_MARKERS):
        return "model_unavailable"
    names = {cls.__name__ for cls in type(exc).__mro__}
    if "InvalidOutput" in names:
        # swarmguard (serving/guard.py): a numerically poisoned row —
        # non-fatal, redispatchable, breaker fodder
        return "invalid_output"
    if "BadAssetError" in names:
        # trust-boundary guard (node/job_args.py): checked BEFORE the
        # blanket ValueError->fatal rule it subclasses into
        return "bad_asset"
    if "HTTPError" in names:
        # requests.HTTPError subclasses OSError via RequestException, so
        # decide by status class BEFORE the blanket OSError check: 5xx is
        # the server's bad day (retry), 4xx means our request is wrong.
        # Prefer the attached response object; fall back to the LEADING
        # status code of raise_for_status()'s message — never a free
        # regex over the whole text, which would match 5xx-looking
        # digits inside the URL ("…/500x500/a.png")
        status = getattr(getattr(exc, "response", None),
                         "status_code", None)
        if status is None:
            match = re.match(r"\s*(\d{3})\b", str(exc))
            status = int(match.group(1)) if match else None
        if status is None:
            return "error"
        return "transient" if 500 <= status <= 599 else "fatal"
    if names & _TRANSIENT_TYPE_NAMES:
        return "transient"
    if isinstance(exc, (TimeoutError, OSError)):
        return "transient"
    if isinstance(exc, ValueError):
        return "fatal"
    return "error"


def classify_result(result: dict[str, Any] | None) -> str:
    """Kind of a finished result envelope: ``ok`` or a failure kind.

    The executor stamps ``pipeline_config["error_kind"]`` on every error
    envelope it builds (node/executor.py); envelopes from older nodes or
    test stubs that lack the stamp fall back to the fatal flag.
    """
    if not isinstance(result, dict):
        return "error"
    config = result.get("pipeline_config") or {}
    if not isinstance(config, dict) or "error" not in config:
        return "ok"
    kind = config.get("error_kind")
    if kind:
        return str(kind)
    return "fatal" if result.get("fatal_error") else "error"


# ---------------------------------------------------------------------------
# backoff
# ---------------------------------------------------------------------------


def backoff_delay(attempt: int, base: float, cap: float,
                  rng: random.Random | None = None) -> float:
    """Capped exponential backoff with equal jitter for ``attempt`` >= 1.

    ``min(cap, base * 2**(attempt-1))``, half fixed + half uniformly
    jittered, so synchronized failures across a fleet decorrelate but the
    delay never collapses to ~0 (which would hammer a struggling hive).
    """
    span = min(float(cap), float(base) * (2.0 ** max(0, int(attempt) - 1)))
    if rng is None:
        return span
    return span / 2.0 + rng.uniform(0.0, span / 2.0)


class Backoff:
    """Stateful capped-exponential backoff with deterministic jitter.

    ``next()`` grows the delay; ``reset()`` (called on the first success)
    snaps back to the base. Seeding by worker name keeps a node's schedule
    reproducible (chaos tests) while decorrelating nodes from each other.
    """

    def __init__(self, base: float, cap: float, seed: Any = None) -> None:
        self.base = float(base)
        self.cap = float(cap)
        self._rng = random.Random(seed)
        self._failures = 0

    def next(self) -> float:
        self._failures += 1
        return backoff_delay(self._failures, self.base, self.cap, self._rng)

    def reset(self) -> None:
        self._failures = 0

    @property
    def failures(self) -> int:
        return self._failures


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """closed -> open after ``threshold`` consecutive failures; after
    ``cooldown_s`` exactly ONE half-open probe is admitted at a time —
    its success closes the breaker, its failure re-opens (and re-arms the
    cooldown), and an inconclusive outcome (the probe died of something
    that says nothing about the model, e.g. bad user inputs) releases the
    probe slot so the next job probes again."""

    def __init__(self, threshold: int, cooldown_s: float,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self.state = "closed"
        self.failures = 0
        self._opened_at = 0.0
        self._probing = False

    def allow(self) -> bool:
        """May a job for this model run now? Transitions open->half_open
        when the cooldown has elapsed (the caller should un-quarantine the
        model before dispatching the probe). In half_open only one probe
        is in flight at a time — a queued backlog must not stampede a
        likely-broken checkpoint the moment the cooldown expires."""
        if self.state == "open":
            if self._clock() - self._opened_at >= self.cooldown_s:
                self.state = "half_open"
                self._probing = True
                return True
            return False
        if self.state == "half_open":
            if self._probing:
                return False
            self._probing = True
            return True
        return True

    def record(self, ok: bool) -> str | None:
        """Record an outcome; returns ``"opened"``/``"closed"`` on a state
        transition the caller must mirror (registry quarantine), else
        None."""
        self._probing = False
        if ok:
            was = self.state
            self.failures = 0
            self.state = "closed"
            return "closed" if was != "closed" else None
        self.failures += 1
        if self.state == "half_open" or self.failures >= self.threshold:
            self.state = "open"
            self._opened_at = self._clock()
            return "opened"
        return None

    def release_probe(self) -> None:
        """The in-flight half-open probe ended without a verdict on the
        model; free the slot so the next job may probe."""
        self._probing = False

    def snapshot(self) -> dict[str, Any]:
        return {"state": self.state,
                "consecutive_failures": self.failures}


class BreakerBoard:
    """Per-model circuit breakers with registry-mirroring callbacks.

    ``on_open(model)`` fires when a breaker opens (quarantine the model),
    ``on_close(model)`` when it closes after a successful probe, and
    ``on_probe(model)`` when a half-open probe is about to dispatch (the
    registry must accept the load again or the probe can never succeed).
    Callbacks may be None (test stubs without a real registry).

    ``persist_path`` makes open breakers survive restarts (ROADMAP PR-2
    candidate): every open/close transition serializes the non-closed
    breakers to one JSON file next to the dead-letter spool, storing the
    REMAINING cooldown (the monotonic clock does not survive a
    restart); loading re-opens them, re-arms the residual cooldown, and
    re-mirrors the quarantine — a checkpoint that broke the node five
    minutes before a crash is still quarantined when it comes back.
    """

    def __init__(self, threshold: int, cooldown_s: float,
                 clock: Callable[[], float] = time.monotonic,
                 on_open: Callable[[str], Any] | None = None,
                 on_close: Callable[[str], Any] | None = None,
                 on_probe: Callable[[str], Any] | None = None,
                 persist_path: Path | str | None = None) -> None:
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._breakers: dict[str, CircuitBreaker] = {}
        self._on_open = on_open
        self._on_close = on_close
        self._on_probe = on_probe
        self._persist_path = (None if persist_path is None
                              else Path(persist_path))
        if self._persist_path is not None:
            self._load()

    @staticmethod
    def _notify(callback: Callable[[str], Any] | None, model: str) -> None:
        if callback is None:
            return
        try:
            callback(model)
        except Exception:  # a mirror must never break dispatch
            log.exception("breaker callback failed for %s", model)

    def allow(self, model: str) -> bool:
        breaker = self._breakers.get(model)
        if breaker is None:
            return True
        was_open = breaker.state == "open"
        allowed = breaker.allow()
        if allowed and was_open:  # open -> half_open: let the probe load
            log.warning("breaker for %s half-open: dispatching one probe",
                        model)
            self._notify(self._on_probe, model)
        return allowed

    def record(self, model: str, ok: bool) -> None:
        breaker = self._breakers.get(model)
        if breaker is None:
            if ok:
                return  # never-failed models stay untracked
            breaker = self._breakers[model] = CircuitBreaker(
                self.threshold, self.cooldown_s, self._clock)
        transition = breaker.record(ok)
        if transition == "opened":
            log.error("breaker OPEN for %s after %d consecutive failures; "
                      "quarantining for %.0fs", model, breaker.failures,
                      self.cooldown_s)
            self._notify(self._on_open, model)
        elif transition == "closed":
            log.info("breaker closed for %s (probe succeeded)", model)
            self._notify(self._on_close, model)
        if transition is not None:
            self._persist()

    def record_inconclusive(self, model: str) -> None:
        """The job's failure says nothing about the model (bad user
        inputs, network blip): don't move the breaker, but release the
        half-open probe slot so another job may probe — otherwise an
        inconclusive probe would leave the breaker stuck half-open."""
        breaker = self._breakers.get(model)
        if breaker is not None:
            breaker.release_probe()

    def states(self) -> dict[str, dict[str, Any]]:
        return {model: breaker.snapshot()
                for model, breaker in self._breakers.items()}

    def open_models(self) -> list[str]:
        return [m for m, b in self._breakers.items() if b.state == "open"]

    # ---- persistence across restarts ----

    def save(self) -> None:
        """Re-serialize now (worker shutdown): transitions persist
        eagerly, but a clean stop refreshes the REMAINING cooldowns so
        a long-lived open breaker doesn't re-arm its full window on the
        next start."""
        self._persist()

    def dump(self) -> dict[str, Any]:
        """Serializable view of the non-closed breakers. Half-open is
        stored as open with zero remaining cooldown: a restart aborts
        any in-flight probe, so the next allow() re-probes cleanly."""
        now = self._clock()
        out: dict[str, Any] = {}
        for model, breaker in self._breakers.items():
            if breaker.state == "closed":
                continue
            if breaker.state == "half_open":
                remaining = 0.0
            else:
                remaining = max(0.0, self.cooldown_s
                                - (now - breaker._opened_at))
            out[model] = {
                "state": "open",
                "consecutive_failures": int(breaker.failures),
                "cooldown_remaining_s": round(remaining, 3),
            }
        return out

    def _persist(self) -> None:
        if self._persist_path is None:
            return
        try:
            data = self.dump()
            path = self._persist_path
            if not data:
                path.unlink(missing_ok=True)
                return
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(path.suffix + ".tmp")
            tmp.write_text(json.dumps({"version": 1, "breakers": data},
                                      sort_keys=True), encoding="utf-8")
            tmp.replace(path)
        except OSError as exc:  # persistence must never break dispatch
            log.warning("breaker-state persist to %s failed: %s",
                        self._persist_path, exc)

    def _load(self) -> None:
        path = self._persist_path
        if path is None or not path.is_file():
            return
        try:
            payload = json.loads(path.read_text(encoding="utf-8"))
            entries = dict(payload.get("breakers") or {})
        except (OSError, json.JSONDecodeError, AttributeError) as exc:
            log.error("unreadable breaker-state file %s (%s); starting "
                      "with closed breakers", path, exc)
            return
        now = self._clock()
        for model, entry in entries.items():
            try:
                remaining = max(0.0, min(
                    self.cooldown_s,
                    float(entry.get("cooldown_remaining_s", 0.0))))
                failures = max(self.threshold,
                               int(entry.get("consecutive_failures", 0)))
            except (TypeError, ValueError):
                continue
            breaker = CircuitBreaker(self.threshold, self.cooldown_s,
                                     self._clock)
            breaker.state = "open"
            breaker.failures = failures
            breaker._opened_at = now - (self.cooldown_s - remaining)
            self._breakers[str(model)] = breaker
            log.warning("breaker for %s restored OPEN from %s "
                        "(%.0fs cooldown remaining)", model, path,
                        remaining)
            self._notify(self._on_open, str(model))


# ---------------------------------------------------------------------------
# hive session (swarmdurable, ISSUE 14: hive-outage ride-through)
# ---------------------------------------------------------------------------


def hive_reachable_error(exc: BaseException) -> bool:
    """True when the error PROVES the hive answered: an HTTP 4xx client
    response (aiohttp sets ``.status``). A reachable hive rejecting a
    request is a protocol problem, not an outage — it must neither grow
    the outage streak (a reference hive 404ing heartbeats would
    otherwise flip the session while polls succeed) nor count as a
    healing success (nothing healed)."""
    status = getattr(exc, "status", None)
    return isinstance(status, int) and 400 <= status < 500


class HiveSession:
    """The worker's view of hive reachability: ONLINE until
    ``outage_after`` consecutive poll/upload/heartbeat failures flip it
    to OUTAGE, and back on the first success ("healed").

    Ride-through semantics the worker attaches to the flip
    (node/worker.py): leases are ASSUMED LOST (a dead hive cannot
    extend them; a journaled hive's recovery voids them anyway),
    in-flight work runs to completion, results spool to the
    DeadLetterSpool after a single upload attempt, and the heal
    triggers a LIVE spool replay — paid chip time rides out the outage
    and lands the moment the hive is back. The capped poll backoff
    (PR 2) already paces the probing; this class only names the state
    so the ladder, the spool, and the operator signals agree on it.

    Stdlib-only and synchronous like the rest of this module.
    """

    def __init__(self, *, outage_after: int = 3,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "") -> None:
        self.outage_after = max(1, int(outage_after))
        self._clock = clock
        # swarmfed (ISSUE 17): a multiplexed worker holds one session
        # PER HIVE SHARD — the label tells their log lines and health
        # snapshots apart (empty for the single-hive worker: snapshot
        # shape unchanged)
        self.name = str(name)
        self.state = "online"
        self.consecutive_failures = 0
        self.outages = 0
        self.outage_started_at: float | None = None
        self.last_outage_s = 0.0
        self.last_failure_source = ""

    @property
    def in_outage(self) -> bool:
        return self.state == "outage"

    def note_failure(self, source: str = "poll") -> bool:
        """Record one hive-unreachable failure; True exactly when this
        one flipped the session into OUTAGE (the caller logs and counts
        the assumed-lost leases once, not per failure)."""
        self.consecutive_failures += 1
        self.last_failure_source = str(source)
        if self.state == "online" \
                and self.consecutive_failures >= self.outage_after:
            self.state = "outage"
            self.outages += 1
            self.outage_started_at = self._clock()
            return True
        return False

    def note_success(self) -> bool:
        """Record one successful hive exchange; True exactly when it
        HEALED an outage (the caller replays the dead-letter spool)."""
        self.consecutive_failures = 0
        if self.state != "outage":
            return False
        self.state = "online"
        if self.outage_started_at is not None:
            self.last_outage_s = max(
                0.0, self._clock() - self.outage_started_at)
        self.outage_started_at = None
        return True

    def snapshot(self) -> dict[str, Any]:
        out = {
            "state": self.state,
            "consecutive_failures": self.consecutive_failures,
            "outages": self.outages,
            "last_outage_s": round(self.last_outage_s, 3),
            "last_failure_source": self.last_failure_source,
        }
        if self.name:
            out["name"] = self.name
        if self.outage_started_at is not None:
            out["outage_age_s"] = round(
                max(0.0, self._clock() - self.outage_started_at), 3)
        return out


# ---------------------------------------------------------------------------
# dead-letter spool
# ---------------------------------------------------------------------------


class DeadLetterSpool:
    """Disk spool for result envelopes whose uploads exhausted retries.

    One JSON file per envelope, named ``<job id>-<content hash>.json`` so
    re-spooling the same envelope is idempotent; the tmp-then-rename write
    keeps a crash mid-spool from leaving a half file that replay would
    then misparse. ``replay()`` yields everything spooled so the worker
    can re-queue it at startup (result durability across restarts)."""

    def __init__(self, directory: Path | str) -> None:
        self.directory = Path(directory)

    def _path_for(self, result: dict[str, Any], payload: str) -> Path:
        digest = hashlib.sha256(payload.encode("utf-8")).hexdigest()[:12]
        job_id = re.sub(r"[^A-Za-z0-9._-]+", "_",
                        str(result.get("id") or "result"))[:80]
        return self.directory / f"{job_id}-{digest}.json"

    def spool(self, result: dict[str, Any]) -> Path:
        payload = json.dumps(result, sort_keys=True)
        self.directory.mkdir(parents=True, exist_ok=True)
        path = self._path_for(result, payload)
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(payload, encoding="utf-8")
        tmp.replace(path)
        log.error("result %s spooled to dead-letter: %s",
                  result.get("id"), path)
        return path

    def replay(self) -> list[tuple[Path, dict[str, Any]]]:
        if not self.directory.is_dir():
            return []
        entries: list[tuple[Path, dict[str, Any]]] = []
        for path in sorted(self.directory.glob("*.json")):
            try:
                entries.append((path, json.loads(
                    path.read_text(encoding="utf-8"))))
            except (OSError, json.JSONDecodeError) as exc:
                log.error("unreadable dead-letter file %s (%s); parking as "
                          ".bad", path, exc)
                try:
                    path.replace(path.with_suffix(".json.bad"))
                except OSError:
                    pass
        return entries

    def discard(self, path: Path | str) -> None:
        try:
            Path(path).unlink()
        except FileNotFoundError:
            pass

    def depth(self) -> int:
        if not self.directory.is_dir():
            return 0
        return sum(1 for _ in self.directory.glob("*.json"))


# ---------------------------------------------------------------------------
# checkpoint spool (ISSUE 6: step-boundary resume state)
# ---------------------------------------------------------------------------


class CheckpointSpool:
    """Disk spool of in-flight job checkpoints — the resume-state twin of
    the dead-letter spool, namespaced per worker the same way.

    One JSON file per job id, overwritten in place as the job progresses
    (lanes snapshot per-row state at step boundaries,
    serving/stepper.py; the solo path records coarser phase markers).
    The worker's heartbeat pushes the latest state to a lease-aware hive
    (node/minihive.py) so a job redelivered after this worker dies
    resumes at step k on a survivor instead of restarting at step 0.

    Hygiene rules (ISSUE 6 satellite):

    - files live under ``<root>/checkpoints/<worker name>/`` — two
      workers sharing a settings root can never read (or garbage-
      collect) each other's state;
    - a corrupt snapshot is skipped LOUDLY: parked as ``.bad``, counted
      in ``corrupt_skipped`` (mirrored to /metrics), never returned;
    - the checkpoint of a completed job is garbage-collected the moment
      its result upload is acked (node/worker.py::_deliver), and a
      fresh startup clears leftovers wholesale — after a restart the
      hive's pushed copy is the authority, not this spool.

    Stdlib-only and thread-safe: lane driver threads save while the
    event loop's heartbeat task loads.
    """

    def __init__(self, directory: Path | str) -> None:
        self.directory = Path(directory)
        self._lock = threading.Lock()
        self.written = 0
        self.corrupt_skipped = 0
        # per-job write sequence (path name -> self.written at last save):
        # the heartbeat's has-it-changed probe. File mtime is NOT usable
        # for this — several saves can land within one timestamp tick on
        # coarse-resolution filesystems, and an "unchanged" verdict there
        # would leave a stale snapshot as the hive's resume authority.
        self._versions: dict[str, int] = {}

    def _path_for(self, job_id: Any) -> Path:
        # digest of the FULL raw id, like DeadLetterSpool._path_for:
        # sanitize+truncate alone lets distinct ids ("job 1"/"job_1", or
        # two sharing an 80-char prefix) collide onto one file — and a
        # collided checkpoint can resume the OTHER job's trajectory
        raw = str(job_id or "job")
        digest = hashlib.sha256(raw.encode("utf-8")).hexdigest()[:12]
        name = re.sub(r"[^A-Za-z0-9._-]+", "_", raw)[:80]
        return self.directory / f"{name}-{digest}.ckpt.json"

    def save(self, job_id: Any, state: dict[str, Any]) -> Path:
        payload = json.dumps(state, sort_keys=True)
        path = self._path_for(job_id)
        self.directory.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(payload, encoding="utf-8")
        tmp.replace(path)
        with self._lock:
            self.written += 1
            self._versions[path.name] = self.written
        return path

    def load(self, job_id: Any) -> dict[str, Any] | None:
        path = self._path_for(job_id)
        if not path.is_file():
            return None
        try:
            return json.loads(path.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError) as exc:
            log.error("corrupt checkpoint %s (%s); parking as .bad — the "
                      "job restarts from scratch", path, exc)
            with self._lock:
                self.corrupt_skipped += 1
            try:
                path.replace(path.with_suffix(".json.bad"))
            except OSError:
                pass
            return None

    def version(self, job_id: Any) -> int | None:
        """Monotone write sequence of a job's checkpoint, or None if
        absent — the heartbeat's cheap has-it-changed probe, so unchanged
        latent-sized snapshots are not re-read and re-pushed every beat.
        A file this process never wrote (possible only with an external
        ``checkpoint_dir``; startup clear() wipes our own leftovers)
        reports 0, which still reads as "present"."""
        path = self._path_for(job_id)
        with self._lock:
            seq = self._versions.get(path.name)
        if seq is not None:
            return seq
        return 0 if path.is_file() else None

    def discard(self, job_id: Any) -> None:
        """GC on ack: the job settled, its resume state is garbage."""
        path = self._path_for(job_id)
        with self._lock:
            self._versions.pop(path.name, None)
        try:
            path.unlink()
        except FileNotFoundError:
            pass
        except OSError as exc:
            log.warning("checkpoint GC for %s failed: %s", job_id, exc)

    def clear(self) -> int:
        """Startup hygiene: drop every leftover checkpoint — including
        parked ``.bad`` corpses and orphaned ``.tmp`` files from a crash
        mid-save, which would otherwise accumulate forever. The hive's
        heartbeat-pushed copies are the resume authority across a
        restart; stale local files would only shadow them."""
        with self._lock:
            self._versions.clear()
        if not self.directory.is_dir():
            return 0
        removed = 0
        for path in self.directory.glob("*.ckpt.json*"):
            try:
                path.unlink()
                removed += 1
            except OSError:
                pass
        if removed:
            log.info("cleared %d stale checkpoint(s) from %s", removed,
                     self.directory)
        return removed

    def depth(self) -> int:
        if not self.directory.is_dir():
            return 0
        return sum(1 for _ in self.directory.glob("*.ckpt.json"))


# the executor binds (spool, job id) for the duration of one job so
# workload callbacks can record phase checkpoints without threading the
# spool through every signature (the obs_trace.activate idiom)
_CKPT_SCOPE: contextvars.ContextVar = contextvars.ContextVar(
    "chiaswarm_checkpoint_scope", default=None)


@contextlib.contextmanager
def checkpoint_scope(spool: CheckpointSpool | None, job_id: Any):
    """Bind ``phase_checkpoint`` to (spool, job_id) for this thread's
    execution of one job (node/executor.py). A None spool (stub slots,
    checkpointing disabled) makes the scope — and every
    ``phase_checkpoint`` inside it — a no-op."""
    if spool is None or job_id is None:
        yield
        return
    token = _CKPT_SCOPE.set((spool, job_id))
    try:
        yield
    finally:
        _CKPT_SCOPE.reset(token)


def phase_checkpoint(phase: str, **extra: Any) -> None:
    """Record a coarse phase boundary for the current solo-path job
    (encoded -> denoised, workloads/diffusion.py). Solo programs have no
    step boundary to snapshot at — the marker records how far the job
    got, so redelivery telemetry can distinguish "died cold" from "died
    with the expensive denoise already done" (the finished-result case
    is the dead-letter spool's job, not this one's)."""
    scope = _CKPT_SCOPE.get()
    if scope is None:
        return
    spool, job_id = scope
    try:
        spool.save(job_id, {"version": 1, "kind": "phase",
                            "phase": str(phase), **extra})
    except OSError as exc:  # durability must never fail the job
        log.warning("phase checkpoint %r for %s failed: %s", phase,
                    job_id, exc)


# ---------------------------------------------------------------------------
# counters
# ---------------------------------------------------------------------------


_STAT_HELP = {
    "jobs_failed": "jobs whose final envelope was a failure",
    "jobs_timed_out": "jobs that exceeded their execution deadline",
    "jobs_retried": "solo re-runs taken by the degradation ladder",
    "jobs_quarantined": "jobs refused by an open circuit breaker",
    "upload_retries": "result-upload attempts that failed and retried",
    "results_dead_lettered": "results spooled after exhausting uploads",
    "results_replayed": "dead-letter results replayed at startup",
    "lease_heartbeats": "heartbeats delivered to a lease-aware hive",
    "leases_lost": "in-flight jobs whose lease the hive reassigned",
    # overload control (ISSUE 9, node/overload.py): sheds and
    # backpressure waits are capacity decisions, counted DISTINCTLY
    # from failures — a shed job is redispatchable work this node
    # declined, not work it broke
    "jobs_shed": "jobs shed by deadline-aware admission control "
                 "(overloaded, redispatched by a lease-aware hive)",
    "polls_backpressured": "poll-loop waits inserted by queue-depth "
                           "backpressure before over-committing",
    # hive-outage ride-through (ISSUE 14, swarmdurable): state the
    # worker keeps while the hive is DOWN, distinct from per-request
    # failures — an outage is one incident however many polls it eats
    "hive_outages": "consecutive-failure streaks that flipped the hive "
                    "session into OUTAGE ride-through",
    "leases_assumed_lost": "in-flight leases written off when the hive "
                           "session flipped to OUTAGE (work rides "
                           "through; results spool and replay on heal)",
    "hive_epoch_changes": "hive epoch bumps observed on grants or "
                          "heartbeat acks (the hive recovered from its "
                          "journal since we last spoke)",
}


def _stat_property(name: str):
    def get(self: "ResilienceStats") -> int:
        return int(self._counters[name].value())

    def set_(self: "ResilienceStats", value: int) -> None:
        # the worker's idiom is `stats.field += 1`; counters stay
        # monotonic because the read-modify-write only ever grows
        counter = self._counters[name]
        counter.inc(max(0, int(value) - int(counter.value())))

    return property(get, set_, doc=_STAT_HELP[name])


class ResilienceStats:
    """Worker-level failure counters, migrated onto the swarmscope
    metrics registry (ISSUE 4): each field IS a registry counter
    (``chiaswarm_<field>_total`` on the worker's /metrics), and
    ``snapshot()`` keeps the original /healthz JSON keys as a
    read-through view. The ``stats.field += 1`` call sites are
    unchanged — the properties forward to the counters."""

    _FIELDS = tuple(_STAT_HELP)

    jobs_failed = _stat_property("jobs_failed")
    jobs_timed_out = _stat_property("jobs_timed_out")
    jobs_retried = _stat_property("jobs_retried")
    jobs_quarantined = _stat_property("jobs_quarantined")
    upload_retries = _stat_property("upload_retries")
    results_dead_lettered = _stat_property("results_dead_lettered")
    results_replayed = _stat_property("results_replayed")
    lease_heartbeats = _stat_property("lease_heartbeats")
    leases_lost = _stat_property("leases_lost")
    jobs_shed = _stat_property("jobs_shed")
    polls_backpressured = _stat_property("polls_backpressured")
    hive_outages = _stat_property("hive_outages")
    leases_assumed_lost = _stat_property("leases_assumed_lost")
    hive_epoch_changes = _stat_property("hive_epoch_changes")

    def __init__(self, registry: Any = None) -> None:
        from chiaswarm_tpu.obs.metrics import Registry

        self.registry = registry if registry is not None else Registry()
        self._counters = {
            name: self.registry.counter(f"chiaswarm_{name}_total", help_)
            for name, help_ in _STAT_HELP.items()
        }

    def snapshot(self) -> dict[str, int]:
        return {name: getattr(self, name) for name in self._FIELDS}
