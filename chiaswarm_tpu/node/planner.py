"""swarmplan (ISSUE 19): the capacity-model-driven fleet autoscaler.

The hive has exported its data plane for three PRs — per-worker metric
snapshots at ``GET /api/fleet`` plus the observed-arrival EWMA (PR 13),
the measured capacity model (PR 9: jobs/s/chip under the offered
workload mix), and a crash-safe journal with exactly-once settlement
across epochs (PR 14/17). This module closes the loop: a hive-side
:class:`FleetPlanner` that, on each planning tick, folds those inputs
into (a) a **target worker count** and (b) a **per-worker model
placement plan**, with the control-theory hygiene a production loop
needs — EWMA smoothing of the demand signal, a hysteresis deadband,
scale-up/scale-down cooldowns, and hard min/max fleet bounds.

Actuation deliberately rides contracts that already exist instead of
inventing a process manager:

- **scale-up** is a *request*: the harness's worker-factory seam spawns
  the workers (``loadgen.run_load(autoscale=...)``); a real deployment's
  supervisor polls ``GET /api/plan`` and starts that many nodes.
- **scale-down** is a *graceful drain*, never the kill path: the victim
  gets ``request_stop()`` (finish in-flight, upload, exit) while
  ``expire_worker()`` preempts its leases so mid-lane jobs redeliver —
  with their journaled checkpoints — to survivors (resume_step >= 1;
  the victim's own racing upload dedupes, exactly-once holds).
- **placement** is a *hint*: the plan's per-worker model lists ride
  heartbeat acks (``ack["placement"]``), and the worker's residency
  ledger warms hinted models on idle polls before traffic shifts — the
  fleet-level generalization of the PR-8 prefetch ranking, driven by
  the same ``UserPopulation`` model affinity.

Every actuating decision is journaled (a ``plan`` HiveJournal
transition plus a flight note on the ``fleet-planner`` pseudo record),
so a recovered hive replays the planner's *intent*: a fresh planner
attached after recovery seeds its cooldown clocks and placement from
``hive.last_plan`` and does not double-actuate the decision the dead
process already made.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import time
from typing import Any, Callable

from chiaswarm_tpu.obs import metrics as obs_metrics

log = logging.getLogger(__name__)

#: the flight-record id every planner decision notes onto — one pseudo
#: record per hive holding the decision timeline (FlightRecorder.note
#: auto-opens it; verify() only audits the job ids it is given, so the
#: pseudo record never trips settlement audits)
PLAN_FLIGHT_ID = "fleet-planner"

# pre-seed the planner families on the GLOBAL registry at import
# (ISSUE 6 convention, asserted by tests/test_obs.py): a dashboard
# scraping /metrics sees zeros before the first planning tick
_TARGET = obs_metrics.planner_target_workers_gauge()
_ACTUAL = obs_metrics.planner_actual_workers_gauge()
_DECISIONS = obs_metrics.planner_decisions_counter()
_MOVES = obs_metrics.planner_placement_moves_counter()
_WORKER_HOURS = obs_metrics.planner_worker_hours_counter()
_TARGET.set(0)
_ACTUAL.set(0)
for _direction in obs_metrics.PLANNER_DIRECTIONS:
    for _reason in obs_metrics.PLANNER_REASONS:
        _DECISIONS.inc(0, direction=_direction, reason=_reason)
_MOVES.inc(0)
_WORKER_HOURS.inc(0)


@dataclasses.dataclass(frozen=True)
class PlannerConfig:
    """The autoscaler's knobs (README "Autoscaling" operator guide).

    ``capacity_jobs_s_per_worker`` is the PRIOR — the PR-9 capacity
    model's jobs/s/worker under the expected mix (BENCH's
    ``jobs_per_s_per_chip`` x chips/worker). The planner refines it
    online from observed settle throughput whenever the fleet is
    provably saturated (hive-side backlog > 0), so a wrong prior
    converges instead of oscillating."""

    min_workers: int = 1
    max_workers: int = 8
    #: plan to run workers at this fraction of measured capacity —
    #: the headroom that absorbs arrival noise between ticks
    target_utilization: float = 0.65
    #: time constant of the demand EWMA (seconds): the planner's view
    #: of the arrival rate moves on this horizon, not per-tick noise
    smoothing_window_s: float = 10.0
    #: fractional deadband around the current size — the raw target
    #: must leave ``actual x (1 +/- hysteresis)`` before actuating
    hysteresis: float = 0.2
    cooldown_up_s: float = 1.0
    cooldown_down_s: float = 5.0
    #: drain any hive-side backlog within this horizon (seconds); the
    #: backlog term is what makes a spike visible before the arrival
    #: EWMA has fully caught up
    backlog_drain_s: float = 5.0
    capacity_jobs_s_per_worker: float = 4.0
    #: blend factor for online capacity refinement (EWMA over
    #: saturated-throughput samples)
    capacity_alpha: float = 0.3
    #: how many workers the hottest model may replicate onto (scaled
    #: by its demand share; every observed model keeps >= 1 home)
    replicate_max: int = 3


class FleetPlanner:
    """One planning loop bound to one hive (or federated front).

    ``tick()`` is pure observation + decision: it never spawns or
    stops anything itself. The caller (the harness's autoscale drive,
    or a real supervisor consuming ``GET /api/plan``) actuates the
    returned decision through the seams named in the module docstring.
    Attaching the planner publishes it on the hive: ``GET /api/plan``
    starts serving and heartbeat acks start carrying placement hints.
    """

    def __init__(self, hive: Any, config: PlannerConfig | None = None,
                 *, clock: Callable[[], float] | None = None,
                 metrics_registry: Any = None) -> None:
        self.hive = hive
        self.config = config or PlannerConfig()
        # a federated front plans fleet-wide over the merged
        # fleet_snapshot; its record_plan/last_plan delegate to the
        # CURRENT shard 0 (the same convention the front's merged read
        # views follow) — bind the front, not the shard object, so a
        # shard-0 kill/restart cycle never strands the planner's
        # journal seam on a dead hive
        shards = getattr(hive, "shards", None)
        self._journal_hive = hive
        self._clock = (clock if clock is not None
                       else getattr(hive, "_clock", time.monotonic))
        reg = (metrics_registry if metrics_registry is not None
               else getattr(hive, "metrics", None))
        if reg is not None:
            self._m_target = obs_metrics.planner_target_workers_gauge(reg)
            self._m_actual = obs_metrics.planner_actual_workers_gauge(reg)
            self._m_decisions = obs_metrics.planner_decisions_counter(reg)
            self._m_moves = obs_metrics.planner_placement_moves_counter(
                reg)
            self._m_hours = obs_metrics.planner_worker_hours_counter(reg)
            self._m_target.set(0)
            self._m_actual.set(0)
            for direction in obs_metrics.PLANNER_DIRECTIONS:
                for reason in obs_metrics.PLANNER_REASONS:
                    self._m_decisions.inc(0, direction=direction,
                                          reason=reason)
            self._m_moves.inc(0)
            self._m_hours.inc(0)
        else:
            self._m_target, self._m_actual = _TARGET, _ACTUAL
            self._m_decisions, self._m_moves = _DECISIONS, _MOVES
            self._m_hours = _WORKER_HOURS
        self._demand: float | None = None
        self._last_tick: float | None = None
        self._capacity = float(self.config.capacity_jobs_s_per_worker)
        self._throughput_anchor: tuple[float, int, int, int] | None = None
        self._arrival_anchor: tuple[float, int] | None = None
        self._last_up: float = float("-inf")
        self._last_down: float = float("-inf")
        # workers this planner has already decided to drain: excluded
        # from the live view (and from re-selection) until they leave
        # the fleet snapshot, so one slow drain is never re-issued
        # tick after tick while blocking OTHER scale-down decisions
        self._draining: dict[str, float] = {}
        self._placement: dict[str, tuple[str, ...]] = {}
        self.last_decision: dict[str, Any] | None = None
        self.ticks = 0
        # recovery seam (the no-double-actuation contract): a journaled
        # hive replays its last plan into ``hive.last_plan``; seeding
        # the cooldown clocks and placement from it means a planner
        # re-attached after a crash treats the dead process's decision
        # as its own recent one instead of re-issuing it
        recovered = getattr(self._journal_hive, "last_plan", None)
        if isinstance(recovered, dict):
            at = float(recovered.get("at_s") or self._clock())
            direction = str(recovered.get("direction") or "hold")
            if direction == "up":
                self._last_up = at
            elif direction == "down":
                self._last_down = at
            placement = recovered.get("placement") or {}
            self._placement = {str(w): tuple(str(m) for m in models)
                               for w, models in placement.items()}
            for name in recovered.get("drain") or ():
                self._draining[str(name)] = at
            if recovered.get("demand_jobs_s") is not None:
                self._demand = float(recovered["demand_jobs_s"])
            if recovered.get("capacity_jobs_s_per_worker"):
                self._capacity = float(
                    recovered["capacity_jobs_s_per_worker"])
            self.last_decision = dict(recovered)
            log.info("planner seeded from journaled plan (direction=%s "
                     "at t=%.3f): cooldowns inherited, no re-actuation",
                     direction, at)
        # publish: /api/plan serves, heartbeat acks carry hints. A
        # federated front publishes on every shard too — shard
        # heartbeat acks are where the workers actually listen.
        hive.planner = self
        for shard in shards or ():
            shard.planner = self

    # ---- observation ---------------------------------------------------

    def _smooth_demand(self, observed: float, now: float) -> float:
        if self._demand is None or self._last_tick is None:
            self._demand = float(observed)
        else:
            dt = max(1e-6, now - self._last_tick)
            alpha = 1.0 - math.exp(-dt / max(1e-6,
                                             self.config.smoothing_window_s))
            self._demand += alpha * (float(observed) - self._demand)
        return self._demand

    def _observe_arrivals(self, agg: dict[str, Any], now: float) -> float:
        """The demand sample for this tick: the hive's own arrival
        EWMA rides a 30 s horizon (a dashboard quantity), which badly
        underestimates a ramp that is seconds old — so the planner also
        differentiates the hive's monotone settlement counters
        (pending + leased + completed + abandoned = total submitted)
        between its OWN ticks and takes the larger of the two. The
        per-tick delta is noisy; :meth:`_smooth_demand` owns smoothing."""
        submitted = (int(agg.get("pending_jobs") or 0)
                     + int(agg.get("leased_jobs") or 0)
                     + int(agg.get("completed_jobs") or 0)
                     + int(agg.get("abandoned_jobs") or 0))
        anchor = self._arrival_anchor
        self._arrival_anchor = (now, submitted)
        hive_ewma = float(agg.get("observed_arrival_jobs_s") or 0.0)
        if anchor is None:
            return hive_ewma
        t0, submitted0 = anchor
        if now <= t0 or submitted < submitted0:
            return hive_ewma
        return max(hive_ewma, (submitted - submitted0) / (now - t0))

    def _refine_capacity(self, agg: dict[str, Any], actual: int,
                         now: float) -> float:
        """Online refinement of the per-worker capacity prior: settle
        throughput is a true capacity sample only while the fleet is
        SATURATED (hive-side backlog waiting), otherwise it just
        measures demand — so only saturated intervals blend in."""
        done = int(agg.get("completed_jobs") or 0)
        pending = int(agg.get("pending_jobs") or 0)
        anchor = self._throughput_anchor
        self._throughput_anchor = (now, done, pending, max(1, actual))
        if anchor is None:
            return self._capacity
        t0, done0, pending0, actual0 = anchor
        dt = now - t0
        if dt <= 0 or done <= done0 or pending0 <= 0:
            return self._capacity
        sample = (done - done0) / dt / actual0
        alpha = self.config.capacity_alpha
        self._capacity += alpha * (sample - self._capacity)
        return self._capacity

    # ---- placement -----------------------------------------------------

    def _plan_placement(self, model_rates: dict[str, float],
                        names: list[str]) -> dict[str, tuple[str, ...]]:
        """Per-worker model assignment from per-model demand: every
        observed model keeps at least one home; hot models replicate
        onto more workers in proportion to their demand share (capped
        at ``replicate_max``). Deterministic: models by (-rate, name),
        homes least-loaded-first — the same inputs always produce the
        same plan, so recovery replays placement exactly."""
        if not names:
            return {}
        names = sorted(names)
        total = sum(r for r in model_rates.values() if r > 0)
        load: dict[str, list[str]] = {name: [] for name in names}
        for model, rate in sorted(model_rates.items(),
                                  key=lambda kv: (-kv[1], kv[0])):
            share = (rate / total) if total > 0 else 0.0
            replicas = max(1, min(len(names), self.config.replicate_max,
                                  math.ceil(share * len(names))))
            homes = sorted(names, key=lambda n: (len(load[n]), n))
            for name in homes[:replicas]:
                load[name].append(model)
        return {name: tuple(models)
                for name, models in load.items() if models}

    def placement_for(self, worker_name: str) -> tuple[str, ...]:
        """The current plan's model list for one worker — what the
        hive piggybacks on that worker's heartbeat acks."""
        return self._placement.get(str(worker_name), ())

    # ---- the planning tick --------------------------------------------

    def tick(self, now: float | None = None) -> dict[str, Any]:
        """One observe->decide step. Returns the decision dict (also
        kept as :attr:`last_decision` and served at ``/api/plan``).

        ``direction`` is ``up``/``down`` only when the caller should
        actuate NOW: ``spawn`` names how many workers to add, ``drain``
        names the victims to retire gracefully. Actuating decisions —
        and placement changes — are journaled; steady holds are not
        (they carry no intent a recovery could double-apply, and a
        busy hive ticks far more often than it decides)."""
        cfg = self.config
        now = self._clock() if now is None else float(now)
        snapshot = self.hive.fleet_snapshot()
        agg = snapshot.get("aggregate") or {}
        workers = snapshot.get("workers") or {}
        # settle the draining ledger: a victim that left the snapshot
        # (or stopped heartbeating) has drained; one stuck past the
        # grace window re-enters the live view and is re-decided
        for name, decided_at in list(self._draining.items()):
            entry = workers.get(name)
            gone = entry is None or not entry.get("live")
            if gone or now - decided_at > 60.0:
                del self._draining[name]
        live = {name: w for name, w in workers.items()
                if w.get("live") and not w.get("partitioned")
                and name not in self._draining}
        actual = len(live)
        observed = self._observe_arrivals(agg, now)
        backlog = int(agg.get("pending_jobs") or 0)
        capacity = self._refine_capacity(agg, actual, now)
        smoothed = self._smooth_demand(observed, now)
        backlog_rate = backlog / max(1e-6, cfg.backlog_drain_s)
        demand = smoothed + backlog_rate
        per_worker = max(1e-6, capacity * cfg.target_utilization)
        raw = demand / per_worker
        raw_desired = math.ceil(raw - 1e-9)
        desired = max(cfg.min_workers,
                      min(cfg.max_workers, raw_desired))
        # worker-hours accrue continuously (actual x wall time) — the
        # cost ledger BENCH compares against static rosters
        if self._last_tick is not None and now > self._last_tick:
            self._m_hours.inc(actual * (now - self._last_tick) / 3600.0)
        self._last_tick = now

        direction, reason = "hold", "steady"
        if desired > actual:
            direction = "up"
            reason = ("backlog" if backlog_rate > smoothed else "demand")
            if actual > 0 and raw <= actual * (1.0 + cfg.hysteresis):
                direction, reason = "hold", "hysteresis"
            elif now - self._last_up < cfg.cooldown_up_s:
                direction, reason = "hold", "cooldown"
        elif desired < actual:
            direction, reason = "down", "demand"
            if raw >= actual * (1.0 - cfg.hysteresis):
                direction, reason = "hold", "hysteresis"
            elif (now - self._last_down < cfg.cooldown_down_s
                  or now - self._last_up < cfg.cooldown_down_s):
                # a fresh scale-up also pins scale-down — for the FULL
                # down cooldown, not just the up one: the spike that
                # forced the up is exactly when a momentarily-clear
                # backlog must not be read as "demand is gone"
                direction, reason = "hold", "cooldown"
        elif raw_desired > cfg.max_workers and actual >= cfg.max_workers:
            # demand asks for more than the ceiling allows: the hold is
            # a BOUNDS hold (an operator alert), not a steady one
            direction, reason = "hold", "bounds"
        elif raw_desired < cfg.min_workers and actual <= cfg.min_workers:
            direction, reason = "hold", "bounds"

        spawn = desired - actual if direction == "up" else 0
        drain: list[str] = []
        if direction == "down":
            # fewest leases drain first (cheapest preemption: least
            # checkpoint custody to move), deterministic tie-break
            victims = sorted(live,
                             key=lambda n: (live[n].get("leased_jobs", 0),
                                            n))
            drain = victims[:actual - desired]
            for name in drain:
                self._draining[name] = now
        survivors = [name for name in live if name not in set(drain)]
        model_rates = {
            str(m): float(r)
            for m, r in (agg.get("model_arrival_jobs_s") or {}).items()}
        placement = self._plan_placement(model_rates, survivors)
        moves = sum(
            1 for name, models in placement.items()
            for model in models
            if model not in self._placement.get(name, ()))
        placement_changed = placement != self._placement
        self._placement = placement

        decision: dict[str, Any] = {
            "at_s": round(now, 6),
            "direction": direction,
            "reason": reason,
            "target": desired,
            "actual": actual,
            "spawn": spawn,
            "drain": drain,
            "demand_jobs_s": round(demand, 4),
            "observed_jobs_s": round(observed, 4),
            "backlog_jobs": backlog,
            "capacity_jobs_s_per_worker": round(capacity, 4),
            "placement": {name: list(models)
                          for name, models in placement.items()},
        }
        if direction == "up":
            self._last_up = now
        elif direction == "down":
            self._last_down = now
        self.ticks += 1
        self.last_decision = decision
        self._m_target.set(desired)
        self._m_actual.set(actual)
        self._m_decisions.inc(direction=direction, reason=reason)
        if moves:
            self._m_moves.inc(moves)
        if direction != "hold" or placement_changed:
            self._journal_hive.record_plan(decision)
        if direction != "hold":
            log.info("plan: %s %s->%s (%s; demand %.2f jobs/s, capacity "
                     "%.2f/worker)%s", direction, actual, desired, reason,
                     demand, capacity,
                     f" drain={drain}" if drain else "")
        return decision

    # ---- the supervisor contract (GET /api/plan) -----------------------

    def plan_snapshot(self) -> dict[str, Any]:
        """The ``GET /api/plan`` body a real deployment's supervisor
        consumes: the latest decision plus the knobs that produced it
        (so an operator reading the endpoint can tell WHY the target
        is what it is)."""
        return {
            "config": dataclasses.asdict(self.config),
            "ticks": self.ticks,
            "decision": self.last_decision,
        }
