"""swarmfed (ISSUE 17): the federated hive — a sharded control plane.

The reference architecture is ONE hive at chiaswarm.ai: a single
process, single WAL, single port. PR 14 made that hive crash-safe but
left it singular — the scaling AND blast-radius bottleneck between "a
durable hive" and the ROADMAP north-star. This module spends every
prerequisite PR 13/14 landed to make the control plane survive the
loss of any one of its own parts:

- **ShardRouter**: the job space partitions across H shards by a
  *stable* hash of the job id (hashlib, never Python's per-process
  salted ``hash()``) — the same job id maps to the same shard before
  and after any number of shard restarts, which is what keeps
  exactly-once settlement hash-routable across crashes.
- **ShardHive**: a full :class:`~chiaswarm_tpu.node.minihive.MiniHive`
  per shard — its own port, its own :class:`HiveJournal` under
  ``<root>/hive/<shard>/``, its own epoch book — so PR-14 recovery
  stays deterministic *per shard*. Federated grants carry
  :data:`HIVE_SHARD_KEY` so the worker routes each upload to the
  owner; a result landing on the WRONG shard forwards through the
  router to the owner, whose settle set stays the single source of
  truth (a duplicate is acked ``duplicate`` there, never
  double-settled anywhere).
- **Cross-shard work stealing**: a poll that finds its shard empty
  pulls one job from the deepest-backlog peer through the router. The
  grant is journaled by the OWNING shard (lease, attempt count, epoch
  stamp, flight record — all the owner's), so exactly-once settlement
  and recovery replay are exactly the PR-14 machinery; the steal adds
  only a journaled ``stolen`` marker and a ``{from,to}``-labeled
  counter that replay rebuilds identically.
- **FederatedHive**: the front — submits/settles by hash, serves the
  aggregated ``/api/fleet``, ``/api/stats`` (fleet-wide
  reconciliation) and ``/api/flight/<id>`` (trace ids are already
  globally unique, so PR-13 stitching generalizes: a stolen job's
  record lives whole on its owner), and owns shard lifecycle incl.
  :meth:`kill_shard` / :meth:`restart_shard` (the PR-14 SIGKILL
  contract, per shard).

Wire parity: with H=1 (or through a plain un-federated MiniHive) no
``hive_shard`` key is ever stamped — the reference hive contract is
byte-identical to PR 14's.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from pathlib import Path
from typing import Any, Callable, Iterable

from chiaswarm_tpu.node.hivelog import HIVE_SHARD_KEY, HiveJournal
from chiaswarm_tpu.node.minihive import MiniHive, kill_hive, restart_hive
from chiaswarm_tpu.obs.metrics import Registry, render_all

log = logging.getLogger("chiaswarm.federation")

__all__ = ["HIVE_SHARD_KEY", "FederatedHive", "ShardHive", "ShardRouter",
           "shard_of"]


def shard_of(job_id: Any, n_shards: int) -> int:
    """Stable job-id -> shard index. hashlib, NOT ``hash()``: Python
    salts ``hash()`` per process, which would re-partition the job
    space on every restart and break hash-routed exactly-once
    settlement (the same job id must find the same shard before and
    after a recovery)."""
    if n_shards <= 1:
        return 0
    digest = hashlib.sha256(str(job_id).encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big") % n_shards


class ShardRouter:
    """The consistent-hash partition of the job space across H shards.
    Pure function of (job id, H) — no state, so every participant
    (front, shards, workers, tests) computes the same owner."""

    def __init__(self, n_shards: int) -> None:
        self.n_shards = max(1, int(n_shards))

    def owner_index(self, job_id: Any) -> int:
        return shard_of(job_id, self.n_shards)


class ShardHive(MiniHive):
    """One shard of a federated hive: a full MiniHive (own journal, own
    epoch book, own port) plus the three federation seams — shard-key
    stamping on grants, cross-shard stealing on empty polls, and
    wrong-shard upload forwarding to the owner."""

    def __init__(self, *args: Any, shard_index: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self.shard_index = int(shard_index)
        #: back-reference set by FederatedHive.attach(); None means
        #: un-federated — every seam below degrades to plain MiniHive
        self.federation: "FederatedHive | None" = None
        m = self.metrics
        # steal accounting lives on the OWNER's registry (the grant is
        # the owner's journaled state transition, so replay rebuilds
        # this counter identically — /api/stats reconciles across
        # restarts). Pre-seeded with the self-pair so the family
        # renders zeroes from scrape one.
        self._steals = m.counter(
            "chiaswarm_hive_steals_total",
            "cross-shard steal grants journaled by this (owning) shard",
            ("from", "to"))
        self._steals.inc(0, **{"from": str(self.shard_index),
                               "to": str(self.shard_index)})
        self._forwarded = m.counter(
            "chiaswarm_hive_shard_forwarded_uploads_total",
            "uploads that landed on this (wrong) shard and were "
            "forwarded through the router to the owner")
        self._forwarded.inc(0)

    # ---- federation seams -----------------------------------------------

    def _federated(self) -> bool:
        fed = self.federation
        return fed is not None and fed.router.n_shards > 1

    def _take_jobs(self, worker_name: str) -> list[dict[str, Any]]:
        out = super()._take_jobs(worker_name)
        if not self._federated():
            return out
        if out:
            for payload in out:
                payload[HIVE_SHARD_KEY] = self.shard_index
            return out
        # empty poll on this shard: hot-spot drain — pull ONE job from
        # the deepest-backlog peer through the router. The grant below
        # is journaled by the OWNER (lease, attempt, epoch, flight),
        # so exactly-once settlement and recovery replay are unmoved.
        return self.federation.steal_for(self, worker_name)

    def steal_to(self, worker_name: str, to_shard: int
                 ) -> list[dict[str, Any]]:
        """Owner side of a steal: grant at most one queued job to a
        worker whose poll landed on (empty) shard ``to_shard``. The
        grant runs the normal journaled handout path on THIS shard;
        the steal itself is an extra journaled marker + the
        ``{from,to}`` counter, both rebuilt identically by replay."""
        saved = self.max_jobs_per_poll
        self.max_jobs_per_poll = 1
        try:
            # explicit super-call past ShardHive: the steal must never
            # re-enter the empty-poll steal seam on the owner
            granted = super()._take_jobs(worker_name)
        finally:
            self.max_jobs_per_poll = saved
        now = self._clock()
        for payload in granted:
            payload[HIVE_SHARD_KEY] = self.shard_index
            job_id = str(payload.get("id"))
            self._steals.inc(**{"from": str(self.shard_index),
                                "to": str(to_shard)})
            self.flights.note(job_id, "stolen", t=now,
                              from_shard=self.shard_index,
                              to_shard=int(to_shard), worker=worker_name)
            self._journal("stolen", id=job_id, t=now,
                          from_shard=self.shard_index,
                          to_shard=int(to_shard), worker=worker_name)
            log.info("job %s stolen from shard %d by %s (polled shard "
                     "%d)", job_id, self.shard_index, worker_name,
                     to_shard)
        self._journal_commit()
        return granted

    def _record_result(self, result: dict[str, Any],
                       worker_name: str) -> dict[str, Any]:
        # the shard identity echo is routing metadata, never stored
        # state — popped like the epoch stamp and the span digest
        result.pop(HIVE_SHARD_KEY, None)
        if self._federated():
            owner = self.federation.owner_shard(result.get("id"))
            if owner is not None and owner is not self:
                # an upload for a job this shard does not own (a stolen
                # job's worker mis-routed, a retrying client with a
                # stale shard map): forward through the router — the
                # OWNER's settle set decides exactly-once, so a
                # duplicate is acked `duplicate` there and never
                # double-settles anywhere
                self._forwarded.inc()
                log.warning("upload for %s landed on shard %d (owner "
                            "is shard %d); forwarding",
                            result.get("id"), self.shard_index,
                            owner.shard_index)
                return owner._record_result(result, worker_name)
        return super()._record_result(result, worker_name)

    def _apply_journal_event(self, record: dict[str, Any],
                             jobs: dict[str, dict[str, Any]]) -> None:
        if str(record.get("ev") or "") == "stolen":
            # replay rebuilds the steal books exactly: counter + flight
            # marker (the grant itself replays as a normal grant event)
            job_id = (None if record.get("id") is None
                      else str(record.get("id")))
            self._steals.inc(
                **{"from": str(record.get("from_shard") or 0),
                   "to": str(record.get("to_shard") or 0)})
            self.flights.note(job_id, "stolen",
                              t=float(record.get("t") or 0.0),
                              from_shard=record.get("from_shard"),
                              to_shard=record.get("to_shard"),
                              worker=record.get("worker"))
            return
        super()._apply_journal_event(record, jobs)

    def stats(self) -> dict[str, Any]:
        data = super().stats()
        data["shard_index"] = self.shard_index
        data["steals"] = {
            f"{key[0]}->{key[1]}": value
            for key, value in self._steals.series().items()
            if value > 0 or key[0] != key[1]
        }
        return data


class FederatedHive:
    """The federation front: H ShardHives + the router + the
    aggregation plane. Submits and settles route by the stable hash;
    each shard keeps its own journal/epoch book so per-shard recovery
    is exactly PR 14's contract. The front's own HTTP surface serves
    the FLEET-wide views; workers talk to the shards directly (the
    shard uris are the worker-facing control plane)."""

    def __init__(self, n_shards: int = 3, *,
                 journal_root: Path | str | None = None,
                 hive_cls: type | None = None,
                 journal_fsync: bool = True,
                 steal: bool = True,
                 clock: Callable[[], float] = time.monotonic,
                 **shard_kwargs: Any) -> None:
        self.router = ShardRouter(n_shards)
        self.hive_cls = hive_cls or ShardHive
        self.steal_enabled = bool(steal)
        self._clock = clock
        self.shard_kwargs = dict(shard_kwargs)
        self.journal_root = (None if journal_root is None
                             else Path(journal_root))
        self.journals: list[HiveJournal | None] = []
        self.shards: list[ShardHive] = []
        self.ports: list[int] = [0] * self.router.n_shards
        for index in range(self.router.n_shards):
            journal = None
            if self.journal_root is not None:
                # the documented shard layout: <root>/hive/<shard>/
                journal = HiveJournal(self.journal_root / str(index),
                                      fsync=journal_fsync)
            self.journals.append(journal)
            shard = self.hive_cls(shard_index=index, journal=journal,
                                  clock=clock, **self.shard_kwargs)
            self.attach(shard, index)
            self.shards.append(shard)
        # ---- the front's own observability plane ----
        self.metrics = Registry()
        self._depth_gauge = self.metrics.gauge(
            "chiaswarm_hive_shard_depth",
            "pending (queued, unleased) jobs per hive shard", ("shard",))
        self._epoch_gauge = self.metrics.gauge(
            "chiaswarm_hive_shard_epoch",
            "current epoch per hive shard (0 = journaling off)",
            ("shard",))
        self._leased_gauge = self.metrics.gauge(
            "chiaswarm_hive_shard_leased",
            "leased (in-flight) jobs per hive shard", ("shard",))
        for index in range(self.router.n_shards):
            self._depth_gauge.set(0, shard=str(index))
            self._leased_gauge.set(0, shard=str(index))
            self._epoch_gauge.set(0, shard=str(index))
        self.metrics.add_collector(self._refresh_shard_gauges)
        self._refresh_shard_gauges()
        self._app = None
        self._runner = None
        self.uri = ""
        self.port = 0
        # swarmplan (ISSUE 19): a FleetPlanner attached to the FRONT
        # plans fleet-wide over the merged fleet_snapshot; None keeps
        # the pre-planner surface (404 /api/plan, hint-free acks)
        self.planner: Any = None

    # ---- wiring ---------------------------------------------------------

    def attach(self, shard: ShardHive, index: int) -> ShardHive:
        """Wire a shard (fresh or recovered) into the federation at
        ``index``: the back-reference gives it the router + peers —
        and the fleet planner, so a recovered shard's heartbeat acks
        resume carrying placement hints without re-attachment."""
        shard.shard_index = int(index)
        shard.federation = self
        shard.planner = getattr(self, "planner", None)
        if index < len(self.shards):
            self.shards[index] = shard
        return shard

    @property
    def n_shards(self) -> int:
        return self.router.n_shards

    def owner_index(self, job_id: Any) -> int:
        return self.router.owner_index(job_id)

    def owner_shard(self, job_id: Any) -> ShardHive | None:
        index = self.router.owner_index(job_id)
        if 0 <= index < len(self.shards):
            return self.shards[index]
        return None

    def shard_uris(self) -> list[str]:
        return [shard.uri for shard in self.shards]

    def worker_uri(self) -> str:
        """The worker-facing control plane: every shard uri, in index
        order (Settings.hive_uris parses this back per shard)."""
        return ",".join(self.shard_uris())

    # ---- lifecycle ------------------------------------------------------

    async def start(self, *, front_port: int = 0) -> str:
        for index, shard in enumerate(self.shards):
            await shard.start(port=self.ports[index] or 0)
            self.ports[index] = shard.port
        from aiohttp import web

        self._app = web.Application()
        self._app.router.add_get("/api/stats", self._stats_endpoint)
        self._app.router.add_get("/api/fleet", self._fleet_endpoint)
        self._app.router.add_get("/api/plan", self._plan_endpoint)
        self._app.router.add_get("/api/shards", self._shards_endpoint)
        self._app.router.add_get("/api/flight", self._flights_endpoint)
        self._app.router.add_get("/api/flight/{job_id}",
                                 self._flight_endpoint)
        self._app.router.add_get("/metrics", self._metrics_endpoint)
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", front_port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        self.uri = f"http://127.0.0.1:{self.port}"
        log.info("federated hive up: front %s, shards %s", self.uri,
                 self.shard_uris())
        return self.uri

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        for shard in self.shards:
            try:
                await shard.stop()
            except Exception:  # a dead shard must not block the rest
                log.exception("shard %d stop failed", shard.shard_index)

    async def kill_shard(self, index: int) -> int:
        """SIGKILL one shard in-process (the PR-14 contract, scoped):
        its in-memory state is garbage, its journal the only survivor;
        every OTHER shard keeps serving — the blast radius this module
        exists to bound. Returns the port for :meth:`restart_shard`."""
        shard = self.shards[index]
        port = await kill_hive(shard)
        self.ports[index] = port
        log.warning("shard %d killed on port %d (%d shard(s) still "
                    "serving)", index, port, self.n_shards - 1)
        return port

    async def restart_shard(self, index: int, *,
                            lease_grace_s: float = 0.0) -> ShardHive:
        """Recover shard ``index`` from ITS OWN journal on its old port
        (riding-through worker sessions heal on their next poll) and
        wire it back into the federation. Deterministic per shard —
        no other shard's state participates."""
        journal = self.journals[index]
        if journal is None:
            raise RuntimeError(
                f"shard {index} has no journal to recover from")
        recovered = await restart_hive(
            journal, port=self.ports[index], hive_cls=self.hive_cls,
            lease_grace_s=lease_grace_s, shard_index=index,
            clock=self._clock, **self.shard_kwargs)
        self.attach(recovered, index)
        return recovered

    # ---- hash-routed control plane --------------------------------------

    def submit(self, job: dict[str, Any]) -> int:
        """Route a submission to its owner shard; returns the index."""
        index = self.router.owner_index(job.get("id"))
        self.shards[index].submit(job)
        return index

    def submit_job(self, job: dict[str, Any]) -> int:
        """LoadHive-compatible alias (the swarmload harness seam)."""
        index = self.router.owner_index(job.get("id"))
        shard = self.shards[index]
        submit = getattr(shard, "submit_job", None)
        if callable(submit):
            submit(job)
        else:
            shard.submit(job)
        return index

    def sweep(self) -> list[str]:
        out: list[str] = []
        for shard in self.shards:
            out.extend(shard.sweep())
        return out

    def steal_for(self, thief: ShardHive, worker_name: str
                  ) -> list[dict[str, Any]]:
        """Router side of a steal: find the deepest-backlog peer of
        ``thief`` and let the OWNER grant one job to the polling
        worker. No backlog anywhere -> nothing handed (the poll stays
        an honest empty poll)."""
        if not self.steal_enabled:
            return []
        # a shard partitioned from this worker must not hand it work
        # through the back door — the lease would live on a hive the
        # worker cannot heartbeat or upload to
        peers = [shard for shard in self.shards
                 if shard is not thief and shard.pending_jobs
                 and worker_name not in shard.partitioned]
        if not peers:
            return []
        victim = max(peers, key=lambda shard: len(shard.pending_jobs))
        return victim.steal_to(worker_name, thief.shard_index)

    # ---- chaos fan-out (harness parity with MiniHive) -------------------

    def partition(self, worker_name: str) -> None:
        for shard in self.shards:
            shard.partition(worker_name)

    def heal(self, worker_name: str) -> None:
        for shard in self.shards:
            shard.heal(worker_name)

    def expire_worker(self, worker_name: str) -> list[str]:
        out: list[str] = []
        for shard in self.shards:
            out.extend(shard.expire_worker(worker_name))
        return out

    def leased_ids(self, worker_name: str) -> list[str]:
        out: list[str] = []
        for shard in self.shards:
            out.extend(shard.leased_ids(worker_name))
        return sorted(out)

    def lease_holder(self, job_id: Any) -> str | None:
        shard = self.owner_shard(job_id)
        return None if shard is None else shard.lease_holder(job_id)

    # ---- merged read views (the reconciliation surface) -----------------

    def _merged_dict(self, attr: str) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for shard in self.shards:
            out.update(getattr(shard, attr))
        return out

    def _merged_list(self, attr: str) -> list[Any]:
        out: list[Any] = []
        for shard in self.shards:
            out.extend(getattr(shard, attr))
        return out

    @property
    def completed(self) -> dict[str, dict[str, Any]]:
        return self._merged_dict("completed")

    @property
    def checkpoints(self) -> dict[str, dict[str, Any]]:
        return self._merged_dict("checkpoints")

    @property
    def submitted_at(self) -> dict[str, float]:
        return self._merged_dict("submitted_at")

    @property
    def abandoned(self) -> list[str]:
        return self._merged_list("abandoned")

    @property
    def results(self) -> list[dict[str, Any]]:
        return self._merged_list("results")

    @property
    def duplicate_results(self) -> list[dict[str, Any]]:
        return self._merged_list("duplicate_results")

    @property
    def issued_ids(self) -> list[str]:
        return self._merged_list("issued_ids")

    @property
    def pending_jobs(self) -> list[dict[str, Any]]:
        return self._merged_list("pending_jobs")

    def uploaded_ids(self) -> list[str]:
        out: list[str] = []
        for shard in self.shards:
            out.extend(shard.uploaded_ids())
        return out

    async def wait_for_results(self, n: int, timeout: float = 30.0
                               ) -> list[dict[str, Any]]:
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            results = self.results
            if len(results) >= n:
                return results
            await asyncio.sleep(0.05)
        raise asyncio.TimeoutError(
            f"federation: {len(self.results)}/{n} results after "
            f"{timeout}s")

    def verify_flights(self, job_ids: Iterable[Any],
                       **kwargs: Any) -> list[dict]:
        """Fleet-wide flight completeness: each job audits against its
        OWNER shard's recorder (a stolen job's record lives whole on
        the owner — the grant, the steal marker, both epochs, and the
        settle are one stitched story there). ``kwargs`` pass through
        to :meth:`FlightRecorder.verify` (e.g. ``require_settled``)."""
        by_owner: dict[int, list[Any]] = {}
        for job_id in job_ids:
            by_owner.setdefault(self.owner_index(job_id),
                                []).append(job_id)
        problems: list[dict] = []
        for index, ids in sorted(by_owner.items()):
            problems.extend(
                self.shards[index].flights.verify(ids, **kwargs))
        return problems

    def flight(self, job_id: Any) -> dict[str, Any] | None:
        shard = self.owner_shard(job_id)
        return None if shard is None else shard.flights.get(job_id)

    # ---- aggregation plane ----------------------------------------------

    def _refresh_shard_gauges(self) -> None:
        for shard in self.shards:
            label = str(shard.shard_index)
            self._depth_gauge.set(len(shard.pending_jobs), shard=label)
            self._leased_gauge.set(len(shard.leases), shard=label)
            self._epoch_gauge.set(shard.hive_epoch, shard=label)

    def steals_total(self) -> int:
        return int(sum(
            value for shard in self.shards
            for key, value in shard._steals.series().items()))

    def stats(self) -> dict[str, Any]:
        """The fleet-wide ``/api/stats`` reconciliation: per-shard
        books plus the cross-shard totals the exactly-once tests (and
        an operator mid-incident) reconcile against — one settle per
        issued job across ALL shards, steals counted once (by their
        owner), forwards visible."""
        shards = [shard.stats() for shard in self.shards]
        self._refresh_shard_gauges()
        steals: dict[str, float] = {}
        for shard in self.shards:
            for key, value in shard._steals.series().items():
                if value <= 0 and key[0] == key[1]:
                    continue
                steals[f"{key[0]}->{key[1]}"] = \
                    steals.get(f"{key[0]}->{key[1]}", 0) + value
        return {
            "n_shards": self.n_shards,
            "shards": shards,
            "aggregate": {
                "pending": sum(s["pending"] for s in shards),
                "leased": sum(len(s["leased"]) for s in shards),
                "completed": sum(s["completed"] for s in shards),
                "duplicates": sum(s["duplicates"] for s in shards),
                "abandoned": sorted(
                    job_id for s in shards for job_id in s["abandoned"]),
                "epochs": [s["hive_epoch"] for s in shards],
                "steals": steals,
                "steals_total": self.steals_total(),
                "forwarded_uploads": int(sum(
                    shard._forwarded.value()
                    for shard in self.shards)),
            },
        }

    def fleet_snapshot(self) -> dict[str, Any]:
        """The aggregated ``/api/fleet``: per-worker entries merged
        freshest-wins across shards (a multiplexed worker heartbeats
        every shard), numeric aggregates summed where they are
        per-shard truth (queue state) and taken from the merged worker
        map where they are per-worker truth (chips, occupancy) — a
        worker reporting to H shards must count once, not H times."""
        now = self._clock()
        per_shard = [shard.fleet_snapshot() for shard in self.shards]
        workers: dict[str, dict[str, Any]] = {}
        for snapshot in per_shard:
            for name, entry in snapshot["workers"].items():
                held = workers.get(name)
                if held is None or entry["age_s"] < held["age_s"]:
                    # freshest snapshot wins; lease counts are
                    # per-shard, so they sum below instead
                    merged = dict(entry)
                    merged["leased_jobs"] = 0
                    workers[name] = merged
        for name in workers:
            workers[name]["leased_jobs"] = sum(
                len(shard.leased_ids(name)) for shard in self.shards)
        active = {name: w for name, w in workers.items()
                  if w.get("live") and not w.get("partitioned")}

        def total(key: str) -> float:
            return round(sum(float(w.get(key) or 0.0)
                             for w in active.values()), 4)

        return {
            "at_s": round(now, 6),
            "n_shards": self.n_shards,
            "workers": workers,
            "aggregate": {
                "workers_reporting": len(workers),
                "workers_live": len({
                    name for shard in self.shards
                    for name in shard.live_workers()}),
                "chips_in_service": int(total("chips_in_service")),
                "arrival_rate_rows_s": total("arrival_rate_rows_s"),
                "queue_depth": int(total("queue_depth")),
                "inflight_jobs": int(total("inflight_jobs")),
                "jobs_done": int(total("jobs_done")),
                "observed_arrival_jobs_s": round(sum(
                    s["aggregate"]["observed_arrival_jobs_s"]
                    for s in per_shard), 4),
                # per-model demand summed across shards (swarmplan,
                # ISSUE 19): jobs hash-route by id, so every shard
                # sees a slice of each model's stream — the fleet-wide
                # rate the placement plan needs is the sum
                "model_arrival_jobs_s": self._merged_model_rates(
                    per_shard),
                "pending_jobs": sum(
                    s["aggregate"]["pending_jobs"] for s in per_shard),
                "leased_jobs": sum(
                    s["aggregate"]["leased_jobs"] for s in per_shard),
                "completed_jobs": sum(
                    s["aggregate"]["completed_jobs"] for s in per_shard),
                "abandoned_jobs": sum(
                    s["aggregate"]["abandoned_jobs"] for s in per_shard),
            },
        }

    @staticmethod
    def _merged_model_rates(per_shard: list[dict[str, Any]]
                            ) -> dict[str, float]:
        merged: dict[str, float] = {}
        for snapshot in per_shard:
            rates = snapshot["aggregate"].get("model_arrival_jobs_s") or {}
            for model, rate in rates.items():
                merged[model] = merged.get(model, 0.0) + float(rate)
        return {model: round(rate, 4)
                for model, rate in sorted(merged.items())}

    # ---- the fleet planner's journal seam (swarmplan, ISSUE 19) ---------
    #
    # The front owns no journal; shard 0's book records fleet-wide
    # intent (the same convention the merged read views follow — one
    # deterministic home, replayed by that shard's recovery).

    def record_plan(self, decision: dict[str, Any]) -> None:
        self.shards[0].record_plan(decision)

    @property
    def last_plan(self) -> dict[str, Any] | None:
        return self.shards[0].last_plan

    # ---- front endpoints ------------------------------------------------

    async def _stats_endpoint(self, request):
        from aiohttp import web

        return web.json_response(self.stats())

    async def _fleet_endpoint(self, request):
        from aiohttp import web

        return web.json_response(self.fleet_snapshot())

    async def _plan_endpoint(self, request):
        """Fleet-wide ``GET /api/plan`` (swarmplan, ISSUE 19): the
        supervisor contract served from the front — one poll address
        for the whole federation."""
        from aiohttp import web

        if self.planner is None:
            return web.json_response({"error": "no planner attached"},
                                     status=404)
        return web.json_response(self.planner.plan_snapshot())

    async def _shards_endpoint(self, request):
        """``GET /api/shards`` (ISSUE 19 satellite, PR-17 residue): the
        front is an aggregation plane, not a proxy — workers must dial
        the shards directly. This endpoint closes the bootstrap gap: a
        worker configured with ONE front address fetches the shard uri
        list here (``bootstrap_shard_uris``) instead of carrying a
        hand-configured ``hive_shard_uris`` tuple."""
        from aiohttp import web

        return web.json_response({
            "n_shards": self.n_shards,
            "shards": self.shard_uris(),
            "worker_uri": self.worker_uri(),
        })

    async def _flights_endpoint(self, request):
        from aiohttp import web

        jobs: list[str] = []
        for shard in self.shards:
            jobs.extend(shard.flights.job_ids())
        return web.json_response({"n_shards": self.n_shards,
                                  "jobs": sorted(jobs)})

    async def _flight_endpoint(self, request):
        from aiohttp import web

        job_id = request.match_info.get("job_id", "")
        record = self.flight(job_id)
        if record is None:
            return web.json_response(
                {"status": "unknown",
                 "error": f"no flight record for job {job_id!r} on "
                          f"shard {self.owner_index(job_id)}"},
                status=404)
        return web.json_response(dict(
            record, shard=self.owner_index(job_id)))

    async def _metrics_endpoint(self, request):
        from aiohttp import web

        from chiaswarm_tpu.obs.metrics import CONTENT_TYPE

        body = render_all([self.metrics]
                          + [shard.metrics for shard in self.shards])
        return web.Response(text=body, content_type="text/plain",
                            charset="utf-8",
                            headers={"X-Content-Type": CONTENT_TYPE})


async def bootstrap_shard_uris(front_uri: str, *,
                               timeout_s: float = 10.0
                               ) -> tuple[str, ...]:
    """Resolve a federated front address into the worker-facing shard
    uri list via ``GET /api/shards`` (ISSUE 19 satellite). The worker
    consumes this at startup when ``hive_front_uri`` is set — one
    operator-configured address instead of a hand-maintained shard
    list that silently goes stale when the federation is resized.
    Raises on an unreachable front or a body with no shards: serving
    against a guessed control plane is worse than failing loudly."""
    import aiohttp

    url = front_uri.rstrip("/") + "/api/shards"
    timeout = aiohttp.ClientTimeout(total=max(0.1, float(timeout_s)))
    async with aiohttp.ClientSession(timeout=timeout) as session:
        async with session.get(url) as response:
            response.raise_for_status()
            body = await response.json()
    uris = tuple(str(u) for u in (body.get("shards") or ()) if u)
    if not uris:
        raise RuntimeError(
            f"front {front_uri} returned no shard uris: {body!r}")
    return uris
