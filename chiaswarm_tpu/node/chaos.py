"""Deterministic chaos harness: a fault-injecting hive + executor.

Fault tolerance proven by hope is not fault tolerance. This module drives
a REAL :class:`~chiaswarm_tpu.node.worker.Worker` — its actual poll loop,
burst drain, degradation ladder, upload retries, and shutdown path —
against scripted faults, entirely in-process and entirely deterministic
(explicit scripts, or schedules expanded from a seed):

- :class:`ChaoticHive` is an aiohttp hive whose ``/api/work`` and
  ``/api/results`` endpoints misbehave on a script: dropped connections,
  injected latency, HTTP 500s, non-JSON HTTP 400s (the misbehaving-worker
  signal), and malformed job payloads.
- :class:`ChaoticExecutor` replaces the node executor (the ``executor``
  seam on ``Worker``): each job's ``chaos`` field scripts its outcome per
  attempt — ``ok`` / ``slow`` / ``hang`` (exceeds the deadline) /
  ``crash`` (raises out of the executor) / ``oom`` / ``fetch`` (transient)
  / ``fatal`` — so retry ladders and burst splits are exercised on demand
  without compiling a single pipeline.

``tests/test_chaos.py`` asserts the invariant the whole fault-tolerance
layer exists for: under any scripted schedule, every injected job ends as
exactly one uploaded success-or-error envelope or one dead-letter file —
no silent drops — and the worker exits cleanly.

The harness is product code (not test code) so operators can smoke a
build the same way: ``python -m chiaswarm_tpu.node.smoke`` covers the
happy path, this covers the unhappy ones.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Iterable

from chiaswarm_tpu.node.executor import error_result
from chiaswarm_tpu.node.output_processor import make_text_result

log = logging.getLogger("chiaswarm.chaos")

#: fault modes a ChaoticHive poll endpoint understands
POLL_MODES = ("ok", "drop", "delay", "http_500", "bad_worker", "malformed")
#: fault modes a ChaoticHive result endpoint understands (per job id)
RESULT_MODES = ("ok", "drop", "http_500")
#: fault modes a ChaoticExecutor understands (per job attempt).
#: ``invalid`` (ISSUE 10) is the guard's poisoned-row retirement: a
#: non-fatal ``invalid_output`` envelope a lease-aware hive
#: redispatches with this worker excluded (REDISPATCH_KINDS), so fleet
#: tests exercise the redispatch path without compiling a pipeline.
EXECUTOR_MODES = ("ok", "slow", "hang", "crash", "oom", "fetch", "fatal",
                  "invalid")


class ChaosSchedule:
    """A consumable script of fault modes; exhausted scripts yield the
    default. ``from_seed`` expands a deterministic pseudo-random schedule
    (same seed -> same faults, forever) for soak-style runs; tests mostly
    pass explicit scripts."""

    def __init__(self, script: Iterable[str] | None = None,
                 default: str = "ok") -> None:
        self._script = list(script or [])
        self.default = default
        self.consumed: list[str] = []

    @classmethod
    def from_seed(cls, seed: Any, modes: tuple[str, ...], length: int,
                  default: str = "ok") -> "ChaosSchedule":
        rng = random.Random(seed)
        return cls([rng.choice(modes) for _ in range(length)],
                   default=default)

    def next(self) -> str:
        mode = self._script.pop(0) if self._script else self.default
        self.consumed.append(mode)
        return mode


def _malformed_job(n: int) -> dict[str, Any]:
    """Syntactically valid JSON, semantically garbage: carries an id (so
    the zero-loss accounting can track it) but fails argument formatting
    — the worker must upload a fatal error envelope, not choke."""
    return {"id": f"malformed-{n}", "model_name": None,
            "height": "not-a-number", "width": 64, "prompt": 3}


class ChaoticHive:
    """In-process hive with scripted fault injection on both endpoints.

    ``poll_faults`` scripts GET /api/work (one mode per request);
    ``result_faults`` maps job id -> per-attempt mode script for
    POST /api/results, so a specific result's uploads can be failed
    deterministically no matter what order uploads arrive in.
    """

    def __init__(self, poll_faults: Iterable[str] | None = None,
                 result_faults: dict[str, Iterable[str]] | None = None,
                 delay_s: float = 0.05) -> None:
        from aiohttp import web

        self.pending_jobs: list[dict[str, Any]] = []
        self.issued_ids: list[str] = []
        self.results: list[dict[str, Any]] = []
        self.result_event = asyncio.Event()
        self.poll_faults = ChaosSchedule(poll_faults)
        self.result_faults = {
            job_id: ChaosSchedule(script)
            for job_id, script in (result_faults or {}).items()
        }
        self.delay_s = float(delay_s)
        self.poll_count = 0
        self._malformed = 0
        self._app = web.Application(client_max_size=256 * 1024 * 1024)
        self._app.router.add_get("/api/work", self._work)
        self._app.router.add_post("/api/results", self._results)
        self._app.router.add_get("/api/models", self._models)
        # static test assets so image-workload jobs (img2img/inpaint —
        # lane-eligible since ISSUE 7) flow through the full
        # start_image_uri/mask_image_uri fetch path under chaos
        self._app.router.add_get("/assets/image.png", self._asset_image)
        self._app.router.add_get("/assets/mask.png", self._asset_mask)
        self._runner = None
        self.uri = ""
        self.port = 0

    # ---- job injection ----

    def submit(self, job: dict[str, Any]) -> None:
        self.pending_jobs.append(job)
        self.issued_ids.append(str(job.get("id")))

    # ---- subclass seams (node/minihive.py grows these into a real
    # lease-tracking mini-hive; the base class stays the PR-2 fault
    # injector with reference handout semantics) ----

    def _take_jobs(self, worker_name: str) -> list[dict[str, Any]]:
        """Hand out jobs for one poll (reference semantics: everything
        queued goes to the first poller)."""
        jobs, self.pending_jobs = self.pending_jobs, []
        return jobs

    def _record_result(self, result: dict[str, Any],
                       worker_name: str) -> dict[str, Any]:
        """Settle one uploaded result; returns the ack body."""
        self.results.append(result)
        self.result_event.set()
        return {"status": "ok"}

    def _worker_reachable(self, worker_name: str) -> bool:
        """Partition seam: False drops this worker's requests on the
        floor (connection reset), simulating a network partition between
        one worker and the hive."""
        return True

    @staticmethod
    def _worker_from(request) -> str:
        return str(request.query.get("worker_name", "") or "")

    # ---- static assets (deterministic inputs for image workloads) ----

    @staticmethod
    def _png_response(pixels):
        import io

        from aiohttp import web
        from PIL import Image

        buf = io.BytesIO()
        Image.fromarray(pixels).save(buf, format="PNG")
        return web.Response(body=buf.getvalue(), content_type="image/png")

    async def _asset_image(self, request):
        import numpy as np

        rng = np.random.default_rng(12)
        return self._png_response(
            rng.integers(0, 255, (64, 64, 3), dtype=np.uint8))

    async def _asset_mask(self, request):
        import numpy as np

        mask = np.zeros((64, 64), dtype=np.uint8)
        mask[32:] = 255  # regenerate the bottom half
        return self._png_response(mask)

    # ---- endpoints ----

    async def _work(self, request):
        from aiohttp import web

        self.poll_count += 1
        worker_name = self._worker_from(request)
        if not self._worker_reachable(worker_name):
            request.transport.close()
            raise ConnectionResetError("chaos: partitioned worker poll")
        mode = self.poll_faults.next()
        if mode == "drop":
            # connection dies mid-request: the client sees a disconnect,
            # queued jobs stay queued for the next (backed-off) poll
            request.transport.close()
            raise ConnectionResetError("chaos: dropped poll connection")
        if mode == "delay":
            await asyncio.sleep(self.delay_s)
        if mode == "http_500":
            return web.Response(status=500, text="chaos: hive on fire")
        if mode == "bad_worker":
            # the misbehaving-worker signal with a NON-JSON body — the
            # client must still raise BadWorkerError (hive.py get_work)
            return web.Response(status=400,
                                text="<html>chaos: bad worker</html>")
        if mode == "malformed":
            self._malformed += 1
            self.submit(_malformed_job(self._malformed))
        return web.json_response({"jobs": self._take_jobs(worker_name)})

    async def _results(self, request):
        from aiohttp import web

        # peek the id WITHOUT recording, so a faulted upload attempt is
        # not double-counted when the worker retries it
        try:
            result = await request.json()
        except Exception:
            return web.Response(status=400, text="unparseable result")
        worker_name = str(result.get("worker_name") or "")
        if not self._worker_reachable(worker_name):
            request.transport.close()
            raise ConnectionResetError("chaos: partitioned worker upload")
        job_id = str(result.get("id"))
        schedule = self.result_faults.get(job_id)
        mode = schedule.next() if schedule else "ok"
        if mode == "drop":
            request.transport.close()
            raise ConnectionResetError("chaos: dropped result connection")
        if mode == "http_500":
            return web.Response(status=500, text="chaos: results on fire")
        return web.json_response(self._record_result(result, worker_name))

    async def _models(self, request):
        from aiohttp import web

        return web.json_response({"models": []})

    # ---- lifecycle ----

    async def start(self, port: int = 0) -> str:
        """Serve on ``port`` (0 = ephemeral). A RESTARTED hive
        (swarmdurable, node/minihive.py::restart_hive) passes the dead
        hive's port so riding-through workers — whose hive URI is fixed
        at construction — heal on their next poll."""
        from aiohttp import web

        self._runner = web.AppRunner(self._app,
                                     access_log=None)  # quiet chaos noise
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", max(0, int(port)))
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        self.uri = f"http://127.0.0.1:{self.port}"
        return self.uri

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def die(self) -> int:
        """The SIGKILL chaos seam (swarmdurable): stop serving NOW.
        Sockets close under in-flight requests (clients see resets, not
        graceful errors) and nothing is flushed or said goodbye to —
        whatever a journal already committed is all that survives.
        Returns the port that just went dark."""
        port = self.port
        await self.stop()
        self._runner = None
        return port

    async def wait_for_results(self, n: int, timeout: float = 60.0) -> None:
        async def _wait():
            while len(self.results) < n:
                self.result_event.clear()
                await self.result_event.wait()

        await asyncio.wait_for(_wait(), timeout)

    def uploaded_ids(self) -> list[str]:
        return [str(result.get("id")) for result in self.results]


class ChaoticExecutor:
    """Executor stand-in with per-job, per-attempt scripted outcomes.

    A job's ``chaos`` field is a list of modes consumed one per execution
    attempt (the last entry repeats once exhausted; no ``chaos`` field
    means always ``ok``), so e.g. ``["oom", "ok"]`` fails the coalesced
    attempt and succeeds the ladder's solo re-run. ``events`` records
    ``("batch"|"solo", [job ids...])`` per attempt for assertions on HOW
    the ladder executed, not just the outcomes.
    """

    def __init__(self, hang_s: float = 5.0, slow_s: float = 0.3) -> None:
        self.hang_s = float(hang_s)
        self.slow_s = float(slow_s)
        self.attempts: dict[str, int] = {}
        self.events: list[tuple[str, list[str]]] = []
        self.started = asyncio.Event()  # first job reached the executor

    def _mode(self, job: dict[str, Any]) -> str:
        script = job.get("chaos") or []
        job_id = str(job.get("id"))
        n = self.attempts.get(job_id, 0)
        self.attempts[job_id] = n + 1
        if not script:
            return "ok"
        return str(script[min(n, len(script) - 1)])

    async def _run_one(self, job: dict[str, Any]) -> dict[str, Any]:
        mode = self._mode(job)
        if mode == "slow":
            await asyncio.sleep(self.slow_s)
            mode = "ok"
        if mode == "hang":
            await asyncio.sleep(self.hang_s)
            mode = "ok"  # too late: the deadline already envelope'd it
        if mode == "crash":
            raise RuntimeError(f"chaos: executor crash on {job.get('id')}")
        if mode == "oom":
            return error_result(
                job, "chaos: RESOURCE_EXHAUSTED: out of memory allocating "
                     "device buffer", kind="oom")
        if mode == "fetch":
            return error_result(
                job, "chaos: ConnectionError fetching input image",
                kind="transient")
        if mode == "fatal":
            return error_result(job, "chaos: unusable job inputs",
                                kind="fatal", fatal=True)
        if mode == "invalid":
            return error_result(
                job, "chaos: non-finite latents screened before upload",
                kind="invalid_output")
        return {
            "id": job.get("id"),
            "artifacts": {"primary": make_text_result(
                f"chaos ok: {job.get('id')}")},
            "nsfw": False,
            "worker_version": "chaos",
            "pipeline_config": {"chaos": True,
                                "attempt": self.attempts[str(job.get("id"))]},
        }

    async def do_work(self, job: dict[str, Any], slot, registry) -> dict:
        self.started.set()
        self.events.append(("solo", [str(job.get("id"))]))
        return await self._run_one(job)

    async def do_work_batch(self, jobs: list[dict[str, Any]], slot,
                            registry) -> list[dict]:
        self.started.set()
        self.events.append(("batch", [str(job.get("id")) for job in jobs]))
        return [await self._run_one(job) for job in jobs]
