"""Layered node configuration.

Capability parity with the reference's config system (swarm/settings.py:7-69):
a JSON settings file under a configurable root directory, overridden by
environment variables, with helpers to persist auxiliary files (e.g. the
hive model catalog). Wire-compatible field names and env vars are kept so a
chiaSWARM operator can point this worker at the same hive unchanged.

Precedence (lowest to highest): built-in defaults < settings.json < env vars.
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path
from typing import Any

# the hive protocol's adaptive poll cadence (the reference's constants,
# swarm/worker.py). They live HERE — the pure-config module — so hive.py
# (which needs aiohttp) can re-export them without config depending on an
# HTTP client: 1 s after work, 11 s idle; 121 s is the reference's flat
# error delay, kept as the CAP of the worker's exponential error backoff
# (node/resilience.py::Backoff).
POLL_BUSY_S = 1
POLL_IDLE_S = 11
POLL_ERROR_S = 121

_ENV_OVERRIDES = {
    # reference env vars (swarm/settings.py:36-38) kept for drop-in parity
    "SDAAS_URI": "hive_uri",
    "SDAAS_TOKEN": "hive_token",
    "SDAAS_WORKERNAME": "worker_name",
    # native names
    "SWARM_TPU_URI": "hive_uri",
    "SWARM_TPU_TOKEN": "hive_token",
    "SWARM_TPU_WORKERNAME": "worker_name",
    "SWARM_TPU_FRONT_URI": "hive_front_uri",
    "SWARM_TPU_LOG_LEVEL": "log_level",
}

_ROOT_ENV_VARS = ("SWARM_TPU_ROOT", "SDAAS_ROOT")


@dataclasses.dataclass
class Settings:
    """Node settings.

    Field names mirror the reference settings file (swarm/settings.py:7-15)
    via ``to_legacy_json``/``from_json`` so existing ``settings.json`` files
    keep working.
    """

    hive_uri: str = "https://chiaswarm.ai"
    # swarmfed (ISSUE 17): a federated control plane is a LIST of shard
    # uris — explicit here, or packed comma-separated into hive_uri
    # (which keeps single-uri plumbing like the loadgen worker factory
    # working unchanged). Empty = un-federated; hive_uris() resolves.
    hive_shard_uris: tuple = ()
    # swarmplan (ISSUE 19 satellite): ONE federated-front address to
    # bootstrap the shard list from (GET /api/shards) at startup —
    # overrides any stale hand-configured hive_shard_uris. Empty =
    # no bootstrap; the explicit list / hive_uri plumbing is used.
    hive_front_uri: str = ""
    hive_token: str = ""
    worker_name: str = "tpu-worker"
    log_level: str = "INFO"
    log_filename: str = "swarm-tpu.log"
    huggingface_token: str = ""
    # TPU-native additions
    mesh_shape: dict[str, int] | None = None  # e.g. {"data": 8} ; None = auto
    # auto-mesh policy: True gives leftover chips to the ``seq`` axis
    # (ring attention shortens each job) instead of ``data`` (coalescing
    # raises job throughput) — see core/mesh.py::derive_mesh_spec
    latency_mode: bool = False
    precision: str = "bfloat16"
    use_flash_attention: bool = True
    compile_cache_size: int = 4
    max_image_size: int = 1024
    default_steps: int = 30
    health_port: int = 0  # >0 serves GET /healthz (SURVEY.md §5 gap fix)
    health_host: str = "127.0.0.1"  # loopback by default (observability)
    health_bind_ephemeral: bool = False  # tests: bind port 0, read address
    # adaptive poll cadence (protocol congestion control; defaults are
    # THE protocol constants from node/hive.py — overridable so hermetic
    # chaos runs can poll fast)
    poll_busy_s: float = float(POLL_BUSY_S)
    poll_idle_s: float = float(POLL_IDLE_S)
    # ---- fault tolerance (node/resilience.py, node/worker.py) ----
    # per-job execution budget; a timed-out job uploads a structured error
    # envelope instead of silently eating the hive's patience
    job_deadline_s: float = 600.0
    # per-workflow overrides, e.g. {"txt2vid": 1800, "img2vid": 1800};
    # the "default" key (if present) replaces job_deadline_s
    workflow_deadline_s: dict[str, float] = dataclasses.field(
        default_factory=dict)
    transient_retries: int = 2          # local re-runs for transient/oom
    retry_backoff_s: float = 0.5        # ladder backoff base
    retry_backoff_cap_s: float = 30.0   # ladder backoff cap
    breaker_threshold: int = 3          # consecutive failures -> quarantine
    breaker_cooldown_s: float = 300.0   # open -> half-open probe window
    poll_backoff_base_s: float = 2.0    # poll-error backoff base
    # backoff cap = the reference's flat error delay (hive.POLL_ERROR_S)
    poll_backoff_cap_s: float = float(POLL_ERROR_S)
    upload_retries: int = 3             # result upload attempts
    upload_retry_delay_s: float = 5.0   # upload backoff base
    drain_timeout_s: float = 30.0       # shutdown: in-flight job drain
    result_drain_timeout_s: float = 20.0  # shutdown: upload-queue drain
    dead_letter_dir: str = ""           # default <settings root>/dead_letter
    install_signal_handlers: bool = True  # SIGTERM/SIGINT -> graceful stop
    # ---- fleet / lease participation (node/minihive.py) ----
    # >0: POST /api/heartbeat every N seconds with the in-flight job ids
    # and their latest resume checkpoints, so a lease-aware hive keeps
    # this worker's leases alive and can redeliver-with-resume if the
    # worker dies. The reference hive has no heartbeat endpoint — leave
    # 0 there (its timeout detector stays the only failure story).
    heartbeat_s: float = 0.0
    checkpoint_dir: str = ""            # default <root>/checkpoints/<worker>
    # hive-outage ride-through (ISSUE 14, node/resilience.py::
    # HiveSession): this many CONSECUTIVE poll/upload/heartbeat
    # failures flip the session to OUTAGE — leases assumed lost,
    # in-flight work completes, results spool after one upload attempt,
    # and the spool replays LIVE the moment the hive heals
    hive_outage_after: int = 3
    # ---- HBM model residency (serving/residency.py, ISSUE 8) ----
    # explicit resident-param budget in bytes; 0 = auto (the
    # CHIASWARM_RESIDENCY_BUDGET env var, else the classic HBM fraction
    # from core/mesh.py as the initial no-model-loaded fallback)
    residency_budget_bytes: int = 0
    # demand-driven prefetch: idle polls warm-load the hottest evicted
    # model back into free budget (CHIASWARM_RESIDENCY_PREFETCH=0 and
    # this flag both disable it)
    residency_prefetch: bool = True
    # ---- overload control (node/overload.py, ISSUE 9) ----
    # deadline-aware admission shedding + queue-depth backpressure +
    # the brownout rung. OFF by default for reference-hive parity:
    # sheds upload as non-fatal "overloaded" envelopes only a
    # lease-aware hive redispatches (node/minihive.py) — the reference
    # hive would settle them as plain errors. The swarmload harness
    # (node/loadgen.py) and lease-aware fleets turn it on.
    overload_control: bool = False
    # shed when predicted completion > margin x remaining deadline
    # budget (job "deadline_s" field, else deadline_for(workflow))
    overload_margin: float = 1.0
    # poll-loop backpressure: stop asking for work once the queued
    # backlog's drain estimate exceeds this many seconds (0 = derive
    # half the default job deadline)
    backpressure_s: float = 0.0
    # brownout rung: this many sheds inside overload_window_s tighten
    # the margin and cap lane admissions per step boundary
    overload_brownout_sheds: int = 6
    overload_window_s: float = 10.0
    overload_cooldown_s: float = 5.0
    overload_admission_cap: int = 2
    # ---- gray-failure guard (serving/guard.py, ISSUE 10) ----
    # the self-healing ladder: hang/slow-step/invalid-output events
    # grow a per-device sickness streak (hang weighs 2, the rest 1; an
    # OK event decays 1); crossing each threshold queues one rung —
    # executable-cache flush, device quarantine (slot mesh shrinks to
    # the healthy chips), graceful self-restart (exit code
    # guard.GUARD_RESTART_EXIT_CODE for supervisors). The watchdog and
    # validation knobs are env vars (CHIASWARM_GUARD*), like the
    # stepper's.
    guard_enabled: bool = True
    guard_cache_flush_after: int = 3
    guard_quarantine_after: int = 5
    guard_restart_after: int = 7
    # per-model-family deadline overrides (ISSUE 10 satellite, ROADMAP
    # 5b): {"sdxl": 45.0, ...} — consulted between a job's explicit
    # deadline_s field and the per-workflow table. The swarmload
    # harness derives suggested values from measured percentiles
    # (node/loadgen.py::score_run "suggested_deadlines" /
    # sweep_deadline_table; shipped defaults pinned by test).
    family_deadline_s: dict[str, float] = dataclasses.field(
        default_factory=dict)

    def deadline_for(self, workflow: str | None) -> float:
        """Execution budget (seconds) for one job of ``workflow`` (None /
        "" = the plain stable-diffusion path)."""
        table = self.workflow_deadline_s or {}
        default = float(table.get("default", self.job_deadline_s))
        if not workflow:
            return default
        return float(table.get(str(workflow), default))

    def hive_uris(self) -> list[str]:
        """The control-plane uris this worker multiplexes across
        (swarmfed, ISSUE 17): the explicit shard list when set, else
        ``hive_uri`` split on commas. A plain single uri yields a
        one-element list — the un-federated wire behavior."""
        if self.hive_shard_uris:
            return [str(uri).strip() for uri in self.hive_shard_uris
                    if str(uri).strip()]
        return [part.strip() for part in str(self.hive_uri).split(",")
                if part.strip()]

    @staticmethod
    def _legacy_key_map() -> dict[str, str]:
        return {
            "sdaas_uri": "hive_uri",
            "sdaas_token": "hive_token",
        }

    @classmethod
    def from_json(cls, data: dict[str, Any]) -> "Settings":
        legacy = cls._legacy_key_map()
        fields = {f.name for f in dataclasses.fields(cls)}
        kwargs: dict[str, Any] = {}
        for key, value in data.items():
            key = legacy.get(key, key)
            if key in fields:
                kwargs[key] = value
        return cls(**kwargs)

    def to_json(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_legacy_json(self) -> dict[str, Any]:
        """Emit the reference's field names for round-trip compatibility."""
        data = self.to_json()
        data["sdaas_uri"] = data.pop("hive_uri")
        data["sdaas_token"] = data.pop("hive_token")
        return data


def settings_root() -> Path:
    """Resolve the settings directory (reference: swarm/settings.py:53-64)."""
    for var in _ROOT_ENV_VARS:
        root = os.environ.get(var)
        if root:
            return Path(root).expanduser()
    return Path.home() / ".swarm-tpu"


def settings_path() -> Path:
    return settings_root() / "settings.json"


def load_settings() -> Settings:
    """Load settings.json (if present) and apply env overrides."""
    path = settings_path()
    if path.exists():
        with open(path, "r", encoding="utf-8") as fh:
            settings = Settings.from_json(json.load(fh))
    else:
        settings = Settings()
    for env, field in _ENV_OVERRIDES.items():
        value = os.environ.get(env)
        if value:
            setattr(settings, field, value)
    return settings


def save_settings(settings: Settings) -> Path:
    root = settings_root()
    root.mkdir(parents=True, exist_ok=True)
    path = settings_path()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(settings.to_json(), fh, indent=2)
    return path


def save_file(data: Any, filename: str) -> Path:
    """Persist an auxiliary JSON document under the settings root
    (reference: swarm/settings.py:67-69, used for the hive model catalog)."""
    root = settings_root()
    root.mkdir(parents=True, exist_ok=True)
    path = root / filename
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2)
    return path


def load_file(filename: str) -> Any | None:
    path = settings_root() / filename
    if not path.exists():
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)
