"""Smoke-test harness: run one hard-coded job per workflow through the real
dispatch + execution stack, no hive required.

Capability parity with swarm/test.py:7-77 (the reference's only test path),
upgraded from "edit the source to pick a job" to a CLI:

    python -m chiaswarm_tpu.node.smoke --workflow txt2img
    python -m chiaswarm_tpu.node.smoke --all --random-weights

``--random-weights`` fabricates weights for missing checkpoints so the
harness runs on a fresh node (the reference requires real downloads).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

SMOKE_JOBS: dict[str, dict[str, Any]] = {
    "txt2img": {
        "id": "smoke-txt2img",
        "model_name": "tiny",
        "prompt": "a lighthouse on a cliff at golden hour",
        "num_inference_steps": 4,
        "height": 64, "width": 64,
        "content_type": "image/png",
    },
    "img2img": {
        "id": "smoke-img2img",
        "model_name": "tiny",
        "prompt": "watercolor style",
        "num_inference_steps": 4,
        "strength": 0.6,
        "content_type": "image/png",
        "_inject_image": True,  # filled below (no network in smoke)
    },
    "txt2audio": {
        "id": "smoke-txt2audio",
        "workflow": "txt2audio",
        "model_name": "random/tiny_audio",
        "prompt": "rain on a tin roof",
        "num_inference_steps": 2,
        "audio_length_in_s": 0.1,
        "content_type": "audio/wav",
    },
    "txt2vid": {
        "id": "smoke-txt2vid",
        "workflow": "txt2vid",
        "model_name": "random/tiny_vid",
        "prompt": "a paper boat drifting",
        "num_frames": 8,
        "num_inference_steps": 2,
        "content_type": "video/mp4",
    },
    "img2txt": {
        "id": "smoke-img2txt",
        "workflow": "img2txt",
        "model_name": "Salesforce/blip-image-captioning-base",
        "content_type": "application/json",
        "_inject_image": True,
    },
    "tts": {
        # the reference's bark smoke job (swarm/test.py:45-51)
        "id": "smoke-tts",
        "workflow": "txt2audio",
        "model_name": "random/tiny_tts",
        "prompt": "hello from the swarm",
        "audio_length_in_s": 0.3,
        "content_type": "audio/wav",
    },
    "cascade": {
        "id": "smoke-cascade",
        "model_name": "DeepFloyd/tiny_cascade",
        "prompt": "a crystal fox",
        "num_inference_steps": 2,
        "sr_steps": 2,
        "upscale": False,
        "content_type": "image/png",
    },
    "img2vid": {
        # image-to-video (SVD-class; beyond the reference — BASELINE.json
        # config #5's model class), frame injected instead of a
        # start_image_uri (no network in smoke)
        "id": "smoke-img2vid",
        "workflow": "img2vid",
        "model_name": "random/tiny_svd",
        "num_frames": 8,
        "num_inference_steps": 2,
        "height": 64, "width": 64,
        "content_type": "video/mp4",
        "_inject_image": True,
    },
    "vid2vid": {
        # the reference's vid2vid smoke job (swarm/test.py:24-33), with
        # frames injected instead of a video_uri (no network in smoke)
        "id": "smoke-vid2vid",
        "workflow": "vid2vid",
        "model_name": "tiny",
        "prompt": "make it watercolor",
        "num_inference_steps": 2,
        "strength": 0.5,
        "content_type": "video/mp4",
        "_inject_frames": True,
    },
    "stitch": {
        "id": "smoke-stitch",
        "workflow": "stitch",
        "model_name": "stitch",
        "content_type": "image/png",
        "_inject_stitch_images": True,
    },
}


def run_smoke(workflow: str, random_weights: bool = True) -> dict[str, Any]:
    import numpy as np

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.node.executor import synchronous_do_work
    from chiaswarm_tpu.node.registry import ModelRegistry

    job = dict(SMOKE_JOBS[workflow])
    if job.pop("_inject_image", False):
        rng = np.random.default_rng(0)
        job["image"] = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
    if job.pop("_inject_frames", False):
        job["frames"] = [np.full((64, 64, 3), 30 * i, np.uint8)
                         for i in range(3)]
        job["fps"] = 8.0
    if job.pop("_inject_stitch_images", False):
        from PIL import Image

        job["jobs"] = [{"resultUri": f"smoke://{i}"} for i in range(3)]
        job["images"] = [Image.new("RGB", (64, 64), (40 * i, 20, 20))
                         for i in range(3)]

    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny"}],
        allow_random=random_weights,
    )
    pool = ChipPool(n_slots=1)
    return synchronous_do_work(job, pool.slots[0], registry)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--workflow", choices=sorted(SMOKE_JOBS),
                        default="txt2img")
    parser.add_argument("--all", action="store_true",
                        help="run every workflow's smoke job")
    parser.add_argument("--random-weights", action="store_true",
                        default=True)
    args = parser.parse_args(argv)

    workflows = sorted(SMOKE_JOBS) if args.all else [args.workflow]
    failures = 0
    for wf in workflows:
        result = run_smoke(wf, args.random_weights)
        config = result.get("pipeline_config", {})
        status = "error" if "error" in config else "ok"
        line = {
            "workflow": wf, "status": status,
            "fatal": bool(result.get("fatal_error")),
            "artifacts": sorted(result.get("artifacts", {})),
        }
        if status == "error":
            line["error"] = config["error"]
            failures += 1
        print(json.dumps(line))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
