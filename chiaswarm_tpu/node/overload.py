"""Overload control: deadline-aware admission, backpressure, brownout.

The worker's pre-ISSUE-9 behavior under 10x offered load is the naive
one: admit everything, watch per-job deadlines expire one by one, and
burn chip time on jobs that were doomed the moment they entered the
queue. SLO-aware serving systems (Clipper-style deadline-aware
admission) show the robust answer is to shed early and cheaply at
ADMISSION, not late and expensively at timeout. This module is that
defense, threaded through the worker (node/worker.py) and the lane
scheduler (serving/stepper.py):

- **Admission estimator**: per-workflow service-time EWMAs (fed by
  completed bursts) plus the lane step-latency EWMA predict a job's
  completion time behind the current queue. A job predicted to miss its
  deadline is shed as a non-fatal ``overloaded`` envelope — a
  :data:`~chiaswarm_tpu.node.resilience.REDISPATCH_KINDS` member, so a
  lease-aware hive requeues it with this worker excluded and a
  less-loaded node gets a chance. No chip time is burned on it.
- **Queue-depth backpressure**: when the queued backlog alone is
  predicted to outlast the backpressure budget, the poll loop stops
  asking for MORE work (counted, surfaced) instead of stacking jobs it
  will only shed later. Intake throttles; execution never stalls.
- **Brownout rung**: sustained shedding inside a sliding window trips
  brownout — the shed margin tightens (jobs shed earlier) and lane
  admissions are capped per step boundary
  (:meth:`~chiaswarm_tpu.serving.stepper.StepScheduler.set_admission_cap`)
  so resident rows finish before fresh rows splice in. The rung clears
  after a shed-free cooldown.

Everything is stdlib-only and synchronous on an injectable monotonic
clock (unit-testable without a worker, like the breaker board), and all
state surfaces as ``chiaswarm_overload_*`` metric families
(obs/metrics.py) plus the worker's ``/healthz`` ``overload`` key.

The controller is OFF by default (``overload_control`` in
settings.json): shedding only helps when the hive redispatches
``overloaded`` envelopes — the reference hive would settle them as
plain errors. Lease-aware fleets (node/minihive.py, the swarmload
harness node/loadgen.py) turn it on.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Callable

from chiaswarm_tpu.obs import metrics as obs_metrics

#: label vocabulary pre-seeded at construction so every family renders
#: zeroes from the first /metrics scrape (the ISSUE-6 convention)
SEED_WORKLOADS = ("txt2img", "img2img", "inpaint", "controlnet")


@dataclasses.dataclass(frozen=True)
class ShedDecision:
    """One admission verdict: shed or admit, with the evidence."""

    shed: bool
    predicted_s: float
    remaining_s: float
    reason: str


class OverloadController:
    """Deadline-aware admission estimator + backpressure + brownout.

    ``margin``            shed when predicted completion exceeds
                          ``margin`` x the job's remaining deadline
                          budget (1.0 = shed exactly at the predicted
                          miss; < 1 sheds earlier, > 1 later)
    ``backpressure_s``    queue-drain estimate (seconds) past which the
                          poll loop stops asking for more work
    ``brownout_sheds``    sheds within ``window_s`` that trip brownout
    ``window_s``          the sliding shed window
    ``cooldown_s``        shed-free seconds that clear brownout
    ``admission_cap_rows``  lane rows admitted per step boundary while
                          brownout holds (pushed into the step
                          schedulers by the worker)
    ``brownout_margin_scale``  how much the margin tightens in brownout
    """

    def __init__(self, *, margin: float = 1.0,
                 backpressure_s: float = 60.0,
                 brownout_sheds: int = 6,
                 window_s: float = 10.0,
                 cooldown_s: float = 5.0,
                 admission_cap_rows: int = 2,
                 brownout_margin_scale: float = 0.7,
                 alpha: float = 0.3,
                 clock: Callable[[], float] = time.monotonic,
                 metrics_registry: Any = None) -> None:
        self.margin = float(margin)
        self.backpressure_s = float(backpressure_s)
        self.brownout_sheds = max(1, int(brownout_sheds))
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.admission_cap_rows = max(1, int(admission_cap_rows))
        self.brownout_margin_scale = float(brownout_margin_scale)
        self.alpha = float(alpha)
        self._clock = clock
        self._lock = threading.Lock()
        # per-workflow service-time EWMAs; "" normalizes to txt2img (the
        # plain stable-diffusion path, node/settings.py deadline_for)
        self._service_ewma: dict[str, float] = {}
        self._overall_ewma = 0.0
        self._sheds: collections.deque[float] = collections.deque()
        self._last_shed = float("-inf")
        self.state = "normal"
        self.sheds_total = 0
        self.backpressure_waits = 0
        reg = metrics_registry
        self._m_state = obs_metrics.overload_state_gauge(reg)
        self._m_shed = obs_metrics.overload_shed_counter(reg)
        self._m_backpressure = obs_metrics.overload_backpressure_counter(reg)
        self._m_predicted = obs_metrics.overload_predicted_wait_histogram(reg)
        self._m_cap = obs_metrics.overload_admission_cap_gauge(reg)
        self._m_state.set(0)
        self._m_cap.set(0)
        self._m_backpressure.inc(0)
        for workload in SEED_WORKLOADS:
            self._m_shed.inc(0, workload=workload)

    # ---- the estimator ------------------------------------------------

    @staticmethod
    def _workload(workflow: str | None) -> str:
        return str(workflow) if workflow else "txt2img"

    def note_service(self, workflow: str | None, seconds: float) -> None:
        """Feed one completed job's wall time into the EWMAs (the worker
        times each executor attempt; shed/refused jobs never feed it —
        they would drag the estimate toward zero)."""
        seconds = max(0.0, float(seconds))
        key = self._workload(workflow)
        with self._lock:
            prev = self._service_ewma.get(key)
            self._service_ewma[key] = (
                seconds if prev is None
                else prev + self.alpha * (seconds - prev))
            self._overall_ewma = (
                seconds if self._overall_ewma <= 0.0
                else self._overall_ewma
                + self.alpha * (seconds - self._overall_ewma))

    def service_estimate(self, workflow: str | None) -> float:
        """Expected solo wall time for one job of ``workflow`` (0.0 =
        no evidence yet — a cold estimator never sheds)."""
        with self._lock:
            return self._service_ewma.get(self._workload(workflow),
                                          self._overall_ewma)

    def queue_drain_estimate(self, queued_ahead: int, slots: int) -> float:
        """Seconds until ``queued_ahead`` already-admitted jobs drain
        across ``slots`` executors, by the overall service EWMA."""
        with self._lock:
            ewma = self._overall_ewma
        return max(0, int(queued_ahead)) * ewma / max(1, int(slots))

    def should_shed(self, *, workflow: str | None, waited_s: float,
                    deadline_s: float, queued_ahead: int, slots: int,
                    lane_estimate_s: float | None = None) -> ShedDecision:
        """The admission verdict for one job about to execute.

        ``waited_s`` is how long the job has already sat on this worker
        (poll receipt -> now); ``lane_estimate_s`` is the lane-path
        prediction (job steps x the scheduler's step-latency EWMA) when
        the job would ride a lane — used as a floor under the workflow
        EWMA, so a cold EWMA cannot under-predict a long lane run."""
        now = self._clock()
        remaining = float(deadline_s) - max(0.0, float(waited_s))
        service = self.service_estimate(workflow)
        if lane_estimate_s is not None:
            service = max(service, float(lane_estimate_s))
        predicted = self.queue_drain_estimate(queued_ahead, slots) + service
        self._m_predicted.observe(predicted)
        if remaining <= 0.0:
            # needs no local-speed evidence — the budget is ALREADY
            # gone, so this sheds even on a cold (just-restarted)
            # worker; executing would only burn chip time into a
            # guaranteed miss
            return self._shed(now, workflow, predicted, remaining,
                              "deadline already expired in queue")
        if service <= 0.0:
            # no evidence about this node's speed yet: never shed on a
            # prediction the estimator cannot make
            return ShedDecision(False, predicted, remaining, "cold")
        margin = self.margin
        state = self._update_state(now)
        if state == "brownout":
            margin *= self.brownout_margin_scale
        if predicted > remaining * margin:
            return self._shed(
                now, workflow, predicted, remaining,
                f"predicted {predicted:.2f}s exceeds "
                f"{margin:.2f} x {remaining:.2f}s remaining")
        return ShedDecision(False, predicted, remaining, "admitted")

    def _shed(self, now: float, workflow: str | None, predicted: float,
              remaining: float, reason: str) -> ShedDecision:
        with self._lock:
            self.sheds_total += 1
            self._sheds.append(now)
            self._last_shed = now
        self._m_shed.inc(workload=self._workload(workflow))
        self._update_state(now)
        return ShedDecision(True, predicted, remaining, reason)

    # ---- brownout rung ------------------------------------------------

    def _update_state(self, now: float) -> str:
        with self._lock:
            while self._sheds and now - self._sheds[0] > self.window_s:
                self._sheds.popleft()
            if self.state == "normal":
                if len(self._sheds) >= self.brownout_sheds:
                    self.state = "brownout"
            elif now - self._last_shed >= self.cooldown_s:
                self.state = "normal"
                # drain the window with the transition: the sheds that
                # TRIPPED the rung must not re-trip it on the very next
                # call (state would flap normal/brownout once per poll
                # until the window ages out — caught by review)
                self._sheds.clear()
            state = self.state
        self._m_state.set(obs_metrics.OVERLOAD_STATES.index(state))
        self._m_cap.set(self.admission_cap_rows
                        if state == "brownout" else 0)
        return state

    def admission_cap(self) -> int | None:
        """Lane rows admissible per step boundary right now (None =
        uncapped). The worker pushes this into every slot's step
        scheduler on each poll and each shed."""
        return (self.admission_cap_rows
                if self._update_state(self._clock()) == "brownout"
                else None)

    # ---- backpressure -------------------------------------------------

    def poll_throttle(self, queue_depth: int, slots: int) -> float:
        """Seconds the poll loop should wait INSTEAD of asking for more
        work (0.0 = poll normally): engages when the queued backlog's
        drain estimate alone exceeds the backpressure budget. The wait
        is one service quantum, bounded — backpressure is a brake, not
        a parking brake (the loop re-evaluates every wait)."""
        drain = self.queue_drain_estimate(queue_depth, slots)
        if drain <= self.backpressure_s:
            return 0.0
        with self._lock:
            self.backpressure_waits += 1
            ewma = self._overall_ewma
        self._m_backpressure.inc()
        return min(2.0, max(0.05, ewma / 2.0))

    # ---- observability ------------------------------------------------

    def fleet_view(self) -> dict[str, Any]:
        """Compact overload state for the fleet-plane heartbeat
        (ISSUE 13, node/worker.py::_fleet_metrics -> GET /api/fleet):
        just the fields an autoscaler reads — brownout state, shed
        volume, and the per-workflow service EWMAs that price this
        node's capacity."""
        snap = self.snapshot()
        return {"state": snap["state"],
                "sheds_total": snap["sheds_total"],
                "service_ewma_s": snap["service_ewma_s"]}

    def snapshot(self) -> dict[str, Any]:
        """The /healthz ``overload`` key (node/worker.py)."""
        now = self._clock()
        state = self._update_state(now)
        with self._lock:
            return {
                "state": state,
                "sheds_total": self.sheds_total,
                "recent_sheds": len(self._sheds),
                "backpressure_waits": self.backpressure_waits,
                "admission_cap": (self.admission_cap_rows
                                  if state == "brownout" else 0),
                "margin": self.margin,
                "backpressure_s": self.backpressure_s,
                "service_ewma_s": {k: round(v, 4) for k, v in
                                   sorted(self._service_ewma.items())},
            }
