"""Artifact envelope: the uniform result format posted back to the hive.

Capability parity with the reference's OutputProcessor
(swarm/output_processor.py:10-136): every workload result becomes
``{blob: base64, content_type, thumbnail: base64, sha256_hash}``; multi-image
batches compose into square-ish grids; text results wrap as JSON; errors
render as images so the user always sees *something* (the reference's
error-as-artifact UX, swarm/generator.py:82-95).

TPU-first differences: generation hands over a single uint8 numpy batch
(device->host happens once, in the pipeline); PNG encoding — the dominant
envelope cost — runs through the native C++ codec (csrc/artifact_codec.cc
via chiaswarm_tpu.native, measured ~2x PIL at 1024px) with PIL as the
portable fallback. sha256/base64 deliberately stay on hashlib/base64:
those stdlib paths are already native (OpenSSL SHA-NI / binascii) and
benchmarked FASTER than a ctypes round-trip.
"""

from __future__ import annotations

import base64
import hashlib
import io
import json
from typing import Any, Iterable

import numpy as np
from PIL import Image, ImageDraw

from chiaswarm_tpu import native

THUMBNAIL_SIZE = 100

# grid layouts: count -> (rows, cols); mirrors the 1/2/4/6/9-up behavior of
# swarm/output_processor.py:90-118
_GRIDS = {1: (1, 1), 2: (1, 2), 3: (1, 3), 4: (2, 2), 6: (2, 3), 9: (3, 3)}


def _b64(data: bytes) -> str:
    return base64.b64encode(data).decode("ascii")


def encode_image(image: Image.Image, content_type: str = "image/png") -> bytes:
    if "png" in content_type and image.mode == "RGB":
        blob = native.png_encode_rgb(np.asarray(image))
        if blob is not None:
            return blob
    fmt = "PNG" if "png" in content_type else "JPEG"
    buf = io.BytesIO()
    if fmt == "JPEG" and image.mode != "RGB":
        image = image.convert("RGB")
    image.save(buf, format=fmt, quality=95)
    return buf.getvalue()


def thumbnail(image: Image.Image) -> bytes:
    if image.mode == "RGB":
        w, h = image.size
        scale = min(THUMBNAIL_SIZE / w, THUMBNAIL_SIZE / h, 1.0)
        tw, th = max(1, round(w * scale)), max(1, round(h * scale))
        small = native.thumbnail_rgb(np.asarray(image), tw, th)
        if small is not None:
            return encode_image(Image.fromarray(small), "image/jpeg")
    thumb = image.copy()
    thumb.thumbnail((THUMBNAIL_SIZE, THUMBNAIL_SIZE))
    return encode_image(thumb, "image/jpeg")


def image_grid(images: list[Image.Image]) -> Image.Image:
    """Compose N images into the canonical grid; odd counts pad with black."""
    n = len(images)
    if n == 1:
        return images[0]
    rows, cols = _GRIDS.get(n, ((n + 2) // 3, 3))
    w, h = images[0].size
    grid = Image.new("RGB", (cols * w, rows * h))
    for i, img in enumerate(images[: rows * cols]):
        grid.paste(img, ((i % cols) * w, (i // cols) * h))
    return grid


def image_from_text(message: str, size: tuple[int, int] = (512, 512)) -> Image.Image:
    """Render an error/status message as an image (error-as-artifact UX)."""
    img = Image.new("RGB", size, (24, 24, 28))
    draw = ImageDraw.Draw(img)
    margin, y, line_w = 16, 16, 56
    words, line = message.split(), ""
    for word in words:
        if len(line) + len(word) + 1 > line_w:
            draw.text((margin, y), line, fill=(230, 230, 230))
            y += 18
            line = word
        else:
            line = f"{line} {word}".strip()
        if y > size[1] - 32:
            break
    draw.text((margin, y), line, fill=(230, 230, 230))
    return img


def make_result(blob: bytes, content_type: str,
                thumb: bytes | None = None) -> dict[str, Any]:
    """The wire envelope: blob + thumbnail + integrity hash
    (sha256 parity with swarm/output_processor.py:46-58)."""
    return {
        "blob": _b64(blob),
        "content_type": content_type,
        "thumbnail": _b64(thumb if thumb is not None else blob),
        "sha256_hash": hashlib.sha256(blob).hexdigest(),
    }


def make_text_result(text: str | dict) -> dict[str, Any]:
    # string payloads wrap as {"caption": ...} — the wire shape hive clients
    # expect for text artifacts (swarm/output_processor.py:61-70)
    payload = json.dumps(text if isinstance(text, dict) else {"caption": text})
    blob = payload.encode("utf-8")
    return {
        "blob": _b64(blob),
        "content_type": "application/json",
        "thumbnail": _b64(blob),
        "sha256_hash": hashlib.sha256(blob).hexdigest(),
    }


class OutputProcessor:
    """Collects named artifacts for one job and emits the result dict."""

    def __init__(self, content_type: str = "image/png") -> None:
        self.content_type = content_type
        self._images: dict[str, list[Image.Image]] = {}
        self._other: dict[str, dict[str, Any]] = {}

    # ---- collection ----

    def add_images(self, images: np.ndarray | Iterable[Image.Image],
                   key: str = "primary") -> None:
        if isinstance(images, np.ndarray):
            if images.ndim == 3:
                images = images[None]
            images = [Image.fromarray(frame) for frame in images]
        self._images.setdefault(key, []).extend(images)

    def add_error(self, message: str, key: str = "primary") -> None:
        self.add_images([image_from_text(message)], key)

    def add_blob(self, blob: bytes, content_type: str, key: str,
                 thumb: bytes | None = None) -> None:
        self._other[key] = make_result(blob, content_type, thumb)

    def add_text(self, text: str | dict, key: str = "primary") -> None:
        self._other[key] = make_text_result(text)

    # ---- emission ----

    def get_results(self) -> dict[str, dict[str, Any]]:
        out: dict[str, dict[str, Any]] = {}
        for key, images in self._images.items():
            composed = image_grid(images)
            out[key] = make_result(
                encode_image(composed, self.content_type),
                self.content_type,
                thumbnail(composed),
            )
        out.update(self._other)
        return out
