"""Rotating-file + console logging (capability of swarm/log_setup.py:5-29).

Uses the stdlib RotatingFileHandler (the reference pulls in an external
concurrent-log-handler package; one process per host writes the log here, so
stdlib rotation is sufficient and dependency-free).
"""

from __future__ import annotations

import logging
import logging.handlers
from pathlib import Path

_MAX_BYTES = 50 * 1024 * 1024
_BACKUP_COUNT = 7

_FORMAT = "%(asctime)s %(levelname)s %(name)s %(message)s"


def setup_logging(log_dir: Path | str, filename: str = "swarm-tpu.log",
                  level: str = "INFO") -> logging.Logger:
    root = logging.getLogger()
    root.setLevel(getattr(logging, str(level).upper(), logging.INFO))

    log_dir = Path(log_dir)
    log_dir.mkdir(parents=True, exist_ok=True)

    have_file = any(
        isinstance(h, logging.handlers.RotatingFileHandler) for h in root.handlers
    )
    if not have_file:
        handler = logging.handlers.RotatingFileHandler(
            log_dir / filename, maxBytes=_MAX_BYTES, backupCount=_BACKUP_COUNT
        )
        handler.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(handler)

    have_stream = any(
        type(h) is logging.StreamHandler for h in root.handlers
    )
    if not have_stream:
        console = logging.StreamHandler()
        console.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(console)
    return root
