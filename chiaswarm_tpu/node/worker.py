"""The worker daemon: poll the hive, execute on mesh slots, upload results.

Capability parity with swarm/worker.py:21-195, with the reference's
concurrency bug fixed: the reference acquires the GPU semaphore both while
*polling* and while *executing* (worker.py:60,108 + 118,127), serializing
the two on single-GPU nodes (SURVEY.md §3.1). Here backpressure is the
bounded ``work_queue`` alone — the poll loop simply waits for queue space,
and each slot task owns its own execution; no shared semaphore.

Fault containment (node/resilience.py) — the reference's only failure
story is the hive's timeout detector (swarm/worker.py:92-97); here
failures are contained at the JOB level and reported explicitly:

- every burst runs under a per-workflow **deadline** (settings.py:
  ``deadline_for``); a timed-out or crashed job uploads a structured
  error envelope through the normal result path, so the hive learns of
  failures in seconds;
- the **degradation ladder**: transient faults (input-image fetch blips,
  device OOM on a coalesced burst) re-run locally with capped backoff +
  jitter — OOM'd bursts split and re-run serially — and a per-model
  circuit breaker quarantines a model in the registry after K consecutive
  permanent failures;
- **graceful shutdown**: SIGTERM/SIGINT stop polling first, in-flight
  slots and the result queue drain (bounded by the drain timeouts), and
  results that exhaust upload retries spool to a disk dead-letter
  directory that replays on the next startup — paid chip time is never
  silently discarded.

Startup gates mirror the reference's (worker.py:166-181): an accelerator
must be present (TPU/virtual-CPU mesh instead of CUDA), logging configured,
and matmul precision pinned (bf16 — the TPU analog of TF32 knobs).
"""

from __future__ import annotations

import asyncio
import functools
import logging
import random
import re
import signal
import time
from pathlib import Path
from typing import Any

import aiohttp
import jax

from chiaswarm_tpu.obs import flight as obs_flight
from chiaswarm_tpu.obs import metrics as obs_metrics
from chiaswarm_tpu.obs import profiling as obs_profiling
from chiaswarm_tpu.obs import trace as obs_trace

from chiaswarm_tpu.core.chip_pool import ChipPool
from chiaswarm_tpu.node.executor import (
    do_work,
    do_work_batch,
    error_result,
    job_rows,
    rows_cap,
    single_chip_rows,
)
from chiaswarm_tpu.node.hive import BadWorkerError, HiveClient
from chiaswarm_tpu.node.hivelog import HIVE_EPOCH_KEY, HIVE_SHARD_KEY
from chiaswarm_tpu.node.logging_setup import setup_logging
from chiaswarm_tpu.node.overload import OverloadController
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.node.resilience import (
    BREAKER_KINDS,
    RETRYABLE_KINDS,
    Backoff,
    BreakerBoard,
    CheckpointSpool,
    DeadLetterSpool,
    HiveSession,
    ResilienceStats,
    backoff_delay,
    classify_exception,
    classify_result,
    hive_reachable_error,
)
from chiaswarm_tpu.node.settings import Settings, load_settings
from chiaswarm_tpu.serving.guard import (
    GUARD_RESTART_EXIT_CODE,
    DeviceGuard,
    _slot_devices,
    suggest_hang_budget,
)

log = logging.getLogger("chiaswarm.worker")


class _HiveShard:
    """One hive shard from the worker's side (swarmfed, ISSUE 17): its
    own client, its own outage session (ride-through flips PER SHARD —
    a dead shard degrades only its own traffic while polls continue
    against the rest), its own dead-letter spool namespace, its own
    poll backoff, and its own epoch handshake (each shard recovers from
    its own journal, so epochs are per-shard truth). A single-hive
    worker holds exactly one of these — shard 0 — and the Worker's
    ``hive``/``hive_session``/``dead_letters`` properties alias it, so
    the pre-federation surface is unchanged."""

    def __init__(self, *, index: int, uri: str, client: Any,
                 session: HiveSession, spool: DeadLetterSpool,
                 backoff: Backoff) -> None:
        self.index = int(index)
        self.uri = str(uri)
        self.client = client
        self.session = session
        self.spool = spool
        self.backoff = backoff
        # the hive epoch last seen on THIS shard's grants/heartbeat
        # acks (None against a journal-less shard); echoed on uploads
        # routed here so a recovered shard dedupes pre-crash grants
        self.last_epoch: int | None = None
        # fleet-plane cadence throttle, per shard (each shard serves
        # its own /api/fleet slice of this worker's snapshots)
        self.last_metrics = float("-inf")


def _burst_key(job: dict) -> tuple | None:
    """Cheap raw-job coalescability key (None = never coalesce).

    Conservative pre-filter for the slot burst drain: plain txt2img,
    img2img and inpaint jobs with identical static fields are drained
    together (images themselves differ per job by design — per-job init
    stacks + encode seeds keep solo equality) — the executor's precise
    post-formatting grouping (node/executor.py::
    synchronous_do_work_batch) is the authority (it also sees the FETCHED
    image shapes, which this pre-filter cannot); this just keeps
    non-coalescable traffic on the per-job path so its results upload as
    soon as each job finishes."""
    if job.get("workflow") not in (None, "", "txt2img", "img2img",
                                   "inpaint"):
        return None
    if job.get("resume") is not None:
        # a redelivered job with resume state rides a lane (or runs
        # solo); coalescing it with fresh jobs would discard the resume
        return None
    model = str(job.get("model_name", ""))
    if model.startswith("DeepFloyd/") or "pix2pix" in model:
        return None
    params = job.get("parameters") or {}
    if params.get("controlnet") or params.get("upscale"):
        return None
    image = job.get("image")
    steps = job.get("num_inference_steps")
    guidance = job.get("guidance_scale")
    strength = job.get("strength")
    from chiaswarm_tpu.serving.stepper import stepper_enabled

    if stepper_enabled():
        # lanes carry steps, guidance AND the img2img strength (its
        # start index) PER ROW (serving/stepper.py): jobs differing only
        # in those fields drain as one burst and splice into one lane —
        # since ISSUE 7 that covers img2img and inpaint too, not just
        # txt2img (the mode split below still keeps workloads apart,
        # and the executor's post-format grouping stays the authority
        # for whatever falls back off a lane)
        steps = guidance = strength = None
    return (model, job.get("height"), job.get("width"),
            steps, guidance,
            job.get("lora"), job.get("textual_inversion"),
            job.get("cross_attention_scale"),
            # mode split: generation vs img2img vs inpaint (+ inline
            # image grids; URI-fetched sizes are the executor's job)
            bool(job.get("start_image_uri") or image is not None),
            bool(job.get("mask_image_uri")
                 or job.get("mask_image") is not None),
            strength,
            None if image is None else tuple(getattr(image, "shape", ())),
            repr(sorted(params.items())))




class Worker:
    """One node process: N mesh-slot executors + poll/upload tasks.

    Designed as a class (vs the reference's module globals) so tests can run
    multiple hermetic workers against a FakeHive in one process.

    ``executor`` (an object with async ``do_work(job, slot, registry)`` and
    ``do_work_batch(jobs, slot, registry)``) overrides the real executor —
    the seam the chaos harness (node/chaos.py) uses to inject scripted
    faults under a real worker.
    """

    def __init__(self, settings: Settings | None = None,
                 pool: ChipPool | None = None,
                 registry: ModelRegistry | None = None,
                 hive: HiveClient | None = None,
                 executor: Any | None = None) -> None:
        self.settings = settings or load_settings()
        # registry first: its catalog feeds the default mesh policy
        self.registry = registry or ModelRegistry(
            attn_impl="auto" if self.settings.use_flash_attention else "xla"
        )
        self.pool = pool if pool is not None else self._default_pool()
        # swarmfed (ISSUE 17): the control plane may be H hive shards
        # (settings.hive_uris() — an explicit list, or commas in
        # hive_uri); the worker multiplexes one session bundle per
        # shard. An injected ``hive`` client (the chaos/test seam)
        # pins a single bundle around it.
        self._hive_injected = hive is not None
        self.shards: list[_HiveShard] = self._build_hive_shards(hive)
        self._executor = executor
        # queue bound = total in-flight capacity: per slot, the larger of
        # its pipeline depth (transfer/compute overlap) and its data-axis
        # width (cross-job coalescing needs that many jobs queued). The
        # reference sizes its queue to the GPU count (worker.py:186).
        self.work_queue: asyncio.Queue = asyncio.Queue(
            maxsize=sum(
                max(getattr(slot, "depth", 1), slot.data_width)
                for slot in self.pool))
        self.result_queue: asyncio.Queue = asyncio.Queue()
        self._stop = asyncio.Event()
        self._draining = asyncio.Event()
        self.jobs_done = 0
        # slots currently blocked on work_queue.get(): the burst drain
        # leaves this many jobs in the queue so coalescing on one slot
        # never starves an idle neighbor (multi-slot fairness reserve)
        self._hungry_slots = 0
        # ---- observability (chiaswarm_tpu/obs, ISSUE 4) ----
        # per-WORKER registry + trace ring: hermetic test workers must
        # not bleed counters into each other; process-wide metrics
        # (compile cache, lane step timing) live on obs.metrics.REGISTRY
        # and /metrics serves both
        self.metrics = obs_metrics.Registry()
        self.traces = obs_trace.TraceRing()
        self._job_seconds = self.metrics.histogram(
            "chiaswarm_job_seconds",
            "end-to-end job wall time (poll receipt -> upload settled)")
        self._phase_seconds = self.metrics.histogram(
            "chiaswarm_job_phase_seconds",
            "per-phase job wall time from the trace spans",
            labelnames=("phase",))
        self._jobs_total = self.metrics.counter(
            "chiaswarm_jobs_total",
            "jobs settled (uploaded or dead-lettered), by final outcome",
            labelnames=("outcome",))
        self.metrics.add_collector(self._collect_metrics)
        # ---- fault-tolerance state (node/resilience.py) ----
        self.stats = ResilienceStats(self.metrics)
        # ---- overload control (node/overload.py, ISSUE 9) ----
        # always constructed (its chiaswarm_overload_* families must
        # render zeroes from scrape one), only CONSULTED when the
        # settings gate is on — reference-hive parity keeps it off
        self.overload = OverloadController(
            margin=self.settings.overload_margin,
            backpressure_s=(float(self.settings.backpressure_s)
                            or self.settings.job_deadline_s / 2.0),
            brownout_sheds=self.settings.overload_brownout_sheds,
            window_s=self.settings.overload_window_s,
            cooldown_s=self.settings.overload_cooldown_s,
            admission_cap_rows=self.settings.overload_admission_cap,
            metrics_registry=self.metrics)
        # ---- gray-failure guard (serving/guard.py, ISSUE 10) ----
        # per-worker device-health ledger + healing ladder. Always
        # constructed (its chiaswarm_guard_* families must render
        # zeroes from scrape one); rung ACTIONS apply only when the
        # settings gate is on. Lane drivers and the solo watchdog find
        # it through the slot handle, like the checkpoint spool.
        self.guard = DeviceGuard(
            enabled=self.settings.guard_enabled,
            cache_flush_after=self.settings.guard_cache_flush_after,
            quarantine_after=self.settings.guard_quarantine_after,
            restart_after=self.settings.guard_restart_after,
            metrics_registry=self.metrics)
        for slot in self.pool:
            try:
                slot._guard = self.guard
            except (AttributeError, TypeError):  # exotic slot stubs
                pass
            self.guard.seed_devices(_slot_devices(slot))
        # process exit status: 0, or GUARD_RESTART_EXIT_CODE after the
        # restart rung's graceful drain (supervisors restart-on-73)
        self.exit_code = 0
        self._retry_rng = random.Random(
            f"retry:{self.settings.worker_name}")
        # the registry mirror tolerates stub registries without
        # quarantine support (several worker tests pass object())
        # breaker state persists NEXT TO the dead-letter spool and
        # reloads here: a checkpoint quarantined before a restart stays
        # quarantined after it (the residual cooldown rides the file)
        self.breakers = BreakerBoard(
            threshold=self.settings.breaker_threshold,
            cooldown_s=self.settings.breaker_cooldown_s,
            on_open=getattr(self.registry, "quarantine", None),
            on_close=getattr(self.registry, "unquarantine", None),
            on_probe=getattr(self.registry, "unquarantine", None),
            persist_path=self._breaker_state_path())
        # dead-letter files currently riding the result queue: ONE set
        # across every shard's spool — the live replay must never
        # enqueue a spooled envelope twice, whichever shard healed
        self._replayed_paths: set[str] = set()
        self._dl_replayed = obs_metrics.dead_letter_replayed_counter(
            self.metrics)
        for when in obs_metrics.DEAD_LETTER_REPLAY_WHEN:
            self._dl_replayed.inc(0, when=when)
        # per-shard session-state gauge (swarmfed, ISSUE 17): rendered
        # with zeroes from scrape one, one series per configured shard
        shard_gauge = obs_metrics.hive_shard_session_state_gauge(
            self.metrics)
        for shard in self.shards:
            shard_gauge.set(0, shard=str(shard.index))
        # ---- fleet durability (ISSUE 6) ----
        # resume-state spool next to the dead-letter spool (same
        # per-worker namespacing); lanes snapshot into it via the slot
        # handle, heartbeats push its latest entries to a lease-aware
        # hive, and an acked upload garbage-collects the job's file.
        # Only the heartbeat ever delivers a checkpoint anywhere (the
        # spool is wholesale-cleared at startup), so with heartbeats off
        # — the reference-hive default — the spool is never attached and
        # lanes/solo jobs pay no snapshot cost for state nothing reads.
        self.checkpoints = CheckpointSpool(self._checkpoint_dir())
        if float(self.settings.heartbeat_s or 0) > 0:
            for slot in self.pool:
                try:
                    slot._checkpoint_spool = self.checkpoints
                except (AttributeError, TypeError):  # exotic slot stubs
                    pass
        # jobs between poll receipt and settled upload — the id set the
        # heartbeat keeps leased (insertion-ordered for stable payloads)
        self._inflight: dict[Any, float] = {}
        # swarmfed (ISSUE 17): which shard OWNS each in-flight job's
        # lease (stolen grants arrive via one shard's poll but belong
        # to the owner) — heartbeats and uploads route by this
        self._inflight_shard: dict[Any, int] = {}
        # ---- HBM residency (ISSUE 8, serving/residency.py) ----
        # push the operator's settings into the registry's ledger: an
        # explicit budget override, and the prefetch toggle (idle polls
        # trigger demand-driven warm loads below)
        residency = getattr(self.registry, "residency", None)
        if residency is not None:
            if int(self.settings.residency_budget_bytes or 0) > 0:
                residency.set_budget(
                    int(self.settings.residency_budget_bytes))
            residency.prefetch_enabled = bool(
                self.settings.residency_prefetch
                and residency.prefetch_enabled)

    def _spool_dirname(self) -> str:
        return re.sub(r"[^A-Za-z0-9._-]+", "_",
                      self.settings.worker_name or "worker")

    def _checkpoint_dir(self) -> Path:
        if self.settings.checkpoint_dir:
            return Path(self.settings.checkpoint_dir).expanduser()
        from chiaswarm_tpu.node.settings import settings_root

        return settings_root() / "checkpoints" / self._spool_dirname()

    def _breaker_state_path(self) -> Path:
        spool = self._dead_letter_dir()
        # sibling FILE, not inside the spool: replay() globs *.json there
        return spool.parent / f"{spool.name}.breakers.json"

    def _dead_letter_dir(self) -> Path:
        if self.settings.dead_letter_dir:
            return Path(self.settings.dead_letter_dir).expanduser()
        from chiaswarm_tpu.node.settings import settings_root

        # namespaced by worker name: hermetic test workers (and multiple
        # workers sharing one settings root) must never replay — and then
        # DELETE — each other's spooled results
        return settings_root() / "dead_letter" / self._spool_dirname()

    def _shard_dead_letter_dir(self, index: int) -> Path:
        """Per-shard spool namespacing (swarmfed, ISSUE 17): shard 0
        keeps the historical directory (the breaker state file is its
        sibling, and single-hive workers never see a suffix); shards
        beyond it suffix the dirname so one shard's heal never replays
        — and then deletes — envelopes owed to another."""
        base = self._dead_letter_dir()
        if index <= 0:
            return base
        return base.parent / f"{base.name}__shard{index}"

    def _build_hive_shards(self, hive: Any | None) -> list[_HiveShard]:
        uris = self.settings.hive_uris() or [self.settings.hive_uri]
        if hive is not None:
            # an injected client (chaos/test seam) IS the control
            # plane: one bundle, whatever the settings say
            uris = uris[:1]
        shards: list[_HiveShard] = []
        for index, uri in enumerate(uris):
            client = hive if hive is not None else HiveClient(
                uri, self.settings.hive_token, self.settings.worker_name)
            # shard 0 keeps the historical backoff seed so single-hive
            # chaos schedules reproduce exactly; further shards
            # decorrelate from it AND from each other
            seed = (f"poll:{self.settings.worker_name}" if index == 0
                    else f"poll:{self.settings.worker_name}:{index}")
            shards.append(_HiveShard(
                index=index, uri=uri, client=client,
                session=HiveSession(
                    outage_after=self.settings.hive_outage_after,
                    name=f"shard{index}" if len(uris) > 1 else ""),
                spool=DeadLetterSpool(self._shard_dead_letter_dir(index)),
                backoff=Backoff(
                    base=self.settings.poll_backoff_base_s,
                    cap=self.settings.poll_backoff_cap_s,
                    seed=seed)))
        return shards

    async def _bootstrap_from_front(self) -> None:
        """Shard-list bootstrap (ISSUE 19 satellite, PR-17 residue):
        ``hive_front_uri`` names ONE federated front; the worker
        resolves it into the live shard uri list via ``GET
        /api/shards`` and rebuilds its session bundles from that —
        replacing any stale hand-configured list. An injected hive
        client (the chaos/test seam) always wins: it IS the control
        plane. Raises on an unreachable front: polling a guessed
        shard list would serve the wrong federation silently."""
        front = str(self.settings.hive_front_uri or "").strip()
        if not front or self._hive_injected:
            return
        from chiaswarm_tpu.node.federation import bootstrap_shard_uris

        uris = await bootstrap_shard_uris(front)
        if list(uris) == self.settings.hive_uris():
            return
        log.info("bootstrapped %d shard uri(s) from front %s",
                 len(uris), front)
        self.settings.hive_shard_uris = tuple(uris)
        self.settings.hive_uri = uris[0]
        self.shards = self._build_hive_shards(None)

    # single-hive compatibility surface: shard 0 IS the pre-federation
    # worker state (read-only views — nothing may rebind these)

    @property
    def hive(self) -> Any:
        return self.shards[0].client

    @property
    def hive_session(self) -> HiveSession:
        return self.shards[0].session

    @property
    def dead_letters(self) -> DeadLetterSpool:
        return self.shards[0].spool

    @property
    def _poll_backoff(self) -> Backoff:
        return self.shards[0].backoff

    @property
    def _last_hive_epoch(self) -> int | None:
        return self.shards[0].last_epoch

    def _default_pool(self) -> ChipPool:
        """One slot over all chips. An explicit ``mesh_shape`` setting
        wins; otherwise dp x tp derives from the device count and the
        heaviest catalog family (core/mesh.py::derive_mesh_spec) — a
        stock multi-chip node engages tensor parallelism exactly when a
        served model needs it, with no operator configuration."""
        from chiaswarm_tpu.core.mesh import MeshSpec, derive_mesh_spec

        if self.settings.mesh_shape:
            spec = MeshSpec(dict(self.settings.mesh_shape))
        else:
            spec = derive_mesh_spec(len(jax.devices()),
                                    self._heaviest_catalog_bytes(),
                                    latency=self.settings.latency_mode)
            log.info("derived default mesh: %s", spec.shape)
        return ChipPool(n_slots=1, mesh_spec=spec)

    def _heaviest_catalog_bytes(self) -> int | None:
        """Footprint of the heaviest model the catalog serves (None =
        empty catalog), feeding the default dp x tp mesh policy.

        MEASURED first (ISSUE 8): the residency ledger persists real
        per-model footprints across restarts (serving/residency.py), so
        a node that has served its catalog before derives its mesh from
        live numbers. Models never measured fall back to the bf16
        family estimate — the pre-ISSUE-8 knob, kept exactly for this
        no-model-has-loaded-yet case. Non-SD names (tts/audio/caption)
        fall through get_family to sd15 — a small, harmless overestimate
        that never turns tp on by itself."""
        try:
            from chiaswarm_tpu.models.configs import get_family
            from chiaswarm_tpu.pipelines.components import (
                estimate_family_bytes,
            )

            names = self.registry.known_models()
            if not names:
                return None
            residency = getattr(self.registry, "residency", None)
            measured = (residency.measured_footprints()
                        if residency is not None else {})
            heaviest = 0
            for name in names:
                nbytes = measured.get(name)
                if nbytes is None:
                    nbytes = estimate_family_bytes(get_family(name).name)
                heaviest = max(heaviest, int(nbytes))
            return heaviest or None
        except Exception as exc:  # policy must never block startup
            log.warning("mesh policy estimate failed (%s); using dp-only",
                        exc)
            return None

    # ---- lifecycle ----

    def startup(self) -> None:
        devices = jax.devices()
        if not devices:
            raise RuntimeError("no accelerator devices present; quitting")
        from chiaswarm_tpu.node.settings import settings_root

        setup_logging(settings_root() / "logs", self.settings.log_filename,
                      self.settings.log_level)
        log.info("worker %s: %d device(s), %d slot(s), backend=%s",
                 self.settings.worker_name, len(devices), len(self.pool),
                 jax.default_backend())
        # bf16 matmuls on the MXU — the TPU analog of the reference's
        # TF32/cudnn.benchmark startup knobs (swarm/worker.py:179-181)
        jax.config.update("jax_default_matmul_precision", "bfloat16")
        # amortize XLA compiles across worker restarts
        from chiaswarm_tpu.core.compile_cache import (
            enable_persistent_compilation_cache,
        )

        enable_persistent_compilation_cache()

    def request_stop(self) -> None:
        self._stop.set()

    def _install_signal_handlers(self, loop) -> list:
        """SIGTERM/SIGINT trigger the graceful-drain path instead of
        killing in-flight paid chip time (settings gate for embedders)."""
        if not self.settings.install_signal_handlers:
            return []
        installed = []
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, self.request_stop)
                installed.append(sig)
            except (NotImplementedError, RuntimeError, ValueError):
                pass  # non-main thread / non-unix loop
        return installed

    @staticmethod
    def _remove_signal_handlers(loop, installed) -> None:
        for sig in installed:
            try:
                loop.remove_signal_handler(sig)
            except (NotImplementedError, RuntimeError, ValueError):
                pass

    def _replay_dead_letters(self, when: str = "startup",
                             shards: list[_HiveShard] | None = None
                             ) -> int:
        """Re-queue spooled results for upload. ``startup`` is the PR-2
        path (worker restarted under a hive outage); ``live`` is the
        ISSUE-14 ride-through — a hive (shard) healed mid-run, so ITS
        spool drains NOW instead of waiting for the next worker restart
        (swarmfed: a per-shard heal replays only that shard's spool —
        envelopes owed to a still-dead shard stay put). A file is only
        discarded after ITS upload succeeds (_deliver);
        ``_replayed_paths`` keeps a file that is already riding the
        result queue from enqueueing twice."""
        replayed = 0
        multiplexed = len(self.shards) > 1
        for shard in (self.shards if shards is None else shards):
            found = 0
            for path, result in shard.spool.replay():
                key = str(path)
                if key in self._replayed_paths:
                    continue  # already riding from an earlier replay
                self._replayed_paths.add(key)
                result["_dead_letter_path"] = key
                if multiplexed:
                    # route the replayed envelope to the shard whose
                    # spool held it (already stamped when its grant
                    # carried a shard key; stamped here for shutdown-
                    # spooled envelopes that never reached _deliver)
                    result.setdefault(HIVE_SHARD_KEY, shard.index)
                self.result_queue.put_nowait(result)
                self.stats.results_replayed += 1
                self._dl_replayed.inc(when=when)
                found += 1
            if found:
                log.warning("replaying %d dead-letter result(s) from %s "
                            "(%s)", found, shard.spool.directory, when)
            replayed += found
        return replayed

    # ---- hive-session bookkeeping (ISSUE 14; per-shard since 17) ----

    def _note_hive_ok(self, shard: _HiveShard | None = None) -> None:
        """A poll/upload/heartbeat reached this shard and succeeded; a
        heal drains the shard's dead-letter spool live — spooled chip
        time lands the moment the shard is back, no restart needed."""
        shard = shard if shard is not None else self.shards[0]
        if shard.session.note_success():
            log.warning(
                "hive%s healed after %.1fs outage; replaying its "
                "dead-letter spool live",
                f" shard {shard.index}" if len(self.shards) > 1 else "",
                shard.session.last_outage_s)
            self._replay_dead_letters(when="live", shards=[shard])

    def _note_hive_failure(self, source: str, exc: Exception,
                           shard: _HiveShard | None = None) -> None:
        """A poll/upload/heartbeat could not reach this shard. An HTTP
        4xx is excluded — the hive ANSWERED (a reference hive 404ing
        heartbeats must not read as an outage while polls succeed)."""
        if hive_reachable_error(exc):
            return
        shard = shard if shard is not None else self.shards[0]
        if shard.session.note_failure(source):
            # only THIS shard's leases are assumed lost: jobs owned by
            # the surviving shards keep their heartbeat coverage (the
            # blast-radius bound federation exists for)
            assumed = sum(
                1 for job_id in self._inflight
                if self._inflight_shard.get(job_id, 0) == shard.index)
            self.stats.hive_outages += 1
            if assumed:
                self.stats.leases_assumed_lost += assumed
            log.error(
                "hive%s OUTAGE after %d consecutive %s failure(s); %d "
                "in-flight lease(s) assumed lost — work rides through, "
                "results spool to dead-letter and replay on heal",
                f" shard {shard.index}" if len(self.shards) > 1 else "",
                shard.session.consecutive_failures, source, assumed)

    def _note_hive_epoch(self, raw: Any,
                         shard: _HiveShard | None = None) -> int | None:
        """Track the epoch stamped on a shard's grants/heartbeat acks;
        a bump means THAT shard recovered from its journal since we
        last spoke — every pre-bump lease it held is void (the
        recovered shard redelivers them), which the ride-through
        already assumed. Epochs are per-shard truth: shard 2 restarting
        must not void shard 1's leases."""
        try:
            epoch = None if raw is None else int(raw)
        except (TypeError, ValueError):
            return None
        if epoch is None:
            return None
        shard = shard if shard is not None else self.shards[0]
        previous = shard.last_epoch
        if previous is not None and epoch != previous:
            self.stats.hive_epoch_changes += 1
            log.warning("hive%s epoch %d -> %d: the hive recovered "
                        "from its journal; pre-recovery leases are void "
                        "and their jobs will redeliver",
                        f" shard {shard.index}"
                        if len(self.shards) > 1 else "",
                        previous, epoch)
        shard.last_epoch = epoch
        return epoch

    def _note_placement(self, raw: Any) -> None:
        """Feed a heartbeat ack's ``placement`` hint (swarmplan,
        ISSUE 19 — the fleet planner's model assignment for THIS
        worker) into the residency ledger: the next idle poll warms
        hinted models first, so placement shifts land before the
        traffic does. Malformed or absent hints are ignored — the
        hint is advisory, never load-bearing for correctness."""
        if not isinstance(raw, (list, tuple)) or not raw:
            return
        residency = getattr(self.registry, "residency", None)
        if residency is None:
            return
        try:
            residency.note_placement([str(m) for m in raw])
        except Exception:  # stub registries
            log.debug("placement hint dropped", exc_info=True)

    async def run(self) -> None:
        await self._bootstrap_from_front()
        self.startup()
        self._replay_dead_letters()
        # stale resume state from a previous run is superseded by the
        # hive's heartbeat-pushed copies (a redelivered job arrives WITH
        # its resume payload); leftovers would only shadow them
        self.checkpoints.clear()
        # bind the health endpoint BEFORE spawning workers: a port clash
        # must fail fast, not leave unsupervised poll/slot tasks running
        health_runner = await self._start_health_server()
        loop = asyncio.get_running_loop()
        signals = self._install_signal_handlers(loop)
        slot_tasks = [
            asyncio.create_task(self._slot_worker(slot), name=f"slot{i}")
            for i, slot in enumerate(self.pool)
        ]
        result_task = asyncio.create_task(self._result_worker(),
                                          name="results")
        # one poll loop per hive shard (swarmfed, ISSUE 17): each runs
        # its own backoff/outage state, so a dead shard slows only its
        # own loop while the rest keep feeding the work queue
        poll_tasks = [
            asyncio.create_task(self._poll_loop(shard),
                                name=(f"poll{shard.index}"
                                      if len(self.shards) > 1
                                      else "poll"))
            for shard in self.shards
        ]
        tasks = slot_tasks + [result_task] + poll_tasks
        if float(self.settings.heartbeat_s or 0) > 0:
            # heartbeats outlive the poll loop on purpose: they keep the
            # leases of draining in-flight jobs alive until the final
            # task cancellation below
            tasks.append(asyncio.create_task(self._heartbeat_loop(),
                                             name="heartbeat"))
        try:
            await self._stop.wait()
            await self._shutdown(poll_tasks, slot_tasks, result_task)
        finally:
            for task in tasks:
                task.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            # anything still queued embodies paid chip time: spool it
            self._spool_unsent_results()
            # refresh persisted breaker cooldowns (they survive restarts)
            self.breakers.save()
            if health_runner is not None:
                await health_runner.cleanup()
            self._remove_signal_handlers(loop, signals)

    async def _shutdown(self, poll_tasks, slot_tasks, result_task) -> None:
        """Graceful drain: polling halts first, in-flight slots finish,
        queued results upload — each phase bounded by its timeout so a
        wedged dependency cannot hold the process hostage."""
        log.info("stopping: polling halts; %d queued job(s) + in-flight "
                 "work drain, then %d pending result(s) upload",
                 self.work_queue.qsize(), self.result_queue.qsize())
        if not isinstance(poll_tasks, (list, tuple)):
            poll_tasks = [poll_tasks]
        for poll_task in poll_tasks:
            poll_task.cancel()
        await asyncio.gather(*poll_tasks, return_exceptions=True)
        self._draining.set()
        try:
            await asyncio.wait_for(
                asyncio.gather(*slot_tasks, return_exceptions=True),
                timeout=self.settings.drain_timeout_s)
        except asyncio.TimeoutError:
            log.error("slot drain exceeded %.0fs; cancelling in-flight "
                      "jobs (the hive recovers them via its timeout "
                      "detector)", self.settings.drain_timeout_s)
            for task in slot_tasks:
                task.cancel()
            await asyncio.gather(*slot_tasks, return_exceptions=True)
        # retire step-scheduler lanes: drained bursts already collected
        # their rows; anything still resident (abandoned executor threads
        # after a timed-out drain) fails over to the per-job path or an
        # envelope — rows are never silently dropped
        for slot in self.pool:
            stepper = getattr(slot, "_stepper", None)
            if stepper is not None:
                stepper.shutdown()
        try:
            await asyncio.wait_for(
                self.result_queue.join(),
                timeout=self.settings.result_drain_timeout_s)
        except asyncio.TimeoutError:
            log.error("result drain exceeded %.0fs; unsent results spool "
                      "to the dead-letter directory",
                      self.settings.result_drain_timeout_s)
        result_task.cancel()
        await asyncio.gather(result_task, return_exceptions=True)

    def _spool_unsent_results(self) -> None:
        """Shutdown durability: whatever the result worker never got to
        goes to disk, not to /dev/null."""
        while True:
            try:
                result = self.result_queue.get_nowait()
            except asyncio.QueueEmpty:
                return
            trace = obs_trace.detach(result)  # never serializes to disk
            if len(self.shards) > 1:
                # stamp the owner shard before serializing (the
                # _deliver path does this pre-upload; these envelopes
                # never got there) so the replay routes correctly
                owner = None
                if trace is not None:
                    owner = trace.meta.get(HIVE_SHARD_KEY)
                if owner is None:
                    owner = self._inflight_shard.get(result.get("id"))
                if owner is not None:
                    result.setdefault(HIVE_SHARD_KEY, int(owner))
            spooled = result.pop("_dead_letter_path", None)
            if spooled is None:  # replayed results already have a file
                self._result_shard(result).spool.spool(result)
                self.stats.results_dead_lettered += 1
            # same settling as _deliver's cancelled-upload path: a job
            # dead-lettered by shutdown still counts in jobs_total and
            # leaves its trace in the ring
            self._settle_inflight(result)
            self._finish_trace(trace, result, settled="dead_letter")
            self.result_queue.task_done()

    # ---- health endpoint (observability gap fix, SURVEY.md §5: the
    # reference's only health signal is the hive's timeout detection) ----

    def health(self) -> dict[str, Any]:
        from chiaswarm_tpu import WORKER_VERSION

        data = {
            "status": "ok",
            "worker_version": WORKER_VERSION,
            "worker_name": self.settings.worker_name,
            "backend": jax.default_backend(),
            "devices": len(jax.devices()),
            "slots": len(self.pool),
            "jobs_done": self.jobs_done,
            "queue_depth": self.work_queue.qsize(),
            "results_pending": self.result_queue.qsize(),
            # degradation-ladder observability (node/resilience.py)
            "breakers": self.breakers.states(),
            "dead_letter_depth": sum(shard.spool.depth()
                                     for shard in self.shards),
            "poll_consecutive_errors": max(shard.backoff.failures
                                           for shard in self.shards),
            # fleet durability (ISSUE 6): resume-state spool + lease view
            "checkpoint_depth": self.checkpoints.depth(),
            "checkpoints_written": self.checkpoints.written,
            "checkpoints_corrupt_skipped": self.checkpoints.corrupt_skipped,
            "inflight_jobs": len(self._inflight),
            # hive-outage ride-through (ISSUE 14): the session state
            # machine + the last hive epoch seen — the edge-side view
            # of a hive incident and its journal recovery
            "hive_session": self.hive_session.snapshot(),
            "hive_epoch": self._last_hive_epoch,
            # swarmfed (ISSUE 17): the multiplexed view — one session/
            # epoch/spool entry per hive shard (a single-hive worker
            # shows its one shard; the keys above stay its aliases)
            "hive_shards": [
                {"shard": shard.index,
                 "uri": shard.uri,
                 "session": shard.session.snapshot(),
                 "hive_epoch": shard.last_epoch,
                 "dead_letter_depth": shard.spool.depth(),
                 "poll_consecutive_errors": shard.backoff.failures}
                for shard in self.shards
            ],
        }
        data.update(self.stats.snapshot())
        data["stepper"] = self._stepper_health()
        # gray-failure guard (ISSUE 10): device health, sickness
        # streaks, rung thresholds, quarantined devices — plus the
        # in-service chip count so a quarantine's capacity shrink is
        # visible next to the static device total
        data["guard"] = self.guard.snapshot()
        # swarmlens (ISSUE 11): the MEASURED hang-budget suggestion
        # derived from this process's chiaswarm_stepper_step_seconds
        # histogram — closes the "watchdog knobs are priors, not
        # measurements" carry-over: a real deployment reads its
        # suggested factor/floor/ceiling here
        data["guard"]["suggested_hang_budget"] = suggest_hang_budget()
        data["chips_in_service"] = sum(
            len(_slot_devices(slot)) or 1 for slot in self.pool)
        # overload control (ISSUE 9): admission-estimator state next to
        # the resilience stats — shed totals, brownout rung, EWMAs
        data["overload"] = dict(
            self.overload.snapshot(),
            enabled=bool(self.settings.overload_control))
        # HBM residency (ISSUE 8): the measured ledger + the one
        # authoritative per-model state enum (quarantine merged in)
        residency = getattr(self.registry, "residency", None)
        if residency is not None:
            data["residency"] = residency.snapshot()
        model_states = getattr(self.registry, "model_states", None)
        if callable(model_states):
            data["models"] = model_states()
        return data

    def _fleet_metrics(self) -> dict[str, Any]:
        """Compact per-worker snapshot the heartbeat pushes to the hive's
        fleet plane (ISSUE 13; served aggregated at ``GET /api/fleet``):
        demand (arrival EWMA), supply (lane occupancy, chips in
        service), state (overload, residency ledger) — the observed
        inputs the ROADMAP item-5 autoscaler closes its loop on. Cheap
        host dicts only; any failure degrades to a partial snapshot."""
        data: dict[str, Any] = {
            "queue_depth": self.work_queue.qsize(),
            "inflight_jobs": len(self._inflight),
            "jobs_done": self.jobs_done,
            "jobs_shed": self.stats.jobs_shed,
            "jobs_failed": self.stats.jobs_failed,
            "chips_in_service": sum(
                len(_slot_devices(slot)) or 1 for slot in self.pool),
        }
        try:
            stepper = self._stepper_health()
            data.update(
                arrival_rate_rows_s=float(
                    stepper.get("arrival_rate") or 0.0),
                lane_occupancy=float(
                    stepper.get("lane_occupancy") or 0.0),
                padding_waste=float(
                    stepper.get("padding_waste") or 0.0),
                lanes_live=int(stepper.get("lanes_live") or 0),
                step_seconds_ewma=float(
                    stepper.get("step_seconds_ewma") or 0.0))
        except Exception:  # lanes absent/stubbed: demand half missing
            pass
        try:
            data["overload"] = self.overload.fleet_view()
        except Exception:
            pass
        residency = getattr(self.registry, "residency", None)
        if residency is not None:
            try:
                snap = residency.snapshot()
                data["residency"] = {
                    "resident_models": len(
                        snap.get("resident_models") or ()),
                    "resident_bytes": snap.get("resident_bytes", 0),
                    "budget_bytes": snap.get("budget_bytes", 0),
                    "evictions": snap.get("evictions", 0),
                }
            except Exception:  # stub registries
                pass
        return data

    def _stepper_health(self) -> dict[str, Any]:
        """Step-scheduler counters next to the resilience stats: lane
        occupancy vs padding waste, rows spliced mid-flight, steps
        executed — the signals an operator tunes lane width by."""
        from chiaswarm_tpu.serving.stepper import (
            aggregate_stats,
            stepper_enabled,
        )

        steppers = [st for st in
                    (getattr(slot, "_stepper", None) for slot in self.pool)
                    if st is not None]
        data = {"enabled": stepper_enabled()}
        data.update(aggregate_stats(steppers))
        return data

    def _collect_metrics(self) -> None:
        """Scrape-time mirror of worker state the registry does not see
        increment-by-increment: queue depths, breaker states, and the
        stepper's lane stats (their sources keep their own monotonic
        totals; Prometheus collect-on-scrape copies them in)."""
        m = self.metrics
        m.gauge("chiaswarm_work_queue_depth",
                "jobs queued and not yet claimed by a slot").set(
            self.work_queue.qsize())
        m.gauge("chiaswarm_results_pending",
                "finished results waiting for upload").set(
            self.result_queue.qsize())
        m.counter("chiaswarm_jobs_done_total",
                  "jobs that completed execution on this worker").set_to(
            self.jobs_done)
        m.gauge("chiaswarm_dead_letter_depth",
                "result envelopes spooled on disk (all shard spools)").set(
            sum(shard.spool.depth() for shard in self.shards))
        m.gauge("chiaswarm_poll_consecutive_errors",
                "current poll-loop error streak (drives the backoff; "
                "worst shard)").set(
            max(shard.backoff.failures for shard in self.shards))
        # fleet durability (ISSUE 6): checkpoint spool + lease signals
        m.gauge("chiaswarm_checkpoint_depth",
                "in-flight resume checkpoints on disk").set(
            self.checkpoints.depth())
        m.counter("chiaswarm_checkpoints_written_total",
                  "lane/phase resume checkpoints written").set_to(
            self.checkpoints.written)
        m.counter("chiaswarm_checkpoints_corrupt_total",
                  "corrupt checkpoint files skipped loudly").set_to(
            self.checkpoints.corrupt_skipped)
        m.gauge("chiaswarm_inflight_jobs",
                "jobs between poll receipt and settled upload (the "
                "lease-heartbeat set)").set(len(self._inflight))
        # hive-outage ride-through (ISSUE 14): the session state gauge
        # next to the outage/assumed-lost counters ResilienceStats
        # already renders. Federated (ISSUE 17): the overall gauge
        # means "ANY shard in outage" (shard-0-equivalent at H=1) and
        # the labeled family carries the per-shard truth.
        obs_metrics.hive_session_state_gauge(self.metrics).set(
            1 if any(shard.session.in_outage for shard in self.shards)
            else 0)
        shard_gauge = obs_metrics.hive_shard_session_state_gauge(
            self.metrics)
        for shard in self.shards:
            shard_gauge.set(1 if shard.session.in_outage else 0,
                            shard=str(shard.index))
        # swarmsight (ISSUE 13): trace-ring eviction becomes a counter
        # so a slow scraper SEES that it lost spans (pair with the
        # /debug/traces?since= cursor instead of scraping faster)
        obs_metrics.trace_spans_evicted_counter(m).set_to(
            self.traces.spans_evicted)
        state_code = {"closed": 0, "half_open": 1, "open": 2}
        breaker_state = m.gauge(
            "chiaswarm_breaker_state",
            "per-model circuit breaker (0=closed 1=half-open 2=open)",
            labelnames=("model",))
        breaker_failures = m.gauge(
            "chiaswarm_breaker_consecutive_failures",
            "per-model consecutive breaker-counted failures",
            labelnames=("model",))
        for model, snap in self.breakers.states().items():
            breaker_state.set(state_code.get(snap["state"], 2), model=model)
            breaker_failures.set(snap["consecutive_failures"], model=model)
        stepper = self._stepper_health()
        counters = ("steps_executed", "rows_admitted",
                    "rows_admitted_midflight", "rows_completed",
                    "rows_expired", "rows_failed", "lanes_created",
                    "lanes_failed", "row_steps_active", "row_steps_padded",
                    "rows_resumed", "resumes_rejected",
                    "checkpoints_written", "lanes_evict_retired",
                    # swarmguard (ISSUE 10): condemnations, hung rows,
                    # poisoned rows, slow steps
                    "lanes_condemned", "rows_hung", "rows_invalid",
                    "steps_slow")
        for key in counters:
            m.counter(f"chiaswarm_stepper_{key}_total",
                      f"step scheduler: cumulative {key}").set_to(
                stepper.get(key, 0))
        gauges = ("lanes_live", "rows_active", "lane_rows_total",
                  "lane_occupancy", "padding_waste")
        for key in gauges:
            m.gauge(f"chiaswarm_stepper_{key}",
                    f"step scheduler: current {key}").set(
                stepper.get(key, 0))
        m.gauge("chiaswarm_stepper_enabled",
                "1 when CHIASWARM_STEPPER lane routing is on").set(
            1 if stepper.get("enabled") else 0)

    async def _start_health_server(self):
        port = int(self.settings.health_port or 0)
        if port <= 0 and not self.settings.health_bind_ephemeral:
            return None
        from aiohttp import web

        async def healthz(_request):
            return web.json_response(self.health())

        async def metrics_endpoint(_request):
            # worker-scoped metrics + the process-global registry
            # (compile cache, lane step timing) in one scrape body
            body = obs_metrics.render_all([self.metrics,
                                           obs_metrics.REGISTRY])
            return web.Response(
                body=body.encode("utf-8"),
                headers={"Content-Type": obs_metrics.CONTENT_TYPE})

        async def traces_endpoint(request):
            # ?since=<seq> is the scrape cursor (ISSUE 13): only traces
            # pushed after that ring sequence return, and the cursor
            # block tells the scraper whether eviction opened a gap
            # since its last visit (oldest_seq > since + 1)
            since = None
            if request.query.get("since"):
                try:
                    since = int(request.query["since"])
                except ValueError:
                    return web.json_response(
                        {"status": "error",
                         "error": "since must be an integer ring "
                                  "sequence number"}, status=400)
            cursor = self.traces.cursor()
            if request.query.get("format") == "tree":
                return web.json_response(
                    {"traces": self.traces.to_dicts(since),
                     "cursor": cursor})
            # default: chrome-tracing "complete" events — load the body
            # as-is at https://ui.perfetto.dev (the extra cursor key is
            # ignored by the viewer)
            doc = self.traces.to_chrome(since)
            doc["cursor"] = cursor
            return web.json_response(doc)

        async def numerics_endpoint(request):
            # swarmlens flight recorder (ISSUE 11): the bounded ring of
            # per-probe summaries, filterable by probe prefix; the
            # payload documents enablement so "empty because off" and
            # "empty because nothing tapped" read differently
            from chiaswarm_tpu.obs import numerics as obs_numerics

            limit = None
            try:
                if request.query.get("limit"):
                    limit = int(request.query["limit"])
            except ValueError:
                return web.json_response(
                    {"status": "error",
                     "error": "limit must be an integer"}, status=400)
            return web.json_response(obs_numerics.debug_payload(
                probe_prefix=request.query.get("probe") or None,
                limit=limit))

        async def profile_endpoint(request):
            try:
                seconds = float(request.query.get("seconds", "5"))
            except ValueError:
                return web.json_response(
                    {"status": "error", "error": "seconds must be a "
                     "number"}, status=400)
            out = request.query.get("dir") or None
            # capture blocks for the duration; keep the event loop free
            result = await asyncio.get_running_loop().run_in_executor(
                None, functools.partial(obs_profiling.capture,
                                        seconds, out))
            status = {"ok": 200, "busy": 409}.get(result.get("status"), 500)
            return web.json_response(result, status=status)

        app = web.Application()
        app.router.add_get("/healthz", healthz)
        app.router.add_get("/metrics", metrics_endpoint)
        app.router.add_get("/debug/traces", traces_endpoint)
        app.router.add_get("/debug/profile", profile_endpoint)
        app.router.add_get("/debug/numerics", numerics_endpoint)
        runner = web.AppRunner(app)
        await runner.setup()
        # loopback by default: the endpoint is operator observability,
        # not a service for arbitrary swarm peers
        host = self.settings.health_host or "127.0.0.1"
        site = web.TCPSite(runner, host, max(port, 0))
        await site.start()
        bound_port = runner.addresses[0][1] if runner.addresses else port
        self.health_address = (host, bound_port)
        log.info("health endpoints on %s:%d (/healthz /metrics "
                 "/debug/traces /debug/profile /debug/numerics)",
                 host, bound_port)
        return runner

    # ---- tasks ----

    async def _poll_loop(self, shard: _HiveShard | None = None) -> None:
        shard = shard if shard is not None else self.shards[0]
        async with aiohttp.ClientSession() as session:
            while not self._stop.is_set():
                # natural backpressure: wait for queue space — but keep
                # watching _stop, so a full queue can never stall shutdown
                while self.work_queue.full() and not self._stop.is_set():
                    try:
                        await asyncio.wait_for(self._stop.wait(),
                                               timeout=1.0)
                    except asyncio.TimeoutError:
                        pass
                if self._stop.is_set():
                    return
                # predictive backpressure (ISSUE 9): the queue-full wait
                # above only engages once the worker has ALREADY
                # over-committed a full queue of jobs it may then shed;
                # the overload controller throttles intake earlier, the
                # moment the queued backlog's drain estimate outruns the
                # backpressure budget
                if self.settings.overload_control:
                    throttle = self.overload.poll_throttle(
                        self.work_queue.qsize(), len(self.pool))
                    if throttle > 0:
                        self.stats.polls_backpressured += 1
                        try:
                            await asyncio.wait_for(self._stop.wait(),
                                                   timeout=throttle)
                        except asyncio.TimeoutError:
                            pass
                        continue
                delay = await self._ask_for_work(session, shard)
                # self-healing ladder (ISSUE 10): apply any rungs the
                # device guard queued since the last poll — cache
                # flush, device quarantine (mesh shrink), restart
                self._apply_heal_rungs()
                try:
                    await asyncio.wait_for(self._stop.wait(), timeout=delay)
                except asyncio.TimeoutError:
                    pass

    async def _ask_for_work(self, session: aiohttp.ClientSession,
                            shard: _HiveShard | None = None) -> float:
        """One poll against one shard; returns the next delay. Errors
        back off exponentially with jitter (capped at hive.POLL_ERROR_S
        by default) and the schedule resets on the first successful
        poll. A federated shard's handout may include a STOLEN job —
        granted (and journaled) by a deeper-backlog peer; its payload
        carries that owner's shard index and epoch, so heartbeats and
        the upload route to the shard that actually holds the lease."""
        shard = shard if shard is not None else self.shards[0]
        t_poll = time.perf_counter()
        try:
            jobs = await shard.client.get_work(session)
        except BadWorkerError as exc:
            # the hive ANSWERED (flagged us): reachable, not an outage
            self._note_hive_ok(shard)
            log.error("hive flagged this worker: %s", exc)
            return shard.backoff.next()
        except Exception as exc:
            self._note_hive_failure("poll", exc, shard)
            log.warning("poll failed: %s", exc)
            return shard.backoff.next()
        self._note_hive_ok(shard)
        shard.backoff.reset()
        poll_http_s = time.perf_counter() - t_poll
        if jobs:
            # poll-loop / step-boundary merge (ISSUE 7c): tell each
            # slot's resident step scheduler how many rows this poll is
            # about to format and submit, so adaptive lanes can grow at
            # their NEXT boundary instead of queueing the burst behind a
            # full lane. A hint only — never creates a scheduler.
            rows_hint = sum(
                max(1, int(job.get("num_images_per_prompt") or 1))
                for job in jobs)
            for slot in self.pool:
                stepper = getattr(slot, "_stepper", None)
                if stepper is not None:
                    stepper.note_poll(rows_hint)
        # brownout rung (ISSUE 9): refresh every slot's per-boundary
        # lane-admission cap on EVERY poll — entering brownout caps
        # promptly under load, and a cleared brownout lifts the cap on
        # the next (possibly idle) poll instead of lingering
        self._push_admission_caps()
        for job in jobs:
            if job.get("id") in self._inflight:
                # a lease-aware hive's starvation valve can redeliver a
                # job BACK to the worker still running it (every other
                # worker excluded). Running a second local copy would
                # orphan the heartbeat coverage of whichever copy
                # outlives the first settle (single id-keyed _inflight
                # entry) and churn the lease forever — drop the
                # duplicate; heartbeats re-hold the new lease and the
                # first run's upload settles it
                log.warning("job %s redelivered here while still in "
                            "flight; dropping the duplicate copy",
                            job.get("id"))
                self._inflight[job.get("id")] = time.monotonic()
                continue
            log.info("got job %s", job.get("id"))
            # the job's trace is born at hive receipt; its "poll" phase
            # covers the queue wait until a slot picks the job up (the
            # HTTP fetch itself rides as metadata — it served the whole
            # poll, not this one job). Redelivered jobs carry their
            # lineage: delivery attempt + the checkpoint step they
            # resume from (lease-aware hives, node/minihive.py).
            # ``queued_s`` (the hive's queue-age stamp) and ``attempt``
            # ride as root-span attributes on EVERY trace, so
            # /debug/traces answers "how stale was this job" without
            # the overload estimator being the only reader (ISSUE 13).
            resume = job.get("resume")
            ctx = job.pop(obs_flight.TRACE_CTX_KEY, None)
            # swarmfed (ISSUE 17): a federated grant names its OWNING
            # shard (a stolen job arrives via this shard's poll but its
            # lease, journal entry, and epoch all live on the owner).
            # Popped like the epoch stamp — never reaches argument
            # formatting — and rides the trace to the upload router.
            owner_raw = job.pop(HIVE_SHARD_KEY, None)
            try:
                owner_index = (shard.index if owner_raw is None
                               else int(owner_raw))
            except (TypeError, ValueError):
                owner_index = shard.index
            owner = (self.shards[owner_index]
                     if 0 <= owner_index < len(self.shards) else shard)
            # swarmdurable (ISSUE 14): the journaled hive's epoch stamp
            # is popped like the trace context (never reaches argument
            # formatting) and rides the trace to the upload, where the
            # envelope echoes it — the recovered hive's dedupe key.
            # Tracked against the OWNER: the epoch is that shard's
            # journal generation, whoever's poll delivered the grant.
            epoch = self._note_hive_epoch(
                job.pop(HIVE_EPOCH_KEY, None), owner)
            try:
                queued_s = max(0.0, float(job.get("queued_s") or 0.0))
            except (TypeError, ValueError):
                queued_s = 0.0
            trace = obs_trace.JobTrace(
                "job", id=job.get("id"),
                model=str(job.get("model_name") or ""),
                workflow=str(job.get("workflow") or ""),
                worker=self.settings.worker_name,
                attempt=job.get("attempt") or 1,
                queued_s=round(queued_s, 4),
                resume_step=(resume.get("step", 0)
                             if isinstance(resume, dict) else 0))
            if epoch is not None:
                trace.meta[HIVE_EPOCH_KEY] = epoch
            if owner_raw is not None:
                # only federated grants carry a shard; the meta stamp
                # routes the upload envelope to the owner (parity: an
                # un-federated grant stamps nothing anywhere)
                trace.meta[HIVE_SHARD_KEY] = owner.index
            if isinstance(ctx, dict) and ctx.get("trace_id"):
                # JOIN the hive's trace context (swarmsight, ISSUE 13):
                # this trace becomes the hive-granted attempt span's
                # child and the upload will carry a span digest for the
                # hive's flight record. With no context (reference
                # hive) the trace originates locally and the upload
                # payload keeps its historical shape — parity.
                trace.meta["trace_id"] = str(ctx.get("trace_id"))
                trace.meta["span_id"] = str(ctx.get("span_id") or "")
            trace.phase("poll", http_s=round(poll_http_s, 6))
            obs_trace.attach(job, trace)
            self._inflight[job.get("id")] = time.monotonic()
            self._inflight_shard[job.get("id")] = owner.index
            await self.work_queue.put(job)
        if jobs:
            return float(self.settings.poll_busy_s)
        # demand-driven prefetch (ISSUE 8): an empty poll is the ONLY
        # moment background warm loads may run — the ledger picks the
        # hottest evicted model (arrival EWMA) that fits the free budget
        # and loads it on a daemon thread; busy polls never trigger it
        if not self._stop.is_set() and self.work_queue.empty():
            residency = getattr(self.registry, "residency", None)
            if residency is not None:
                try:
                    residency.note_idle()
                except Exception as exc:  # prefetch must never stop polls
                    log.debug("residency prefetch tick failed: %s", exc)
        return float(self.settings.poll_idle_s)

    def _push_admission_caps(self) -> None:
        """Mirror the overload controller's brownout admission cap into
        every slot's resident step scheduler (None clears it)."""
        cap = (self.overload.admission_cap()
               if self.settings.overload_control else None)
        for slot in self.pool:
            stepper = getattr(slot, "_stepper", None)
            if stepper is not None:
                stepper.set_admission_cap(cap)

    # ---- the self-healing ladder (serving/guard.py, ISSUE 10) ----

    def _apply_heal_rungs(self) -> None:
        """Drain the device guard's queued ladder actions. The first
        rung (lane rebuild) is intrinsic to condemnation and already
        happened lane-side; this applies the worker-level escalations:

        - **cache_flush**: drop every cached executable — a sick
          device sometimes serves a corrupted compiled program; the
          next call recompiles fresh (``LruCache.drop_where``).
        - **device_quarantine**: shrink every slot's mesh to the
          healthy chips (data-axis meshes only — model-parallel slots
          cannot lose a chip and stay well-formed, so they escalate to
          restart instead). Capacity re-advertises through /healthz
          (``chips_in_service``) and the lane width bounds, which read
          the live ``slot.data_width``.
        - **restart**: request the graceful PR-2 drain and leave
          :data:`GUARD_RESTART_EXIT_CODE` for the supervisor — the
          "heal me by replacing me" rung of last resort.
        """
        if not self.settings.guard_enabled:
            return
        for action in self.guard.take_actions():
            if action.rung == "cache_flush":
                from chiaswarm_tpu.core.compile_cache import GLOBAL_CACHE
                from chiaswarm_tpu.serving.guard import note_cache_flush

                dropped = GLOBAL_CACHE.flush_executables()
                # re-cold every lane's hang budget: the recompiles this
                # flush causes must run under the ceiling, or the rung
                # would manufacture its own "hangs"
                note_cache_flush()
                log.error("guard heal: flushed %d cached executable(s) "
                          "(%s)", dropped, action.reason)
            elif action.rung == "device_quarantine":
                self._quarantine_device(action.device, action.reason)
            elif action.rung == "restart":
                log.error("guard heal: self-restart requested (%s); "
                          "draining gracefully, exit code %d",
                          action.reason, GUARD_RESTART_EXIT_CODE)
                self.exit_code = GUARD_RESTART_EXIT_CODE
                self.request_stop()

    def _quarantine_device(self, device: str, reason: str) -> None:
        """Shrink every slot mesh that contains ``device`` to its
        healthy chips. Lanes on the slot retire first (their rows
        bounce through the zero-loss fallback paths); fresh programs
        then build on the shrunk mesh. A slot that cannot shrink (its
        only chip, or a model-parallel mesh) logs and leaves the
        ladder to escalate."""
        from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh

        for slot in self.pool:
            mesh = getattr(slot, "mesh", None)
            if mesh is None:
                continue
            devices = list(mesh.devices.flatten())
            healthy = [d for d in devices if str(d.id) != str(device)]
            if len(healthy) == len(devices):
                continue  # this slot never held the sick chip
            shape = dict(zip(mesh.axis_names, mesh.devices.shape))
            non_data = 1
            for name, size in shape.items():
                if name != "data":
                    non_data *= int(size)
            if not healthy or non_data != 1:
                log.error("guard heal: cannot quarantine device %s out "
                          "of slot %s (mesh %s); the ladder escalates "
                          "to restart instead", device,
                          getattr(slot, "index", "?"), shape)
                continue
            stepper = getattr(slot, "_stepper", None)
            if stepper is not None:
                # retire resident lanes: their device state is the last
                # holder of programs placed on the sick chip; unfinished
                # rows fail over to the per-job path (never lost)
                stepper.shutdown(timeout_s=5.0)
            slot.mesh = build_mesh(MeshSpec({"data": len(healthy)}),
                                   devices=healthy)
            log.error("guard heal: device %s quarantined (%s); slot %s "
                      "mesh shrunk to %d healthy chip(s) — capacity "
                      "re-advertised", device, reason,
                      getattr(slot, "index", "?"), len(healthy))

    async def _heartbeat_loop(self) -> None:
        """Lease keep-alive (ISSUE 6): every ``heartbeat_s``, tell the
        hive which jobs are in flight here and push their latest resume
        checkpoints (node/resilience.py spool; lanes write it at step
        boundaries). A hive that reassigned one of our leases answers
        with the lost ids — the local run keeps going (its result is
        deduped hive-side; first upload wins either way), but the loss
        is counted and logged so operators see lease churn."""
        interval = float(self.settings.heartbeat_s)
        # fleet-plane cadence (ISSUE 13): metric snapshots refresh at
        # most every ~2s — lease keep-alives can beat at 20 Hz in tests,
        # and re-serializing occupancy/residency state on every beat
        # would tax exactly the busy loops the plane observes. An
        # autoscaler reads seconds-scale state; 0 forces the next beat.
        # The throttle clock lives per shard (shard.last_metrics): each
        # shard serves its own /api/fleet slice of this worker.
        metrics_every = max(interval, 2.0)
        pushed: dict[Any, int] = {}  # job id -> spool version last pushed
        # leases the hive already told us it reassigned: count + warn
        # ONCE per loss, not once per beat for as long as the local run
        # keeps going (a 60s job at heartbeat_s=0.1 would otherwise
        # inflate leases_lost ~600x for a single reassignment)
        lost_reported: set[str] = set()

        def build_jobs(ids: list) -> list[dict]:
            # runs in a thread: checkpoint files are latent-sized, and a
            # synchronous read+parse per job per beat would stall the
            # event loop (polls, uploads, the health server). A None
            # checkpoint means "unchanged since my last beat" — the hive
            # keeps its stored copy, so skipping the re-push is free.
            jobs = []
            for job_id in ids:
                version = self.checkpoints.version(job_id)
                if (version is None or pushed.get(job_id) == version
                        or str(job_id) in lost_reported):
                    # a lost lease's checkpoint custody moved with the
                    # lease — the hive would reject the push as stale
                    jobs.append({"id": job_id, "checkpoint": None})
                    continue
                checkpoint = self.checkpoints.load(job_id)
                if checkpoint is not None:
                    pushed[job_id] = version
                jobs.append({"id": job_id, "checkpoint": checkpoint})
            return jobs

        async def idle_beat(shard: _HiveShard) -> None:
            # fleet plane (ISSUE 13): a worker with nothing in flight
            # ON THIS SHARD still pushes metrics-only beats (no jobs,
            # no lease bookkeeping) so its /api/fleet reads fresh
            # occupancy and capacity — an autoscaler must see idle
            # workers, not just busy ones — at the throttled metrics
            # cadence, not the lease cadence
            if time.monotonic() - shard.last_metrics < metrics_every:
                return
            idle_payload = {
                "worker_name": self.settings.worker_name,
                "jobs": [],
                "metrics": self._fleet_metrics(),
            }
            if shard.last_epoch is not None:
                idle_payload[HIVE_EPOCH_KEY] = shard.last_epoch
            try:
                ack = await shard.client.post_heartbeat(
                    session, idle_payload)
                self._note_hive_ok(shard)
                if isinstance(ack, dict):
                    self._note_hive_epoch(ack.get(HIVE_EPOCH_KEY), shard)
                    self._note_placement(ack.get("placement"))
                shard.last_metrics = time.monotonic()
            except Exception as exc:
                self._note_hive_failure("heartbeat", exc, shard)
                log.debug("idle heartbeat failed: %s", exc)

        async with aiohttp.ClientSession() as session:
            while True:
                await asyncio.sleep(interval)
                if self._stop.is_set() and not self._inflight:
                    return
                if not self._inflight:
                    pushed.clear()
                    lost_reported.clear()
                    for shard in self.shards:
                        await idle_beat(shard)
                    continue
                inflight = list(self._inflight)
                for job_id in [j for j in pushed if j not in self._inflight]:
                    pushed.pop(job_id, None)
                lost_reported &= {str(j) for j in inflight}
                # swarmfed (ISSUE 17): one beat per shard, each naming
                # only the jobs whose lease that shard OWNS (a stolen
                # job heartbeats to its owner, not the shard whose poll
                # delivered it) under that shard's own epoch handshake.
                # A dead shard fails only its own beat — the rest keep
                # their leases alive (per-shard outage independence).
                by_owner: dict[int, list] = {}
                for job_id in inflight:
                    by_owner.setdefault(
                        self._inflight_shard.get(job_id, 0),
                        []).append(job_id)
                reported: set[str] = set()
                any_beat_ok = False
                for shard in self.shards:
                    owned = by_owner.get(shard.index)
                    if not owned:
                        # nothing leased here: keep the shard's fleet
                        # plane fresh at the metrics cadence
                        await idle_beat(shard)
                        continue
                    payload = {
                        "worker_name": self.settings.worker_name,
                        "jobs": await asyncio.to_thread(
                            build_jobs, owned),
                    }
                    if shard.last_epoch is not None:
                        # the epoch handshake (ISSUE 14): a recovered
                        # hive rejects beats claiming a pre-restart
                        # epoch — the ack below hands back the current
                        # one, so the NEXT beat re-registers under it
                        payload[HIVE_EPOCH_KEY] = shard.last_epoch
                    if time.monotonic() - shard.last_metrics \
                            >= metrics_every:
                        # fleet plane (ISSUE 13): busy beats carry the
                        # metric snapshot at the same throttled
                        # cadence; the hive keeps the latest per worker
                        # at /api/fleet. Reference hives (no heartbeat
                        # endpoint) never see it — heartbeats are
                        # already off there.
                        payload["metrics"] = self._fleet_metrics()
                        shard.last_metrics = time.monotonic()
                    try:
                        response = await shard.client.post_heartbeat(
                            session, payload)
                        self._note_hive_ok(shard)
                        # a malformed 2xx body (non-dict JSON, non-list
                        # "lost") counts as a failed beat, NOT a loop
                        # exit: one bad proxy answer must never kill
                        # the keep-alive for the rest of the process
                        # lifetime
                        lost_raw = response.get("lost") or []
                        if not isinstance(lost_raw, list):
                            raise TypeError(
                                "non-list 'lost' in heartbeat "
                                f"response: {lost_raw!r}")
                        reported |= {str(j) for j in lost_raw}
                        self._note_hive_epoch(
                            response.get(HIVE_EPOCH_KEY), shard)
                        self._note_placement(response.get("placement"))
                        any_beat_ok = True
                    except Exception as exc:
                        # reference hives have no heartbeat endpoint,
                        # and a partitioned hive is exactly when we
                        # keep beating
                        self._note_hive_failure("heartbeat", exc, shard)
                        log.debug("heartbeat failed: %s", exc)
                if not any_beat_ok:
                    continue
                self.stats.lease_heartbeats += 1
                reported &= {str(j) for j in inflight}
                lost = sorted(reported - lost_reported)
                # REPLACE, don't accumulate: a job the hive stops
                # reporting lost was re-leased to us (starvation-valve
                # redelivery back to this worker) — checkpoint custody
                # returns, pushes resume, and a future loss warns anew
                lost_reported = reported
                if lost:
                    self.stats.leases_lost += len(lost)
                    log.warning("hive reassigned lease(s) for %s; local "
                                "work continues, upload will dedupe",
                                lost)

    async def _next_job(self) -> dict | None:
        """Block for the next queued job; returns None once the worker is
        draining AND the queue is empty (graceful-shutdown exit)."""
        if self._draining.is_set() and self.work_queue.empty():
            return None
        get_task = asyncio.ensure_future(self.work_queue.get())
        drain_task = asyncio.ensure_future(self._draining.wait())
        try:
            await asyncio.wait({get_task, drain_task},
                               return_when=asyncio.FIRST_COMPLETED)
            while not get_task.done():
                # draining with jobs still queued: claim them — but a
                # sibling slot may win the race for the last one, after
                # which this get can never be satisfied again (polling
                # already stopped), so re-check emptiness instead of
                # blocking the whole drain on it
                if self.work_queue.empty():
                    return None
                await asyncio.wait({get_task}, timeout=0.05)
            return get_task.result()
        finally:
            # no awaits between the queue checks above and these cancels,
            # and asyncio.Queue re-wakes the next getter when a woken one
            # is cancelled — a queued job can never be lost here
            get_task.cancel()
            drain_task.cancel()
            await asyncio.gather(get_task, drain_task,
                                 return_exceptions=True)

    async def _slot_worker(self, slot) -> None:
        """Feed one slot, keeping up to ``slot.depth`` jobs in flight.

        With depth 2, job N+1's host prep + program dispatch overlap job
        N's device->host image transfer (chip never idles between jobs);
        the slot's bounded semaphore enforces the cap, this semaphore
        just avoids pulling queue items nothing can run yet."""
        inflight = asyncio.Semaphore(max(1, getattr(slot, "depth", 1)))
        pending: set[asyncio.Task] = set()
        # cross-job coalescing: a dp-sharded slot runs up to dp compatible
        # jobs as ONE batched program (executor groups them; incompatible
        # jobs in a burst just run serially). 512px-class jobs
        # additionally batch up to single_chip_rows() per device — one
        # chip is NOT saturated by them at batch 1 (+20% measured,
        # BASELINE.md r4); 1024px-class stays at one row per device
        # (saturated, r1). On multi-slot pools the drain loop below
        # additionally leaves ``_hungry_slots`` jobs in the queue, so a
        # coalescing slot never strips work an idle neighbor is already
        # waiting for.
        base_merge = slot.data_width

        async def run_burst(burst: list[dict]) -> None:
            try:
                results = await self._execute_burst(burst, slot)
                for result in results:
                    await self.result_queue.put(result)
                    self.jobs_done += 1
            except Exception as exc:
                # fault containment: a crash in the execution path must
                # never silently eat the burst (the reference's behavior —
                # the hive would wait out its deadline then flag the whole
                # worker); every job reports an explicit error envelope
                log.exception("slot worker error: %s", exc)
                kind = classify_exception(exc)
                outcomes: dict[str, set[str]] = {}
                for job in burst:
                    self.stats.jobs_failed += 1
                    outcomes.setdefault(
                        str(job.get("model_name") or ""), set()).add(kind)
                    envelope = error_result(job, exc, kind=kind)
                    trace = obs_trace.detach(job)
                    if trace is not None:  # ride on to the upload phase
                        obs_trace.attach(envelope, trace)
                    await self.result_queue.put(envelope)
                    self.jobs_done += 1
                self._record_outcomes(outcomes)
            finally:
                inflight.release()
                for _ in burst:
                    self.work_queue.task_done()

        held: dict | None = None  # mismatched drain candidate, runs next
        try:
            while True:
                await inflight.acquire()
                if held is not None:
                    burst, held = [held], None
                else:
                    # a slot that ALREADY has work in flight must not
                    # synchronously grab a job a hungry neighbor is
                    # blocked on (acquire+get both return without
                    # yielding when satisfiable, so at depth>=2 this
                    # slot would steal the fairness reserve before the
                    # woken neighbor's coroutine ever runs). Yield until
                    # the reserved jobs are consumed or surplus arrives.
                    while (pending and self._hungry_slots
                           and 0 < self.work_queue.qsize()
                           <= self._hungry_slots):
                        await asyncio.sleep(0)
                    self._hungry_slots += 1
                    try:
                        job = await self._next_job()
                    finally:
                        self._hungry_slots -= 1
                    if job is None:  # draining and the queue is dry
                        inflight.release()
                        break
                    burst = [job]
                key = _burst_key(burst[0])
                rows = rows_max = job_rows(burst[0])
                per_device = single_chip_rows(burst[0])
                max_merge = base_merge * per_device
                while key is not None and len(burst) < max_merge:
                    # fairness reserve: jobs other slots are blocked on
                    # stay in the queue (the drain below has no awaits,
                    # so this count cannot change mid-drain)
                    if self.work_queue.qsize() <= self._hungry_slots:
                        break
                    try:
                        candidate = self.work_queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    cand_rows = job_rows(candidate)
                    # num_images_per_prompt multiplies batch rows; never
                    # drain a burst whose total rows exceed what the
                    # heaviest member's solo run would put per device
                    # (the executor's _row_chunks is the authority, this
                    # avoids claiming jobs it would split anyway)
                    fits = rows + cand_rows <= rows_cap(
                        max(rows_max, cand_rows), base_merge, per_device)
                    if _burst_key(candidate) == key and fits:
                        burst.append(candidate)
                        rows += cand_rows
                        rows_max = max(rows_max, cand_rows)
                    else:
                        # hold the mismatch and run it as the NEXT burst:
                        # re-queueing at the tail would let it repeatedly
                        # lose its FIFO position to later-arriving
                        # coalescable jobs (unbounded reordering)
                        held = candidate
                        break
                task = asyncio.create_task(run_burst(burst))
                pending.add(task)
                task.add_done_callback(pending.discard)
            # graceful drain: in-flight bursts COMPLETE (and their results
            # reach the result queue) before this slot's task returns
            if pending:
                await asyncio.gather(*list(pending), return_exceptions=True)
        finally:
            # a held job was claimed from the queue but never dispatched;
            # put it back so cancellation cannot silently drop it (and
            # work_queue.join() accounting stays balanced)
            if held is not None:
                try:
                    self.work_queue.put_nowait(held)
                except asyncio.QueueFull:
                    log.error("dropping held job %s at shutdown: queue "
                              "full (hive recovers it via timeout)",
                              held.get("id"))
                self.work_queue.task_done()
            # forced-cancel path: cancel in-flight jobs, then AWAIT them
            # so their finally blocks (queue bookkeeping) run and no
            # pending task outlives the event loop
            for task in list(pending):
                task.cancel()
            if pending:
                await asyncio.gather(*list(pending), return_exceptions=True)

    # ---- execution with deadlines + the degradation ladder ----

    async def _attempt(self, jobs: list[dict], slot) -> list[dict]:
        """One executor call under the per-workflow deadline. A timed-out
        attempt yields explicit timeout envelopes — the hive hears about
        it NOW, not when its own worker-level detector fires. (The
        abandoned executor thread finishes in the background and its
        result is discarded; run_in_executor work is not interruptible.)
        """
        budget = max(self.settings.deadline_for(job.get("workflow"))
                     for job in jobs)
        for job in jobs:
            trace = obs_trace.job_trace(job)
            if trace is not None:
                # every member's "execute" phase spans this WHOLE
                # attempt (the burst runs as one call), so the service
                # EWMA must divide by the attempt size or a coalesced
                # burst teaches it N x the true per-job cost — and the
                # shed gate then sheds comfortably-servable jobs
                # (caught by review). Solo retries overwrite this to 1.
                trace.meta["attempt_jobs"] = len(jobs)
        executor = self._executor
        if len(jobs) == 1:
            dw = executor.do_work if executor is not None else do_work
            call = dw(jobs[0], slot, self.registry)
        else:
            dwb = (executor.do_work_batch if executor is not None
                   else do_work_batch)
            call = dwb(jobs, slot, self.registry)
        try:
            out = await asyncio.wait_for(call, timeout=budget)
        except asyncio.TimeoutError:
            self.stats.jobs_timed_out += len(jobs)
            # the estimator must learn the slowness a timeout proves:
            # the job burned at least the whole budget
            self.overload.note_service(jobs[0].get("workflow"), budget)
            log.error("burst %s exceeded its %.0fs deadline",
                      [job.get("id") for job in jobs], budget)
            return [error_result(
                job, f"job exceeded the node's {budget:.0f}s execution "
                     f"deadline", kind="timeout") for job in jobs]
        except Exception as exc:
            # the real executor renders its own failures as envelopes, so
            # anything raising THROUGH it is a genuine crash — contain it
            # at the job level with explicit envelopes (the reference
            # silently eats such jobs; the hive then times out the whole
            # worker, swarm/worker.py:92-97)
            log.exception("executor crashed on burst %s",
                          [job.get("id") for job in jobs])
            kind = classify_exception(exc)
            return [error_result(job, exc, kind=kind) for job in jobs]
        results = [out] if len(jobs) == 1 else list(out)
        # never let a miscounting executor silently drop a job
        while len(results) < len(jobs):
            results.append(error_result(
                jobs[len(results)], "executor returned no result for this "
                "job", kind="error"))
        return results

    async def _execute_burst(self, burst: list[dict], slot) -> list[dict]:
        """Run a burst through the degradation ladder:

        1. circuit-breaker gate — jobs for quarantined models get an
           immediate (non-fatal) refusal envelope, no chip time burned;
        2. one batched attempt under the deadline;
        3. jobs that failed transiently (image-fetch blip, device OOM)
           re-run SOLO with capped backoff + jitter — an OOM'd coalesced
           burst thereby splits and re-runs serially;
        4. final outcomes feed the per-model breakers.
        """
        results: list[dict | None] = [None] * len(burst)
        for job in burst:
            trace = obs_trace.job_trace(job)
            if trace is not None:  # poll phase ends, execute begins
                trace.phase("execute")
        ready: list[int] = []
        for i, job in enumerate(burst):
            model = str(job.get("model_name") or "")
            if model and not self.breakers.allow(model):
                self.stats.jobs_failed += 1
                self.stats.jobs_quarantined += 1
                # NOT fatal: this node refuses, another node may serve it
                results[i] = error_result(
                    job, f"model {model!r} is quarantined on this node "
                         f"(circuit breaker open)", kind="quarantined")
            else:
                ready.append(i)
        # deadline-aware admission (ISSUE 9): shed jobs the estimator
        # predicts would miss their deadline behind the local backlog —
        # BEFORE any chip time is spent. Sheds upload as non-fatal
        # "overloaded" envelopes (REDISPATCH_KINDS) and count as
        # capacity decisions, never failures.
        if ready and self.settings.overload_control:
            ready = self._shed_gate(burst, results, ready)
        if ready:
            attempt = await self._attempt([burst[i] for i in ready], slot)
            for i, result in zip(ready, attempt):
                results[i] = result
        max_retries = max(0, int(self.settings.transient_retries))
        outcomes: dict[str, set[str]] = {}
        for i in ready:
            kind = classify_result(results[i])
            for retry in range(1, max_retries + 1):
                if kind not in RETRYABLE_KINDS:
                    break
                delay = backoff_delay(retry, self.settings.retry_backoff_s,
                                      self.settings.retry_backoff_cap_s,
                                      self._retry_rng)
                log.warning("job %s hit a %s fault; solo re-run %d/%d "
                            "in %.2fs", burst[i].get("id"), kind, retry,
                            max_retries, delay)
                self.stats.jobs_retried += 1
                await asyncio.sleep(delay)
                results[i] = (await self._attempt([burst[i]], slot))[0]
                kind = classify_result(results[i])
            if kind != "ok":
                self.stats.jobs_failed += 1
            outcomes.setdefault(
                str(burst[i].get("model_name") or ""), set()).add(kind)
        self._record_outcomes(outcomes)
        # the trace hops from the consumed job dict onto its result
        # envelope so the upload phase (and finish) can find it
        for i, job in enumerate(burst):
            trace = obs_trace.detach(job)
            if trace is not None and results[i] is not None:
                obs_trace.attach(results[i], trace)
        return [result for result in results if result is not None]

    def _job_deadline_s(self, job: dict) -> float:
        """A job's end-to-end deadline budget: its own ``deadline_s``
        field (the swarmload harness attaches one per workload profile;
        the reference hive sends none), else the operator's per-model-
        FAMILY override (ISSUE 10 satellite — heavy families need more
        budget than their workflow's default; the harness derives
        suggested values from measured percentiles,
        node/loadgen.py::score_run), else the per-workflow setting."""
        raw = job.get("deadline_s")
        if raw is not None:
            try:
                value = float(raw)
                if value > 0:
                    return value
            except (TypeError, ValueError):
                pass
        table = self.settings.family_deadline_s or {}
        if table:
            family = self._model_family(job.get("model_name"))
            if family is not None and family in table:
                try:
                    value = float(table[family])
                    if value > 0:
                        return value
                except (TypeError, ValueError):
                    pass
        return self.settings.deadline_for(job.get("workflow"))

    @staticmethod
    def _model_family(model_name: Any) -> str | None:
        """Catalog family of a model name (None when unresolvable) —
        the key of the ``family_deadline_s`` override table."""
        if not model_name:
            return None
        try:
            from chiaswarm_tpu.models.configs import get_family

            return str(get_family(str(model_name)).name)
        except Exception:
            return None

    def _shed_gate(self, burst: list[dict], results: list,
                   ready: list[int]) -> list[int]:
        """Per-job admission verdicts for a burst about to execute;
        returns the indices that survive. Shed envelopes settle through
        the normal result path (exactly-once accounting unchanged)."""
        now = time.monotonic()
        stepper = self._stepper_health()
        step_ewma = float(stepper.get("step_seconds_ewma") or 0.0)
        queued = self.work_queue.qsize()
        slots = len(self.pool)
        admitted: list[int] = []
        for i in ready:
            job = burst[i]
            received = self._inflight.get(job.get("id"))
            # the job's age is hive queue time (the "queued_s" stamp a
            # lease-aware hive sends with each delivery — under
            # overload the backlog lives there) plus local queue wait
            try:
                queued_s = max(0.0, float(job.get("queued_s") or 0.0))
            except (TypeError, ValueError):
                queued_s = 0.0
            lane_estimate = None
            if stepper.get("enabled") and step_ewma > 0.0:
                try:
                    steps = int(job.get("num_inference_steps") or 0)
                except (TypeError, ValueError):
                    steps = 0
                if steps > 0:
                    lane_estimate = steps * step_ewma
            decision = self.overload.should_shed(
                workflow=job.get("workflow"),
                waited_s=queued_s + (0.0 if received is None
                                     else max(0.0, now - received)),
                deadline_s=self._job_deadline_s(job),
                # burst peers admitted ahead of this job are backlog
                # too — they left the work queue together, so qsize
                # alone undercounts exactly the jobs that will run
                # first (the 30-50 ms misses the harness caught)
                queued_ahead=queued + len(admitted), slots=slots,
                lane_estimate_s=lane_estimate)
            if not decision.shed:
                admitted.append(i)
                continue
            self.stats.jobs_shed += 1
            log.warning("job %s shed at admission: %s", job.get("id"),
                        decision.reason)
            results[i] = error_result(
                job, f"shed by overload control on this node "
                     f"({decision.reason}); a less-loaded node may "
                     f"still serve it", kind="overloaded")
        if len(admitted) < len(ready):
            # sheds may have tripped (or extended) brownout: cap lanes
            self._push_admission_caps()
        return admitted

    def _record_outcomes(self, outcomes: dict[str, set[str]]) -> None:
        """Feed the per-model circuit breakers, ONE record per model per
        burst: a single burst-level incident (e.g. a deadline expiry on
        an N-job coalesced burst) must count as one "consecutive"
        failure, not N — or one cold compile could quarantine a healthy
        model. Which kinds count is resilience.BREAKER_KINDS policy:
        model-load failures, timeouts, OOM that survived the ladder, and
        unclassified execution errors — NOT fatal user-input errors (K
        bad requests in a row must not quarantine a healthy model) and
        NOT transient network faults. A success for the model anywhere in
        the burst proves it serves and wins over same-burst failures."""
        for model, kinds in outcomes.items():
            if not model:
                continue
            if "ok" in kinds:
                self.breakers.record(model, ok=True)
            elif kinds & BREAKER_KINDS:
                self.breakers.record(model, ok=False)
            else:
                # says nothing about the model — but if this burst held
                # the half-open probe, free the slot for the next one
                self.breakers.record_inconclusive(model)

    # ---- result upload with durability ----

    async def _result_worker(self) -> None:
        async with aiohttp.ClientSession() as session:
            while True:
                result = await self.result_queue.get()
                try:
                    await self._deliver(session, result)
                finally:
                    self.result_queue.task_done()

    async def _deliver(self, session, result: dict) -> None:
        """A completed job's result embodies real chip time; a transient
        upload blip must not discard it (and a dropped result gets this
        worker flagged by the hive's timeout-based failure detection).
        Exhausted retries spool the envelope to the dead-letter directory
        for replay on the next startup."""
        trace = obs_trace.detach(result)  # must never reach json.dumps
        spooled = result.pop("_dead_letter_path", None)
        # lease attribution: a lease-aware hive partitions faults per
        # worker and dedupes redelivery races by uploader; the reference
        # hive ignores the extra field
        result.setdefault("worker_name", self.settings.worker_name)
        if trace is not None:
            trace.phase("upload")
            # swarmdurable (ISSUE 14): echo the grant's hive-epoch
            # stamp so a recovered hive can tell a pre-crash grant's
            # upload (settled once as epoch salvage) from a live one.
            # Stamped BEFORE the upload attempts so a spooled envelope
            # keeps it — a dead-letter replay after the restart still
            # carries its original epoch. Never stamped when the hive
            # sent none: reference wire shape untouched.
            if trace.meta.get(HIVE_EPOCH_KEY) is not None:
                result.setdefault(HIVE_EPOCH_KEY,
                                  trace.meta[HIVE_EPOCH_KEY])
            # swarmfed (ISSUE 17): echo the grant's owner-shard stamp
            # the same way — the upload routes to the shard that holds
            # the lease (a stolen job's owner, not its delivery path),
            # and a spooled envelope keeps the routing for its replay.
            # Never stamped when the hive sent none: wire parity.
            if trace.meta.get(HIVE_SHARD_KEY) is not None:
                result.setdefault(HIVE_SHARD_KEY,
                                  trace.meta[HIVE_SHARD_KEY])
            if trace.meta.get("trace_id"):
                # swarmsight (ISSUE 13): a hive that stamped a trace
                # context gets the span digest back on the envelope —
                # the worker half of the cross-worker flight record.
                # Attached BEFORE the upload so a dead-lettered result
                # replays it later (straggler salvage keeps its story);
                # never attached without a context, so the reference-
                # hive wire shape is untouched.
                try:
                    result[obs_flight.SPAN_DIGEST_KEY] = \
                        obs_flight.span_digest(
                            trace, worker_name=self.settings.worker_name)
                except Exception as exc:  # telemetry must never block
                    log.debug("span digest failed for %s: %s",
                              result.get("id"), exc)
        shard = self._result_shard(result)
        try:
            with obs_trace.activate(trace):
                uploaded = await self._upload_with_retry(session, result,
                                                         shard)
        except asyncio.CancelledError:
            # shutdown cancelled us mid-upload: persist before dying
            if spooled is None:
                shard.spool.spool(result)
                self.stats.results_dead_lettered += 1
            self._settle_inflight(result)
            self._finish_trace(trace, result, settled="dead_letter")
            raise
        if uploaded:
            if spooled is not None:
                shard.spool.discard(spooled)
                self._replayed_paths.discard(str(spooled))
            # GC on ack (ISSUE 6 satellite): the job settled, its resume
            # checkpoint is stale by definition
            self.checkpoints.discard(result.get("id"))
        elif spooled is None:
            shard.spool.spool(result)
            self.stats.results_dead_lettered += 1
        else:
            # a replayed result that failed again keeps its existing
            # file — and leaves the in-queue set, so the NEXT heal's
            # live replay picks it up again
            self._replayed_paths.discard(str(spooled))
        self._settle_inflight(result)
        self._finish_trace(trace, result,
                           settled="uploaded" if uploaded else "dead_letter")

    def _result_shard(self, result: dict) -> _HiveShard:
        """Which shard an upload belongs to: the envelope's owner-shard
        echo first (stamped from the grant; survives spool + replay),
        the in-flight routing table second, shard 0 otherwise (the
        single-hive worker always lands here)."""
        raw = result.get(HIVE_SHARD_KEY)
        if raw is None:
            raw = self._inflight_shard.get(result.get("id"))
        try:
            index = 0 if raw is None else int(raw)
        except (TypeError, ValueError):
            index = 0
        if 0 <= index < len(self.shards):
            return self.shards[index]
        return self.shards[0]

    def _settle_inflight(self, result: dict) -> None:
        """The job left this worker's hands (uploaded or dead-lettered):
        stop heartbeating its lease."""
        self._inflight.pop(result.get("id"), None)
        self._inflight_shard.pop(result.get("id"), None)

    def _finish_trace(self, trace, result: dict, settled: str) -> None:
        """Close a job's span tree, publish it to the worker's trace
        ring, and fold its phase durations into the latency histograms
        — the per-job numbers the ROADMAP's perf work tunes against."""
        if trace is None:
            return
        outcome = classify_result(result)
        trace.meta["outcome"] = outcome
        trace.meta["settled"] = settled
        trace.finish(self.traces)
        service_s = 0.0
        for phase in trace.root.children:
            self._phase_seconds.observe(phase.duration_s, phase=phase.name)
            if phase.name in ("execute", "upload"):
                service_s += phase.duration_s
        self._job_seconds.observe(trace.root.duration_s)
        self._jobs_total.inc(outcome=outcome)
        if outcome == "ok" and service_s > 0.0:
            # the admission estimator's service EWMA (node/overload.py)
            # learns the worker-side cost of a successful job — execute
            # + upload, queue wait excluded (the queue-drain term
            # models that separately), divided by the attempt size its
            # execute phase spanned (see _attempt). Failure envelopes
            # are excluded: a fast refusal would drag the estimate
            # toward zero and re-admit exactly the jobs being shed.
            try:
                attempt_jobs = max(1, int(
                    trace.meta.get("attempt_jobs") or 1))
            except (TypeError, ValueError):
                attempt_jobs = 1
            self.overload.note_service(trace.meta.get("workflow"),
                                       service_s / attempt_jobs)

    async def _upload_with_retry(self, session, result,
                                 shard: _HiveShard | None = None) -> bool:
        shard = shard if shard is not None else self.shards[0]
        retries = max(1, int(self.settings.upload_retries))
        for attempt in range(1, retries + 1):
            try:
                response = await shard.client.post_result(session, result)
                self._note_hive_ok(shard)
                log.info("uploaded result %s: %s", result.get("id"),
                         response)
                return True
            except Exception as exc:
                self._note_hive_failure("upload", exc, shard)
                self.stats.upload_retries += 1
                log.warning("result upload attempt %d/%d failed: %s",
                            attempt, retries, exc)
                if shard.session.in_outage:
                    # ride-through (ISSUE 14): during a declared outage
                    # the full retry ladder only delays the spool (and
                    # the next result behind it). One probe per result
                    # keeps testing the hive; the spool replays LIVE on
                    # heal, so giving up early costs nothing.
                    log.warning("hive in outage; spooling result %s "
                                "after a single attempt",
                                result.get("id"))
                    return False
                if attempt < retries:
                    await asyncio.sleep(backoff_delay(
                        attempt, self.settings.upload_retry_delay_s,
                        self.settings.poll_backoff_cap_s,
                        self._retry_rng))
        return False


async def run_worker(settings: Settings | None = None) -> int:
    """Run one worker to completion; returns its exit code — 0, or
    guard.GUARD_RESTART_EXIT_CODE when the self-healing ladder's
    restart rung requested a supervisor-visible restart (ISSUE 10)."""
    worker = Worker(settings)
    await worker.run()
    return int(worker.exit_code)


def main() -> None:  # `python -m chiaswarm_tpu.node.worker`
    import sys

    sys.exit(asyncio.run(run_worker()))


if __name__ == "__main__":
    main()
