"""The worker daemon: poll the hive, execute on mesh slots, upload results.

Capability parity with swarm/worker.py:21-195, with the reference's
concurrency bug fixed: the reference acquires the GPU semaphore both while
*polling* and while *executing* (worker.py:60,108 + 118,127), serializing
the two on single-GPU nodes (SURVEY.md §3.1). Here backpressure is the
bounded ``work_queue`` alone — the poll loop simply waits for queue space,
and each slot task owns its own execution; no shared semaphore.

Startup gates mirror the reference's (worker.py:166-181): an accelerator
must be present (TPU/virtual-CPU mesh instead of CUDA), logging configured,
and matmul precision pinned (bf16 — the TPU analog of TF32 knobs).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any

import aiohttp
import jax

from chiaswarm_tpu.core.chip_pool import ChipPool
from chiaswarm_tpu.node.executor import (
    do_work,
    do_work_batch,
    job_rows,
    rows_cap,
    single_chip_rows,
)
from chiaswarm_tpu.node.hive import (
    POLL_BUSY_S,
    POLL_ERROR_S,
    POLL_IDLE_S,
    BadWorkerError,
    HiveClient,
)
from chiaswarm_tpu.node.logging_setup import setup_logging
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.node.settings import Settings, load_settings

log = logging.getLogger("chiaswarm.worker")


def _burst_key(job: dict) -> tuple | None:
    """Cheap raw-job coalescability key (None = never coalesce).

    Conservative pre-filter for the slot burst drain: plain txt2img,
    img2img and inpaint jobs with identical static fields are drained
    together (images themselves differ per job by design — per-job init
    stacks + encode seeds keep solo equality) — the executor's precise
    post-formatting grouping (node/executor.py::
    synchronous_do_work_batch) is the authority (it also sees the FETCHED
    image shapes, which this pre-filter cannot); this just keeps
    non-coalescable traffic on the per-job path so its results upload as
    soon as each job finishes."""
    if job.get("workflow") not in (None, "", "txt2img", "img2img",
                                   "inpaint"):
        return None
    model = str(job.get("model_name", ""))
    if model.startswith("DeepFloyd/") or "pix2pix" in model:
        return None
    params = job.get("parameters") or {}
    if params.get("controlnet") or params.get("upscale"):
        return None
    image = job.get("image")
    return (model, job.get("height"), job.get("width"),
            job.get("num_inference_steps"), job.get("guidance_scale"),
            job.get("lora"), job.get("textual_inversion"),
            job.get("cross_attention_scale"),
            # mode split: generation vs img2img vs inpaint (+ inline
            # image grids; URI-fetched sizes are the executor's job)
            bool(job.get("start_image_uri") or image is not None),
            bool(job.get("mask_image_uri")
                 or job.get("mask_image") is not None),
            job.get("strength"),
            None if image is None else tuple(getattr(image, "shape", ())),
            repr(sorted(params.items())))




class Worker:
    """One node process: N mesh-slot executors + poll/upload tasks.

    Designed as a class (vs the reference's module globals) so tests can run
    multiple hermetic workers against a FakeHive in one process.
    """

    def __init__(self, settings: Settings | None = None,
                 pool: ChipPool | None = None,
                 registry: ModelRegistry | None = None,
                 hive: HiveClient | None = None) -> None:
        self.settings = settings or load_settings()
        # registry first: its catalog feeds the default mesh policy
        self.registry = registry or ModelRegistry(
            attn_impl="auto" if self.settings.use_flash_attention else "xla"
        )
        self.pool = pool if pool is not None else self._default_pool()
        self.hive = hive or HiveClient(
            self.settings.hive_uri, self.settings.hive_token,
            self.settings.worker_name,
        )
        # queue bound = total in-flight capacity: per slot, the larger of
        # its pipeline depth (transfer/compute overlap) and its data-axis
        # width (cross-job coalescing needs that many jobs queued). The
        # reference sizes its queue to the GPU count (worker.py:186).
        self.work_queue: asyncio.Queue = asyncio.Queue(
            maxsize=sum(
                max(getattr(slot, "depth", 1), slot.data_width)
                for slot in self.pool))
        self.result_queue: asyncio.Queue = asyncio.Queue()
        self._stop = asyncio.Event()
        self.jobs_done = 0
        # slots currently blocked on work_queue.get(): the burst drain
        # leaves this many jobs in the queue so coalescing on one slot
        # never starves an idle neighbor (multi-slot fairness reserve)
        self._hungry_slots = 0

    def _default_pool(self) -> ChipPool:
        """One slot over all chips. An explicit ``mesh_shape`` setting
        wins; otherwise dp x tp derives from the device count and the
        heaviest catalog family (core/mesh.py::derive_mesh_spec) — a
        stock multi-chip node engages tensor parallelism exactly when a
        served model needs it, with no operator configuration."""
        from chiaswarm_tpu.core.mesh import MeshSpec, derive_mesh_spec

        if self.settings.mesh_shape:
            spec = MeshSpec(dict(self.settings.mesh_shape))
        else:
            spec = derive_mesh_spec(len(jax.devices()),
                                    self._heaviest_catalog_bytes(),
                                    latency=self.settings.latency_mode)
            log.info("derived default mesh: %s", spec.shape)
        return ChipPool(n_slots=1, mesh_spec=spec)

    def _heaviest_catalog_bytes(self) -> int | None:
        """bf16 footprint of the largest diffusion family the catalog
        serves (None = empty catalog). Non-SD names (tts/audio/caption)
        fall through get_family to sd15 — a small, harmless overestimate
        that never turns tp on by itself."""
        try:
            from chiaswarm_tpu.models.configs import get_family
            from chiaswarm_tpu.pipelines.components import (
                estimate_family_bytes,
            )

            names = self.registry.known_models()
            if not names:
                return None
            families = {get_family(name).name for name in names}
            return max(estimate_family_bytes(f) for f in families)
        except Exception as exc:  # policy must never block startup
            log.warning("mesh policy estimate failed (%s); using dp-only",
                        exc)
            return None

    # ---- lifecycle ----

    def startup(self) -> None:
        devices = jax.devices()
        if not devices:
            raise RuntimeError("no accelerator devices present; quitting")
        from chiaswarm_tpu.node.settings import settings_root

        setup_logging(settings_root() / "logs", self.settings.log_filename,
                      self.settings.log_level)
        log.info("worker %s: %d device(s), %d slot(s), backend=%s",
                 self.settings.worker_name, len(devices), len(self.pool),
                 jax.default_backend())
        # bf16 matmuls on the MXU — the TPU analog of the reference's
        # TF32/cudnn.benchmark startup knobs (swarm/worker.py:179-181)
        jax.config.update("jax_default_matmul_precision", "bfloat16")
        # amortize XLA compiles across worker restarts
        from chiaswarm_tpu.core.compile_cache import (
            enable_persistent_compilation_cache,
        )

        enable_persistent_compilation_cache()

    def request_stop(self) -> None:
        self._stop.set()

    async def run(self) -> None:
        self.startup()
        # bind the health endpoint BEFORE spawning workers: a port clash
        # must fail fast, not leave unsupervised poll/slot tasks running
        health_runner = await self._start_health_server()
        tasks = [
            asyncio.create_task(self._slot_worker(slot), name=f"slot{i}")
            for i, slot in enumerate(self.pool)
        ]
        tasks.append(asyncio.create_task(self._result_worker(),
                                         name="results"))
        tasks.append(asyncio.create_task(self._poll_loop(), name="poll"))
        try:
            await self._stop.wait()
        finally:
            for task in tasks:
                task.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            if health_runner is not None:
                await health_runner.cleanup()

    # ---- health endpoint (observability gap fix, SURVEY.md §5: the
    # reference's only health signal is the hive's timeout detection) ----

    def health(self) -> dict[str, Any]:
        from chiaswarm_tpu import WORKER_VERSION

        return {
            "status": "ok",
            "worker_version": WORKER_VERSION,
            "worker_name": self.settings.worker_name,
            "backend": jax.default_backend(),
            "devices": len(jax.devices()),
            "slots": len(self.pool),
            "jobs_done": self.jobs_done,
            "queue_depth": self.work_queue.qsize(),
            "results_pending": self.result_queue.qsize(),
        }

    async def _start_health_server(self):
        port = int(self.settings.health_port or 0)
        if port <= 0 and not self.settings.health_bind_ephemeral:
            return None
        from aiohttp import web

        async def healthz(_request):
            return web.json_response(self.health())

        app = web.Application()
        app.router.add_get("/healthz", healthz)
        runner = web.AppRunner(app)
        await runner.setup()
        # loopback by default: the endpoint is operator observability,
        # not a service for arbitrary swarm peers
        host = self.settings.health_host or "127.0.0.1"
        site = web.TCPSite(runner, host, max(port, 0))
        await site.start()
        bound_port = runner.addresses[0][1] if runner.addresses else port
        self.health_address = (host, bound_port)
        log.info("health endpoint on %s:%d/healthz", host, bound_port)
        return runner

    # ---- tasks ----

    async def _poll_loop(self) -> None:
        async with aiohttp.ClientSession() as session:
            while not self._stop.is_set():
                # natural backpressure: wait for queue space, not a semaphore
                while self.work_queue.full():
                    await asyncio.sleep(1)
                delay = await self._ask_for_work(session)
                try:
                    await asyncio.wait_for(self._stop.wait(), timeout=delay)
                except asyncio.TimeoutError:
                    pass

    async def _ask_for_work(self, session: aiohttp.ClientSession) -> float:
        try:
            jobs = await self.hive.get_work(session)
        except BadWorkerError as exc:
            log.error("hive flagged this worker: %s", exc)
            return POLL_ERROR_S
        except Exception as exc:
            log.warning("poll failed: %s", exc)
            return POLL_ERROR_S
        for job in jobs:
            log.info("got job %s", job.get("id"))
            await self.work_queue.put(job)
        return POLL_BUSY_S if jobs else POLL_IDLE_S

    async def _slot_worker(self, slot) -> None:
        """Feed one slot, keeping up to ``slot.depth`` jobs in flight.

        With depth 2, job N+1's host prep + program dispatch overlap job
        N's device->host image transfer (chip never idles between jobs);
        the slot's bounded semaphore enforces the cap, this semaphore
        just avoids pulling queue items nothing can run yet."""
        inflight = asyncio.Semaphore(max(1, getattr(slot, "depth", 1)))
        pending: set[asyncio.Task] = set()
        # cross-job coalescing: a dp-sharded slot runs up to dp compatible
        # jobs as ONE batched program (executor groups them; incompatible
        # jobs in a burst just run serially). 512px-class jobs
        # additionally batch up to single_chip_rows() per device — one
        # chip is NOT saturated by them at batch 1 (+20% measured,
        # BASELINE.md r4); 1024px-class stays at one row per device
        # (saturated, r1). On multi-slot pools the drain loop below
        # additionally leaves ``_hungry_slots`` jobs in the queue, so a
        # coalescing slot never strips work an idle neighbor is already
        # waiting for.
        base_merge = slot.data_width

        async def run_burst(burst: list[dict]) -> None:
            try:
                if len(burst) == 1:
                    results = [await do_work(burst[0], slot, self.registry)]
                else:
                    results = await do_work_batch(burst, slot,
                                                  self.registry)
                for result in results:
                    await self.result_queue.put(result)
                    self.jobs_done += 1
            except Exception as exc:  # keep the loop alive, always
                log.exception("slot worker error: %s", exc)
            finally:
                inflight.release()
                for _ in burst:
                    self.work_queue.task_done()

        held: dict | None = None  # mismatched drain candidate, runs next
        try:
            while True:
                await inflight.acquire()
                if held is not None:
                    burst, held = [held], None
                else:
                    # a slot that ALREADY has work in flight must not
                    # synchronously grab a job a hungry neighbor is
                    # blocked on (acquire+get both return without
                    # yielding when satisfiable, so at depth>=2 this
                    # slot would steal the fairness reserve before the
                    # woken neighbor's coroutine ever runs). Yield until
                    # the reserved jobs are consumed or surplus arrives.
                    while (pending and self._hungry_slots
                           and 0 < self.work_queue.qsize()
                           <= self._hungry_slots):
                        await asyncio.sleep(0)
                    self._hungry_slots += 1
                    try:
                        burst = [await self.work_queue.get()]
                    finally:
                        self._hungry_slots -= 1
                key = _burst_key(burst[0])
                rows = rows_max = job_rows(burst[0])
                per_device = single_chip_rows(burst[0])
                max_merge = base_merge * per_device
                while key is not None and len(burst) < max_merge:
                    # fairness reserve: jobs other slots are blocked on
                    # stay in the queue (the drain below has no awaits,
                    # so this count cannot change mid-drain)
                    if self.work_queue.qsize() <= self._hungry_slots:
                        break
                    try:
                        candidate = self.work_queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    cand_rows = job_rows(candidate)
                    # num_images_per_prompt multiplies batch rows; never
                    # drain a burst whose total rows exceed what the
                    # heaviest member's solo run would put per device
                    # (the executor's _row_chunks is the authority, this
                    # avoids claiming jobs it would split anyway)
                    fits = rows + cand_rows <= rows_cap(
                        max(rows_max, cand_rows), base_merge, per_device)
                    if _burst_key(candidate) == key and fits:
                        burst.append(candidate)
                        rows += cand_rows
                        rows_max = max(rows_max, cand_rows)
                    else:
                        # hold the mismatch and run it as the NEXT burst:
                        # re-queueing at the tail would let it repeatedly
                        # lose its FIFO position to later-arriving
                        # coalescable jobs (unbounded reordering)
                        held = candidate
                        break
                task = asyncio.create_task(run_burst(burst))
                pending.add(task)
                task.add_done_callback(pending.discard)
        finally:
            # a held job was claimed from the queue but never dispatched;
            # put it back so cancellation cannot silently drop it (and
            # work_queue.join() accounting stays balanced)
            if held is not None:
                try:
                    self.work_queue.put_nowait(held)
                except asyncio.QueueFull:
                    log.error("dropping held job %s at shutdown: queue "
                              "full (hive recovers it via timeout)",
                              held.get("id"))
                self.work_queue.task_done()
            # drain in-flight jobs before the loop closes: cancel, then
            # AWAIT them so their finally blocks (queue bookkeeping) run
            # and no pending task outlives the event loop
            for task in list(pending):
                task.cancel()
            if pending:
                await asyncio.gather(*list(pending), return_exceptions=True)

    RESULT_RETRIES = 3
    RESULT_RETRY_DELAY_S = 5.0

    async def _result_worker(self) -> None:
        async with aiohttp.ClientSession() as session:
            while True:
                result = await self.result_queue.get()
                try:
                    await self._upload_with_retry(session, result)
                finally:
                    self.result_queue.task_done()

    async def _upload_with_retry(self, session, result) -> None:
        """A completed job's result embodies real chip time; a transient
        upload blip must not discard it (and a dropped result gets this
        worker flagged by the hive's timeout-based failure detection)."""
        for attempt in range(1, self.RESULT_RETRIES + 1):
            try:
                response = await self.hive.post_result(session, result)
                log.info("uploaded result %s: %s", result.get("id"), response)
                return
            except Exception as exc:
                log.warning("result upload attempt %d/%d failed: %s",
                            attempt, self.RESULT_RETRIES, exc)
                if attempt < self.RESULT_RETRIES:
                    await asyncio.sleep(self.RESULT_RETRY_DELAY_S * attempt)
        log.error("dropping result %s after %d failed uploads",
                  result.get("id"), self.RESULT_RETRIES)


async def run_worker(settings: Settings | None = None) -> None:
    await Worker(settings).run()


def main() -> None:  # `python -m chiaswarm_tpu.node.worker`
    asyncio.run(run_worker())


if __name__ == "__main__":
    main()
