"""Job executor: async -> blocking bridge with error-as-artifact semantics.

Capability parity with swarm/generator.py:12-95:

- ``do_work`` hops from the event loop to a worker thread so generation
  never blocks polling/uploads (reference: loop.run_in_executor, :12-14).
- Error taxonomy drives hive retry behavior: argument-formatting errors and
  ``ValueError`` raised by callbacks are **fatal** (``fatal_error: True`` —
  the job's inputs are bad, do not redispatch, :34-41,:56-63); any other
  exception returns an error artifact *without* the fatal flag so the hive
  may retry elsewhere (:65-79).
- Every failure renders as an artifact (image or JSON by requested
  content type) so the user always receives a result object.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any

import numpy as np

from chiaswarm_tpu import WORKER_VERSION
from chiaswarm_tpu.node.job_args import format_args
from chiaswarm_tpu.node.output_processor import (
    encode_image,
    image_from_text,
    make_result,
    make_text_result,
)
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.node.resilience import (
    NONFATAL_KINDS,
    checkpoint_scope,
    classify_exception,
)
from chiaswarm_tpu.obs import trace as obs_trace
from chiaswarm_tpu.node.hivelog import HIVE_EPOCH_KEY
from chiaswarm_tpu.obs.flight import TRACE_CTX_KEY
from chiaswarm_tpu.obs.profiling import job_profile
from chiaswarm_tpu.obs.trace import span

log = logging.getLogger("chiaswarm.executor")


async def do_work(job: dict[str, Any], slot, registry: ModelRegistry) -> dict:
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(
        None, synchronous_do_work, job, slot, registry
    )


async def do_work_batch(jobs: list[dict[str, Any]], slot,
                        registry: ModelRegistry) -> list[dict]:
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(
        None, synchronous_do_work_batch, jobs, slot, registry
    )


def _error_payload(exc: Exception, content_type: str,
                   kind: str | None = None) -> tuple[dict, dict]:
    message = exc.args[0] if exc.args else "error generating result"
    message = str(message)
    # structured envelope: the failure kind + exception class ride in the
    # config so the hive (and the worker's own degradation ladder,
    # node/worker.py) learn of failures explicitly instead of via the
    # hive's timeout detector (swarm/worker.py:92-97)
    config = {
        "error": message,
        "error_kind": kind or classify_exception(exc),
        "error_class": type(exc).__name__,
    }
    if content_type.startswith("image/"):
        img = image_from_text(message)
        artifacts = {
            "primary": make_result(encode_image(img, content_type),
                                   content_type)
        }
    else:
        artifacts = {"primary": make_text_result(message)}
    return artifacts, config


def _result(job_id: Any, artifacts: dict, config: dict,
            fatal: bool = False) -> dict[str, Any]:
    result = {
        "id": job_id,
        "artifacts": artifacts,
        "nsfw": config.get("nsfw", False),
        "worker_version": WORKER_VERSION,
        "pipeline_config": config,
    }
    if fatal:
        result["fatal_error"] = True
    return result


def error_result(job: dict[str, Any], exc_or_message: Any, *,
                 kind: str | None = None, fatal: bool = False) -> dict:
    """Structured error envelope for a job that never produced a result
    through the normal executor path — deadline expiry, a crashed slot
    task, a circuit-breaker refusal (node/worker.py), or a chaos-injected
    executor fault (node/chaos.py). Same wire shape as executor-internal
    failures, so the hive's result handler needs no new cases."""
    if isinstance(exc_or_message, BaseException):
        exc: Exception = exc_or_message if isinstance(
            exc_or_message, Exception) else RuntimeError(str(exc_or_message))
    else:
        exc = RuntimeError(str(exc_or_message))
    content_type = str(job.get("content_type") or "image/jpeg")
    artifacts, config = _error_payload(exc, content_type, kind=kind)
    return _result(job.get("id"), artifacts, config, fatal=fatal)


# per-job XLA tracing when CHIASWARM_PROFILE_DIR is set — the hook the
# reference lacks entirely (SURVEY.md §5: its only telemetry is print
# statements). Traces open in XProf/Perfetto. Now shared with the
# worker's on-demand /debug/profile capture, which holds the same
# process-global profiler lock (chiaswarm_tpu/obs/profiling.py).
_maybe_profile = job_profile


def _format(job: dict[str, Any], registry: ModelRegistry):
    """-> (job_id, content_type, callback, kwargs) or a fatal result."""
    job = dict(job)
    job.pop(obs_trace.TRACE_KEY, None)  # never a pipeline kwarg
    # the hive's trace context and epoch stamp are normally popped at
    # poll receipt (node/worker.py); strip them defensively for
    # directly-injected jobs (tests, resubmissions) — like the trace
    # itself, never a kwarg
    job.pop(TRACE_CTX_KEY, None)
    job.pop(HIVE_EPOCH_KEY, None)
    job_id = job.pop("id", None)
    content_type = job.get("content_type", "image/jpeg")
    try:
        with span("format"):
            callback, kwargs = format_args(job, registry)
    except Exception as exc:
        # bad inputs are fatal (do not redispatch) — but formatting also
        # FETCHES input images, and a network blip is not the user's
        # fault: transient kinds upload without the fatal flag so the
        # worker's ladder (and failing that, the hive) may retry, and a
        # node-local model-unavailable is a ROUTING problem a lease-aware
        # hive redispatches (resilience.REDISPATCH_KINDS), never fatal
        kind = classify_exception(exc)
        fatal = kind not in NONFATAL_KINDS
        log.warning("job %s failed formatting (%s): %s", job_id, kind, exc)
        artifacts, config = _error_payload(exc, content_type, kind=kind)
        return None, _result(job_id, artifacts, config, fatal=fatal)
    return (job_id, content_type, callback, kwargs), None


def _execute(job_id, content_type, callback, kwargs, slot) -> dict:
    from chiaswarm_tpu.serving.guard import (
        InvalidOutput,
        _slot_devices,
        watch_solo,
    )

    # swarmguard (ISSUE 10): the solo denoise phase runs under the hang
    # watchdog (budget = steps x the lane step EWMA x k; never armed
    # cold, so a first-call compile cannot false-positive). DIFFUSION
    # callbacks only — the step EWMA is a diffusion-lane signal and
    # says nothing about video/audio/caption service times. A
    # hung-but-returned call raises StepHung -> classified transient ->
    # the PR-2 ladder re-runs it; one that never returns is the
    # deadline envelope's job (node/worker.py).
    watched_steps = (kwargs.get("num_inference_steps")
                     if getattr(callback, "__name__", "")
                     == "diffusion_callback" else None)
    # warmth key ~ the solo program variant: a new model or resolution
    # compiles its own executable, and its first call must get the
    # ceiling budget, not another variant's steady-state one
    watch_key = (str(kwargs.get("model_name")), kwargs.get("height"),
                 kwargs.get("width"))
    try:
        with _maybe_profile(job_id), \
                watch_solo(slot, watched_steps, key=watch_key):
            artifacts, config = slot(callback, **kwargs)
    except InvalidOutput as exc:
        # numerically poisoned output screened before upload: a
        # non-fatal invalid_output envelope (REDISPATCH_KINDS) instead
        # of garbage pixels, and a health event for this slot's devices
        guard = getattr(slot, "_guard", None)
        if guard is not None:
            guard.note_invalid_output(
                _slot_devices(slot),
                model=str(kwargs.get("model_name") or ""))
        log.error("job %s produced invalid output (%s); envelope "
                  "uploaded instead of the poisoned image", job_id, exc)
        artifacts, config = _error_payload(exc, content_type,
                                           kind="invalid_output")
        return _result(job_id, artifacts, config)
    except ValueError as exc:  # callback-declared unrecoverable input error
        # ...EXCEPT a node-local model-unavailable (missing/broken/
        # quarantined checkpoint): that is this node refusing, not the
        # inputs being bad — it uploads WITHOUT the fatal flag so a
        # lease-aware hive redispatches it to a node that holds the
        # model (ISSUE 6; resolves the PR-2 taxonomy tension)
        kind = classify_exception(exc)
        fatal = kind not in NONFATAL_KINDS
        log.warning("job %s %s: %s", job_id,
                    "fatal" if fatal else kind, exc)
        artifacts, config = _error_payload(exc, content_type, kind=kind)
        return _result(job_id, artifacts, config, fatal=fatal)
    except Exception as exc:  # error artifact without the fatal flag: the
        log.exception("job %s errored", job_id)  # hive may retry elsewhere
        artifacts, config = _error_payload(exc, content_type)
        return _result(job_id, artifacts, config)
    return _result(job_id, artifacts, config)


def _stepper_submit(job_id, content_type, callback, kwargs, slot,
                    registry):
    """Submit an eligible diffusion job (txt2img / img2img / inpaint /
    ControlNet, ISSUE 7) to the slot's continuous step scheduler
    (serving/stepper.py). Returns a ticket or None (run the job through
    the ordinary burst/solo path instead). Submission failures are
    never terminal for the job — it just falls back."""
    from chiaswarm_tpu.workloads.diffusion import (
        diffusion_callback,
        stepper_eligible,
        stepper_submit,
    )

    if callback is not diffusion_callback or not stepper_eligible(kwargs):
        return None
    # residency fast-path (ISSUE 8): a model the ledger knows is
    # degraded to load-per-job must not pin a lane resident — and must
    # not pay a full transient load just to be rejected by the lane
    # (workloads.stepper_submit re-checks after first-ever loads)
    lane_ok = getattr(registry, "lane_resident_ok", None)
    if callable(lane_ok) and not lane_ok(str(kwargs.get("model_name"))):
        log.debug("job %s model degraded to load-per-job; skipping lanes",
                  job_id)
        return None
    from chiaswarm_tpu.core.rng import draw_seed
    from chiaswarm_tpu.serving.stepper import LaneReject

    seed = kwargs.get("seed")
    seed = draw_seed() if seed is None else int(seed)
    try:
        return stepper_submit(slot, registry, kwargs, seed, job_id=job_id)
    except LaneReject as exc:
        log.debug("job %s not lane-eligible (%s)", job_id, exc)
        return None
    except Exception as exc:
        log.warning("job %s lane submit failed (%s); per-job path",
                    job_id, exc)
        return None


def _stepper_collect(job_id, content_type, slot, ticket,
                     registry=None, kwargs=None) -> dict | None:
    """Wait out a lane ticket. Returns the finished result, a timeout
    envelope (in-lane deadline expiry), an ``invalid_output`` envelope
    (poisoned row, swarmguard), or None — meaning the job must re-run
    through the per-job path (lane fault; zero-loss fallback).

    When ``kwargs`` is provided and the lane was CONDEMNED by the hang
    watchdog (guard.LaneHung), the job is re-admitted ONCE to a freshly
    built lane, resuming from the condemnation checkpoint — the
    self-healing lane-rebuild rung. A second hang (or a reject) falls
    through to the per-job path, the PR-2 ladder."""
    from chiaswarm_tpu.serving.guard import (
        InvalidOutput,
        LaneHung,
        _slot_devices,
    )
    from chiaswarm_tpu.serving.stepper import LaneDeadline
    from chiaswarm_tpu.workloads.diffusion import stepper_finish

    try:
        artifacts, config = stepper_finish(ticket)
    except LaneDeadline as exc:
        return error_result({"id": job_id, "content_type": content_type},
                            exc, kind="timeout")
    except InvalidOutput as exc:
        guard = getattr(slot, "_guard", None)
        if guard is not None:
            guard.note_invalid_output(_slot_devices(slot),
                                      model=str(ticket.model_name))
        log.error("job %s retired invalid_output (%s); envelope "
                  "uploaded instead of a poisoned image", job_id, exc)
        return error_result({"id": job_id, "content_type": content_type},
                            exc, kind="invalid_output")
    except LaneHung as exc:
        # hang accounting (device health, condemned-lane counters)
        # already happened lane-side when the watchdog condemned it
        if kwargs is not None:
            healed = _stepper_resubmit(job_id, content_type, slot,
                                       registry, kwargs, ticket, exc)
            if healed is not None:
                return healed
        log.warning("job %s lost its lane to the watchdog (%s); "
                    "per-job path", job_id, exc)
        return None
    except Exception as exc:
        kind = classify_exception(exc)
        if kind == "oom":
            from chiaswarm_tpu.serving.stepper import get_stepper

            get_stepper(slot).note_oom()  # rebuild lanes narrower
        log.warning("job %s lane run failed (%s: %s); per-job path",
                    job_id, kind, exc)
        return None
    return _result(job_id, artifacts, config)


def _stepper_resubmit(job_id, content_type, slot, registry, kwargs,
                      ticket, exc) -> dict | None:
    """Re-admit a condemned lane's job to a freshly built lane
    (swarmguard lane-rebuild rung): same kwargs, the SAME seed the
    first admission drew (a resumed trajectory must not re-derive its
    noise), and the condemnation checkpoint as the resume payload so
    surviving rows splice back in at step k instead of restarting.
    Returns the finished result or None (fall back to the per-job
    path). The inner collect passes no kwargs — a second hang is not
    healed again."""
    from chiaswarm_tpu.workloads.diffusion import stepper_submit

    retry_kwargs = dict(kwargs)
    retry_kwargs["seed"] = ticket.seed
    resume = getattr(exc, "resume", None)
    if isinstance(resume, dict):
        retry_kwargs["resume"] = resume
    else:
        retry_kwargs.pop("resume", None)
    try:
        retry = stepper_submit(slot, registry, retry_kwargs, ticket.seed,
                               job_id=job_id)
    except Exception as submit_exc:
        log.warning("job %s lane re-admission failed (%s); per-job "
                    "path", job_id, submit_exc)
        return None
    log.warning("job %s re-admitted to a fresh lane after condemnation"
                "%s", job_id,
                (f", resuming at step {resume.get('step')}"
                 if isinstance(resume, dict) else " (no checkpoint — "
                 "restarting at step 0)"))
    return _stepper_collect(job_id, content_type, slot, retry)


def synchronous_do_work(job: dict[str, Any], slot,
                        registry: ModelRegistry) -> dict[str, Any]:
    log.info("processing job %s", job.get("id"))
    # the job's span tree follows it into this thread: format / encode /
    # step / decode spans below attach under the worker's open
    # "execute" phase (chiaswarm_tpu/obs/trace.py). The checkpoint scope
    # binds the worker's spool so the solo path can record its coarse
    # phase markers (workloads/diffusion.py; lanes snapshot themselves).
    with obs_trace.activate(obs_trace.job_trace(job)), \
            checkpoint_scope(getattr(slot, "_checkpoint_spool", None),
                             job.get("id")):
        formatted, fatal = _format(job, registry)
        if formatted is None:
            return fatal
        job_id, content_type, _, kwargs = formatted
        ticket = _stepper_submit(*formatted, slot, registry)
        if ticket is not None:
            result = _stepper_collect(job_id, content_type, slot, ticket,
                                      registry, kwargs)
            if result is not None:
                return result
        return _execute(*formatted, slot)


def _coalesce_key(kwargs: dict[str, Any]):
    from chiaswarm_tpu.workloads.diffusion import COALESCE_KEYS

    # img2img/inpaint coalesce only with matching modes AND pixel grids:
    # the height/width kwargs may be absent for image jobs (the callback
    # takes the image's own size), so key on the fetched image AND mask
    # shapes (mask sizes are free-form solo — the pipeline resizes — so
    # presence alone would group unstackable masks)
    image = kwargs.get("image")
    mask = kwargs.get("mask_image")
    return ((kwargs.get("model_name"),
             None if image is None else tuple(np.asarray(image).shape),
             None if mask is None else tuple(np.asarray(mask).shape))
            + tuple(repr(kwargs.get(k)) for k in COALESCE_KEYS))


def job_rows(job_or_kwargs: dict[str, Any]) -> int:
    """Batch rows one job contributes to a coalesced program
    (``num_images_per_prompt`` multiplies rows; a bad value surfaces per
    job downstream, not here). Shared by this module's chunking and the
    worker's drain (node/worker.py) so the two never drift."""
    try:
        return max(1, int(job_or_kwargs.get("num_images_per_prompt") or 1))
    except (TypeError, ValueError):
        return 1


def single_chip_rows(kwargs: dict[str, Any]) -> int:
    """How many batch rows ONE device profitably carries for this job
    class. Measured (BASELINE.md r4) on the DIFFUSION workflows — the
    only job class reaching this via _burst_key/coalescable, so the rule
    cannot leak onto unbenched classes (ADVICE r4 #4): 512px-class
    programs are not MXU-saturated at batch 1 — batch 4 reaches +20%
    images/sec on one chip and the gain plateaus there; 1024px-class is
    saturated at batch 1 (r1). Size comes from the explicit kwargs or,
    for img2img/inpaint jobs that take the image's own grid, the fetched
    image shape; otherwise assumed large."""
    try:
        h, w = int(kwargs.get("height") or 0), int(kwargs.get("width") or 0)
    except (TypeError, ValueError):
        return 1
    if not (h and w):
        image = kwargs.get("image")
        if image is not None and getattr(image, "ndim", 0) >= 2:
            h, w = int(image.shape[0]), int(image.shape[1])
    return 4 if 0 < h * w <= 512 * 512 else 1


def rows_cap(rows_max: int, data_width: int, per_device_rows: int = 1) -> int:
    """Max total rows a coalesced program may carry:
    dp * max(ceil(rows_max/dp), per_device_rows) — per device, the LARGER
    of the heaviest member's own solo footprint and the measured
    profitable batch, never their product (a multi-image 512px job must
    not multiply into 4x its solo per-device memory; rows past the
    plateau add no throughput anyway)."""
    dw = max(1, int(data_width))
    return dw * max(-(-rows_max // dw), max(1, int(per_device_rows)))


def _row_chunks(group: list, data_width: int) -> list[list]:
    """Split a compatible group so one batched program never exceeds the
    per-device row footprint of its heaviest member's solo run.

    ``num_images_per_prompt`` multiplies batch rows, so bounding by job
    count alone would let e.g. 4 jobs x 8 images coalesce into a batch-32
    program — data_width times the per-device memory of any solo run, a
    likely OOM recovered only after a wasted large-batch compile. Greedy
    chunking keeps ceil(total_rows / dp) <= ceil(max_member_rows / dp)."""
    chunks: list[list] = []
    cur: list = []
    cur_rows = cur_max = 0
    # group members share COALESCE_KEYS (incl. height/width), so the
    # per-device row budget is uniform across the group
    per_device = single_chip_rows(group[0][3]) if group else 1
    for item in group:
        rows = job_rows(item[3])
        if cur and cur_rows + rows > rows_cap(max(cur_max, rows),
                                              data_width, per_device):
            chunks.append(cur)
            cur, cur_rows, cur_max = [], 0, 0
        cur.append(item)
        cur_rows += rows
        cur_max = max(cur_max, rows)
    if cur:
        chunks.append(cur)
    return chunks


def synchronous_do_work_batch(jobs: list[dict[str, Any]], slot,
                              registry: ModelRegistry) -> list[dict]:
    """Run a burst of jobs, coalescing compatible txt2img jobs into ONE
    batched program (workloads/diffusion.py::diffusion_coalesced_callback)
    — the dp-mesh efficiency path with no reference analog. Jobs that
    cannot coalesce (different static params, image inputs, non-diffusion
    workflows) run through the normal per-job path; a failed coalesced
    run falls back to per-job execution."""
    from chiaswarm_tpu.core.rng import draw_seed
    from chiaswarm_tpu.workloads.diffusion import (
        coalescable,
        diffusion_callback,
        diffusion_coalesced_callback,
    )

    if len(jobs) == 1:
        return [synchronous_do_work(jobs[0], slot, registry)]

    results: list[dict | None] = [None] * len(jobs)
    groups: dict[Any, list[tuple[int, Any, str, dict]]] = {}
    singles: list[tuple[int, Any, str, Any, dict]] = []
    # lane tickets: eligible jobs are submitted FIRST so their rows
    # splice into running lanes while the rest of the burst executes
    tickets: list[tuple[int, Any, str, dict, Any]] = []
    def _job_trace(i: int):
        return obs_trace.job_trace(jobs[i])

    for i, job in enumerate(jobs):
        log.info("processing job %s (burst of %d)", job.get("id"),
                 len(jobs))
        with obs_trace.activate(_job_trace(i)):
            formatted, fatal = _format(job, registry)
            if formatted is None:
                results[i] = fatal
                continue
            job_id, content_type, callback, kwargs = formatted
            if callback is diffusion_callback:
                # lanes first (the default engine, ISSUE 7) — incl.
                # non-coalescable ControlNet jobs, which ride
                # bundle-keyed lanes the burst path has no analog for
                ticket = _stepper_submit(job_id, content_type, callback,
                                         kwargs, slot, registry)
                if ticket is not None:
                    tickets.append((i, job_id, content_type, kwargs,
                                    ticket))
                    continue
            if callback is diffusion_callback and coalescable(kwargs):
                groups.setdefault(_coalesce_key(kwargs), []).append(
                    (i, job_id, content_type, kwargs))
            else:
                singles.append((i, job_id, content_type, callback, kwargs))

    data_width = max(1, int(getattr(slot, "data_width", 1)))
    chunked = [chunk for whole in groups.values()
               for chunk in _row_chunks(whole, data_width)]
    for group in chunked:
        if len(group) == 1:
            i, job_id, content_type, kwargs = group[0]
            singles.append((i, job_id, content_type, diffusion_callback,
                            kwargs))
            continue
        from chiaswarm_tpu.workloads.diffusion import COALESCE_KEYS

        kwargs0 = group[0][3]
        shared = {k: kwargs0.get(k) for k in COALESCE_KEYS}
        per_job = []
        for i, job_id, content_type, kwargs in group:
            seed = kwargs.get("seed")  # 0 is a valid pinned seed
            per_job.append({
                "prompt": kwargs.get("prompt"),
                "negative_prompt": kwargs.get("negative_prompt"),
                "num_images_per_prompt":
                    kwargs.get("num_images_per_prompt", 1),
                "seed": draw_seed() if seed is None else int(seed),
                # per-job init/mask images (img2img/inpaint coalescing;
                # shapes/presence are uniform across the group by key)
                "image": kwargs.get("image"),
                "mask_image": kwargs.get("mask_image"),
                # solo-equivalence: an absent content_type must hit the
                # same default the solo callback uses (image/png), NOT
                # _format's error-payload jpeg default
                "content_type": kwargs.get("content_type", "image/png"),
            })
        ids = [job_id for _, job_id, _, _ in group]
        # one batched program serves the whole group: each member's
        # trace gets a "coalesced" span with the shared boundaries
        group_spans = []
        for i, _, _, _ in group:
            trace = _job_trace(i)
            if trace is not None:
                group_spans.append(
                    trace.tail().child("coalesced", jobs=len(group)))
        try:
            with _maybe_profile(f"coalesced-{ids[0]}"):
                outs = slot.call_multi(
                    diffusion_coalesced_callback,
                    model_name=kwargs0.get("model_name"),
                    seed=per_job[0]["seed"],
                    registry=registry, jobs=per_job, **shared)
            if len(outs) != len(group):  # never silently drop a job
                raise RuntimeError(
                    f"coalesced callback returned {len(outs)} results "
                    f"for {len(group)} jobs")
            log.info("coalesced %d jobs onto one program: %s",
                     len(group), ids)
            for (i, job_id, _, _), (artifacts, config) in zip(group, outs):
                results[i] = _result(job_id, artifacts, config)
        except Exception as exc:
            log.warning("coalesced run %s failed (%s); falling back to "
                        "per-job execution", ids, exc)
            for i, job_id, content_type, kwargs in group:
                singles.append((i, job_id, content_type,
                                diffusion_callback, kwargs))
        finally:
            for group_span in group_spans:
                group_span.end()

    # collect lane tickets after the burst groups dispatched: a failed
    # lane row falls back to the per-job path below (zero-loss)
    for i, job_id, content_type, kwargs, ticket in tickets:
        with obs_trace.activate(_job_trace(i)):
            result = _stepper_collect(job_id, content_type, slot, ticket,
                                      registry, kwargs)
        if result is not None:
            results[i] = result
        else:
            singles.append((i, job_id, content_type, diffusion_callback,
                            kwargs))

    for i, job_id, content_type, callback, kwargs in singles:
        with obs_trace.activate(_job_trace(i)), \
                checkpoint_scope(getattr(slot, "_checkpoint_spool", None),
                                 job_id):
            results[i] = _execute(job_id, content_type, callback, kwargs,
                                  slot)
    return [r for r in results if r is not None]
