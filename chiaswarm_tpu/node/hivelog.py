"""swarmdurable: the hive's write-ahead log — crash-safe queue state.

Every fault arc so far (PR-2 worker ladder, PR-6 fleet leases, PR-10
gray failures) hardened the WORKER side; the hive — the job queue of
record (swarm/worker.py:58-110 long-poll contract) — was still one
in-memory process whose crash lost jobs, leases, checkpoints, and
flight records. This module is the standard WAL + deterministic-replay
recipe the checkpoint/recovery literature applies to training
orchestrators (Orbax-style save/restore, Pathways-style resilient
dispatch), sized for the mini-hive:

- **Journal**: :class:`HiveJournal` is an append-only JSONL log under a
  directory (operators: ``<root>/hive/``). Every
  :class:`~chiaswarm_tpu.node.minihive.MiniHive` state transition —
  submit, grant(attempt, worker), heartbeat checkpoint custody,
  shed/redispatch/lease-expiry/salvage/abandon, exactly-once settle —
  appends one record ``{"seq": n, "ev": ..., ...}``; the hive commits
  the batch (write + flush + one fsync) BEFORE acking the request, so
  an acked transition is durable by construction.
- **Segments + compaction**: the log rotates into bounded segments
  (``wal-<first seq>.jsonl``); :meth:`write_snapshot` captures the
  hive's full state at a sequence point and prunes the segments it
  covers, bounding recovery time. Replay(snapshot + tail) must equal
  replay(full log) — the compaction-equivalence gate in
  tests/test_durability.py.
- **Repairing replay**: :meth:`replay` is how a killed hive comes back
  (``MiniHive.recover``). A SIGKILL can tear the final record mid-write;
  replay stops at the last COMPLETE entry and parks the torn tail as a
  ``.bad`` file, counted — never parsed, never silently dropped (the
  PR-6 CheckpointSpool convention). A corrupt or out-of-sequence record
  mid-log parks everything from the corruption onward the same way:
  recovery is the longest consistent prefix, deterministically.
- **Epochs**: each journal attachment bumps a monotone ``hive_epoch``
  (persisted in a tiny ``EPOCH.json`` sidecar so it survives even a
  compacted log). The hive stamps the epoch into every granted payload
  (:data:`HIVE_EPOCH_KEY`) and workers echo it on uploads, so a
  recovered hive can tell a pre-crash grant's late upload (settled once,
  counted as epoch salvage) from a live one, and a stale worker's
  heartbeat is rejected by the epoch handshake.

Knobs (env, all optional): ``CHIASWARM_HIVE_JOURNAL_SEGMENT_BYTES``
(rotation threshold, default 4 MiB), ``CHIASWARM_HIVE_JOURNAL_FSYNC``
(``0`` trades durability for speed in harness runs),
``CHIASWARM_HIVE_JOURNAL_COMPACT_EVERY`` (auto-snapshot cadence in
records, default 4096, ``0`` = manual only).

Stdlib-only and synchronous, like the rest of the hive plane — the
journal, recovery, and the durability tests all run without jax.
"""

from __future__ import annotations

import json
import logging
import os
from pathlib import Path
from typing import Any

log = logging.getLogger("chiaswarm.hivelog")

#: wire field a journaled hive stamps into every granted payload and a
#: worker echoes on its uploads (node/worker.py pops it at poll receipt,
#: exactly like the swarmsight trace context). NEVER stamped without a
#: journal, so the reference-hive wire shape stays byte-compatible.
HIVE_EPOCH_KEY = "hive_epoch"

#: wire field a FEDERATED hive shard stamps into granted payloads
#: (swarmfed, ISSUE 17 — node/federation.py): the owning shard's index,
#: echoed on uploads so a multiplexed worker routes each result to the
#: shard that holds the lease. Stamped ONLY when the federation has
#: H > 1 shards — a single shard (or a plain MiniHive) keeps the
#: reference wire shape byte-identical, like the epoch stamp above.
#: Defined here (not in federation.py) so the worker's import graph
#: never touches the hive-side federation module.
HIVE_SHARD_KEY = "hive_shard"

ENV_SEGMENT_BYTES = "CHIASWARM_HIVE_JOURNAL_SEGMENT_BYTES"
ENV_FSYNC = "CHIASWARM_HIVE_JOURNAL_FSYNC"
ENV_COMPACT_EVERY = "CHIASWARM_HIVE_JOURNAL_COMPACT_EVERY"

_SEGMENT_PREFIX = "wal-"
_SEGMENT_SUFFIX = ".jsonl"
_SNAPSHOT_PREFIX = "snapshot-"
_SNAPSHOT_SUFFIX = ".json"
_EPOCH_FILE = "EPOCH.json"


def _env_int(name: str, default: int) -> int:
    try:
        raw = os.environ.get(name)
        return int(raw) if raw not in (None, "") else default
    except ValueError:
        return default


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "off", "false", "no")


def _segment_name(first_seq: int) -> str:
    return f"{_SEGMENT_PREFIX}{int(first_seq):012d}{_SEGMENT_SUFFIX}"


def _snapshot_name(seq: int) -> str:
    return f"{_SNAPSHOT_PREFIX}{int(seq):012d}{_SNAPSHOT_SUFFIX}"


def _name_seq(path: Path, prefix: str, suffix: str) -> int | None:
    name = path.name
    if not (name.startswith(prefix) and name.endswith(suffix)):
        return None
    try:
        return int(name[len(prefix):-len(suffix)])
    except ValueError:
        return None


class HiveJournal:
    """Append-only JSONL write-ahead log with batch commits, segment
    rotation, compaction snapshots, and a repairing replay. One journal
    owns one directory; concurrent writers are not supported (the hive
    is one process — that being the failure mode this exists for).

    ``append`` buffers; :meth:`commit` writes the batch, flushes, and
    fsyncs once — the hive calls it at the end of each request handler,
    so durability costs one fsync per *batch* of transitions, not one
    per record.
    """

    def __init__(self, directory: Path | str, *,
                 segment_bytes: int | None = None,
                 fsync: bool | None = None,
                 compact_every: int | None = None) -> None:
        self.directory = Path(directory)
        self.segment_bytes = max(4096, int(
            segment_bytes if segment_bytes is not None
            else _env_int(ENV_SEGMENT_BYTES, 4 * 1024 * 1024)))
        self.fsync = (fsync if fsync is not None
                      else _env_flag(ENV_FSYNC, True))
        self.compact_every = max(0, int(
            compact_every if compact_every is not None
            else _env_int(ENV_COMPACT_EVERY, 4096)))
        self._buffer: list[str] = []
        self._fh = None
        self._segment_path: Path | None = None
        self._segment_size = 0
        # counters (mirrored into the hive's metrics registry)
        self.records_written = 0
        self.records_since_snapshot = 0
        self.fsyncs = 0
        self.bytes_written = 0
        self.tails_parked = 0
        self.snapshots_written = 0
        self.segments_pruned = 0
        self._next_seq = self._scan_next_seq()

    # ---- layout ---------------------------------------------------------

    def _segments(self) -> list[Path]:
        if not self.directory.is_dir():
            return []
        out = [p for p in self.directory.iterdir()
               if _name_seq(p, _SEGMENT_PREFIX, _SEGMENT_SUFFIX) is not None]
        return sorted(out)

    def _snapshots(self) -> list[Path]:
        if not self.directory.is_dir():
            return []
        out = [p for p in self.directory.iterdir()
               if _name_seq(p, _SNAPSHOT_PREFIX, _SNAPSHOT_SUFFIX)
               is not None]
        return sorted(out)

    def _scan_next_seq(self) -> int:
        """Cheap startup scan: the next seq continues after the last
        parseable record of the newest segment (a torn tail there is
        repaired by :meth:`replay` before anything appends)."""
        last = 0
        for snap in self._snapshots():
            last = max(last, _name_seq(snap, _SNAPSHOT_PREFIX,
                                       _SNAPSHOT_SUFFIX) or 0)
        segments = self._segments()
        if segments:
            tail = segments[-1]
            first = _name_seq(tail, _SEGMENT_PREFIX, _SEGMENT_SUFFIX) or 1
            last = max(last, first - 1)
            try:
                for line in tail.read_text(encoding="utf-8").splitlines():
                    try:
                        record = json.loads(line)
                        last = max(last, int(record.get("seq") or 0))
                    except (json.JSONDecodeError, TypeError, ValueError):
                        break  # torn/corrupt tail: replay() repairs it
            except OSError:
                pass
        return last + 1

    @property
    def last_seq(self) -> int:
        return self._next_seq - 1

    # ---- epoch sidecar --------------------------------------------------

    def stored_epoch(self) -> int:
        """Highest epoch ever attached to this journal (0 = fresh). The
        sidecar survives compaction, so epochs stay monotone even when
        the epoch records themselves were pruned into a snapshot."""
        path = self.directory / _EPOCH_FILE
        try:
            payload = json.loads(path.read_text(encoding="utf-8"))
            return max(0, int(payload.get("epoch") or 0))
        except (OSError, json.JSONDecodeError, TypeError, ValueError):
            return 0

    def _store_epoch(self, epoch: int) -> None:
        self.directory.mkdir(parents=True, exist_ok=True)
        path = self.directory / _EPOCH_FILE
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps({"version": 1, "epoch": int(epoch)}),
                       encoding="utf-8")
        tmp.replace(path)

    def begin_epoch(self, epoch: int, *, t: float) -> None:
        """Record one epoch attachment: sidecar first (monotone even if
        the crash lands between the two writes), then the journal record
        the replay stream carries."""
        self._store_epoch(epoch)
        self.append("epoch", epoch=int(epoch), t=t)
        self.commit()

    # ---- appending ------------------------------------------------------

    def append(self, ev: str, **fields: Any) -> int:
        """Buffer one record; returns its assigned seq. Nothing touches
        disk until :meth:`commit` — callers batch per request."""
        seq = self._next_seq
        self._next_seq += 1
        record = {"seq": seq, "ev": str(ev)}
        record.update(fields)
        self._buffer.append(json.dumps(record, sort_keys=True))
        return seq

    def _open_segment(self, first_seq: int) -> None:
        self.directory.mkdir(parents=True, exist_ok=True)
        self._segment_path = self.directory / _segment_name(first_seq)
        self._fh = open(self._segment_path, "ab")
        self._segment_size = self._fh.tell()

    def rotate(self) -> None:
        """Close the open segment; the next commit starts a fresh one
        (recovery always rotates so appends never extend a repaired
        file)."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._segment_path = None
        self._segment_size = 0

    def commit(self) -> int:
        """Write buffered records, flush, fsync once. Returns the
        number of records made durable. The caller acks its request
        only after this returns — write-ahead, then answer.

        A failed write/fsync keeps the batch buffered (seqs are already
        assigned; dropping it would leave a permanent sequence gap that
        replay treats as corruption) and rolls the segment back to its
        known-good prefix, so a retrying commit can never leave a torn
        record followed by a duplicate."""
        if not self._buffer:
            return 0
        if self._fh is None or self._segment_size >= self.segment_bytes:
            self.rotate()
            self._open_segment(self._next_seq - len(self._buffer))
        payload = ("\n".join(self._buffer) + "\n").encode("utf-8")
        n = len(self._buffer)
        try:
            self._fh.write(payload)
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
                self.fsyncs += 1
        except OSError:
            try:
                self._fh.truncate(self._segment_size)
            except OSError:
                # cannot even roll back: abandon this segment so the
                # retry opens a fresh one (the torn tail is parked at
                # the next recovery)
                self.rotate()
            raise
        self._buffer.clear()
        self._segment_size += len(payload)
        self.bytes_written += len(payload)
        self.records_written += n
        self.records_since_snapshot += n
        return n

    def close(self) -> None:
        self.commit()
        self.rotate()

    # ---- compaction -----------------------------------------------------

    def write_snapshot(self, state: dict[str, Any], *, epoch: int,
                       t: float, prune: bool = True) -> Path:
        """Capture the hive's full state at the current sequence point
        and prune every segment the snapshot covers. ``state`` must be
        exactly what :meth:`replay` hands back for the hive to restore —
        replay(snapshot + tail) ≡ replay(full log) is gated by test
        (``prune=False`` keeps the covered segments so the gate can run
        both paths over one journal)."""
        self.commit()  # the snapshot covers everything appended so far
        seq = self.last_seq
        self.directory.mkdir(parents=True, exist_ok=True)
        path = self.directory / _snapshot_name(seq)
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps({"version": 1, "seq": seq,
                                   "epoch": int(epoch), "t": float(t),
                                   "state": state}, sort_keys=True),
                       encoding="utf-8")
        if self.fsync:
            with open(tmp, "rb") as fh:
                os.fsync(fh.fileno())
        tmp.replace(path)
        self.snapshots_written += 1
        self.records_since_snapshot = 0
        # prune: a segment is covered when every record in it has
        # seq <= snapshot seq — i.e. the NEXT segment starts at or
        # before seq + 1. Rotate first so the open segment is closed.
        self.rotate()
        if not prune:
            log.info("hive journal snapshot at seq %d (%s; segments "
                     "kept)", seq, path.name)
            return path
        segments = self._segments()
        for i, segment in enumerate(segments):
            nxt = (_name_seq(segments[i + 1], _SEGMENT_PREFIX,
                             _SEGMENT_SUFFIX)
                   if i + 1 < len(segments) else self._next_seq)
            if nxt is not None and nxt <= seq + 1:
                try:
                    segment.unlink()
                    self.segments_pruned += 1
                except OSError as exc:
                    log.warning("could not prune covered segment %s: %s",
                                segment, exc)
        # older snapshots are superseded
        for snap in self._snapshots():
            if snap.name != _snapshot_name(seq):
                try:
                    snap.unlink()
                except OSError:
                    pass
        log.info("hive journal snapshot at seq %d (%s)", seq, path.name)
        return path

    def maybe_compact(self) -> bool:
        """Auto-compaction trigger: True when the caller should snapshot
        now (``compact_every`` records appended since the last one)."""
        return (self.compact_every > 0
                and self.records_since_snapshot >= self.compact_every)

    # ---- replay ---------------------------------------------------------

    def _park(self, path: Path, good_bytes: int, reason: str) -> None:
        """Park everything past ``good_bytes`` of ``path`` as a sibling
        ``.bad`` file and truncate the segment to its good prefix —
        loud, counted, never reparsed (the CheckpointSpool convention)."""
        try:
            data = path.read_bytes()
        except OSError as exc:
            log.error("cannot read %s for repair (%s)", path, exc)
            return
        bad = data[good_bytes:]
        if not bad:
            return
        bad_path = path.with_suffix(path.suffix
                                    + f".{self.tails_parked}.bad")
        try:
            bad_path.write_bytes(bad)
            if good_bytes:
                with open(path, "r+b") as fh:
                    fh.truncate(good_bytes)
            else:
                path.unlink()
        except OSError as exc:
            log.error("cannot park bad tail of %s (%s)", path, exc)
            return
        self.tails_parked += 1
        log.error("hive journal: parked %d byte(s) of %s as %s (%s)",
                  len(bad), path.name, bad_path.name, reason)

    def _load_snapshot(self) -> dict[str, Any] | None:
        for snap in reversed(self._snapshots()):
            try:
                payload = json.loads(snap.read_text(encoding="utf-8"))
                if isinstance(payload, dict) and \
                        isinstance(payload.get("state"), dict):
                    return payload
            except (OSError, json.JSONDecodeError) as exc:
                log.error("unreadable snapshot %s (%s); parking as .bad",
                          snap, exc)
                try:
                    snap.replace(snap.with_suffix(snap.suffix + ".bad"))
                except OSError:
                    pass
                self.tails_parked += 1
        return None

    def replay(self, *, repair: bool = True
               ) -> tuple[dict[str, Any] | None, list[dict[str, Any]]]:
        """Read the journal back: ``(snapshot, tail_records)``.

        ``snapshot`` is the newest readable snapshot payload (or None);
        ``tail_records`` are every complete record after it, in seq
        order, stopping at the first torn / unparseable / out-of-
        sequence record. With ``repair`` (the recovery path) the bad
        remainder — the rest of that segment AND every later segment —
        is parked ``.bad`` so future appends and replays see only the
        consistent prefix; ``repair=False`` is the read-only inspection
        view."""
        snapshot = self._load_snapshot()
        after_seq = int(snapshot["seq"]) if snapshot else 0
        records: list[dict[str, Any]] = []
        # the snapshot pins the ladder at its seq; a fresh log pins it
        # at the first record seen (normally 1)
        expected = after_seq + 1 if snapshot else None
        broken = False
        for segment in self._segments():
            if broken:
                if repair:
                    self._park(segment, 0, "after a corrupt record")
                continue
            try:
                data = segment.read_bytes()
            except OSError as exc:
                log.error("unreadable segment %s (%s)", segment, exc)
                broken = True
                continue
            offset = 0
            for raw in data.split(b"\n"):
                if not raw:
                    offset += 1  # the newline the empty split consumed
                    continue
                # a complete record is terminated by its newline; the
                # final chunk of a torn write has none
                torn = data[offset + len(raw):
                            offset + len(raw) + 1] != b"\n"
                try:
                    record = json.loads(raw.decode("utf-8"))
                    seq = int(record["seq"])
                except (json.JSONDecodeError, UnicodeDecodeError,
                        KeyError, TypeError, ValueError):
                    record, seq = None, None
                ok = record is not None and not torn
                if ok and seq <= after_seq:
                    offset += len(raw) + 1  # pre-snapshot: covered
                    continue
                if ok and expected is None:
                    expected = seq
                if not ok or seq != expected:
                    reason = ("torn final record" if torn
                              else "corrupt record" if record is None
                              else f"sequence gap (want {expected}, "
                                   f"got {seq})")
                    if repair:
                        self._park(segment, offset, reason)
                    broken = True
                    break
                offset += len(raw) + 1
                records.append(record)
                expected += 1
        if repair:
            # crash semantics: appends never committed died with the
            # process; and after parking, the journal continues at
            # exactly last-good + 1 (a parked gap must not leave a
            # permanent hole every future replay would stop at)
            self._buffer.clear()
            if records:
                self._next_seq = int(records[-1]["seq"]) + 1
            else:
                self._next_seq = after_seq + 1
            self.rotate()  # recovery never extends a repaired segment
        elif records:
            self._next_seq = max(self._next_seq,
                                 int(records[-1]["seq"]) + 1)
        return (snapshot, records)

    # ---- observability --------------------------------------------------

    def snapshot_counters(self) -> dict[str, int]:
        return {
            "records_written": self.records_written,
            "bytes_written": self.bytes_written,
            "fsyncs": self.fsyncs,
            "tails_parked": self.tails_parked,
            "snapshots_written": self.snapshots_written,
            "segments_pruned": self.segments_pruned,
            "segments": len(self._segments()),
            "last_seq": self.last_seq,
        }


__all__ = ["HIVE_EPOCH_KEY", "HiveJournal"]
